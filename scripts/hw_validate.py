"""Hardware validation battery: run the moment the TPU tunnel answers.

Captures, in order of value-per-second (the tunnel may die again):
1. transfer bandwidth + dispatch latency;
2. fused group-by kernel matmul-vs-scatter across G (the one-hot
   materialization question, ops/kernels.py);
3. Pallas group-by vs XLA at its small-G envelope (VERDICT r2 #8);
4. warm/cold engine smoke on the persistent .benchwork dataset (config 4
   shape) — encoded-cache cold vs live cold vs hot-set warm.

Writes JSON lines to scripts/hw_results.jsonl (append; timestamped by the
caller's wall clock).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

OUT = Path(__file__).with_name("hw_results.jsonl")


def emit(kind: str, **kw) -> None:
    rec = {"kind": kind, "at": time.time(), **kw}
    with OUT.open("a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def probe(timeout_secs: float = 60.0) -> bool:
    import threading

    ok: list = []

    def go():
        try:
            import jax
            import jax.numpy as jnp

            jnp.ones(8).sum().block_until_ready()
            ok.append(jax.devices())
        except Exception as e:  # noqa: BLE001
            ok.append(e)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout_secs)
    return bool(ok) and not isinstance(ok[0], Exception)


def bench_transfer() -> None:
    import jax
    import numpy as np

    a = np.random.rand(32 * 1024 * 256).astype(np.float32)  # 32 MB
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(a).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    import jax.numpy as jnp

    f = jax.jit(lambda x: x.sum())
    x = jnp.ones(1024)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(20):
        f(x).block_until_ready()
    emit(
        "transfer",
        mb_per_s=round(32 / best, 1),
        dispatch_ms=round((time.perf_counter() - t0) / 20 * 1000, 3),
    )


def bench_kernel_matrix() -> None:
    """matmul vs scatter across G at N=1M, via the real fused kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parseable_tpu.ops import kernels as K

    n = 1 << 20
    rng = np.random.default_rng(0)
    mask = jnp.asarray(np.ones(n, bool))
    sumv = jnp.asarray(rng.random((1, n), np.float32))
    z = jnp.zeros((0, n), jnp.float32)
    valid = jnp.asarray(np.ones((2, n), bool))
    for g in (256, 1024, 4096, 8192, 16384, 65536, 1 << 20):
        ids = jnp.asarray(rng.integers(0, g, n).astype(np.int32))
        for route, max_elems in (("matmul", 1 << 62), ("scatter", 0)):
            if route == "matmul" and g > 8192:
                continue
            orig_g, orig_e = K.MATMUL_MAX_GROUPS, K.MATMUL_MAX_ONEHOT_ELEMS
            K.MATMUL_MAX_GROUPS = 8192 if route == "matmul" else 0
            K.MATMUL_MAX_ONEHOT_ELEMS = max_elems if route == "matmul" else 0
            try:
                K.fused_groupby_block.clear_cache()
                args = (ids, mask, sumv, z, z, valid, g, 1, 0, 0)
                try:
                    out = K.fused_groupby_block(*args)
                    jax.block_until_ready(out)
                    t0 = time.perf_counter()
                    for _ in range(5):
                        out = K.fused_groupby_block(*args)
                    jax.block_until_ready(out)
                    dt = (time.perf_counter() - t0) / 5
                    emit(
                        "kernel", g=g, route=route,
                        ms_per_1m_block=round(dt * 1000, 3),
                        m_rows_per_s=round(n / dt / 1e6, 1),
                    )
                except Exception as e:  # noqa: BLE001
                    emit("kernel", g=g, route=route, error=str(e)[:200])
            finally:
                K.MATMUL_MAX_GROUPS, K.MATMUL_MAX_ONEHOT_ELEMS = orig_g, orig_e
                K.fused_groupby_block.clear_cache()


def bench_pallas() -> None:
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from parseable_tpu.ops import kernels as K

    try:
        from parseable_tpu.ops.pallas_groupby import PALLAS_AVAILABLE
    except ImportError:
        PALLAS_AVAILABLE = False
    if not PALLAS_AVAILABLE:
        emit("pallas", error="pallas unavailable")
        return
    n = 1 << 20
    rng = np.random.default_rng(0)
    mask = jnp.asarray(np.ones(n, bool))
    sumv = jnp.asarray(rng.random((1, n), np.float32))
    z = jnp.zeros((0, n), jnp.float32)
    valid = jnp.asarray(np.ones((2, n), bool))
    for g in (64, 256, 512):
        ids = jnp.asarray(rng.integers(0, g, n).astype(np.int32))
        for use in ("0", "1"):
            os.environ["P_TPU_USE_PALLAS"] = use
            K.fused_groupby_block.clear_cache()
            args = (ids, mask, sumv, z, z, valid, g, 1, 0, 0)
            try:
                out = K.fused_groupby_block(*args)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(5):
                    out = K.fused_groupby_block(*args)
                jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / 5
                emit(
                    "pallas", g=g, pallas=use == "1",
                    ms_per_1m_block=round(dt * 1000, 3),
                )
            except Exception as e:  # noqa: BLE001
                emit("pallas", g=g, pallas=use == "1", error=str(e)[:200])
    os.environ.pop("P_TPU_USE_PALLAS", None)
    K.fused_groupby_block.clear_cache()


def bench_engine_suite() -> None:
    """The full cold+warm battery on the persistent .benchwork dataset
    (VERDICT r4 #1): configs 2-4 at 32M rows, highcard configs 3-4 at 32M,
    then config 4 at FULL scale (700M rows ~= 100GB logical) through the
    tiering. Each config emits as it completes — a dying tunnel still
    records whatever finished; cheapest-first ordering maximizes captured
    value per second of tunnel life. The measurement protocol itself is
    bench_scale.run_battery, shared so the two harnesses cannot drift."""
    workdir = Path("/root/repo/.benchwork")
    meta_path = workdir / "meta.json"
    if not meta_path.exists():
        emit("engine", error="no .benchwork dataset (scripts/build_benchwork.py)")
        return
    meta = json.loads(meta_path.read_text())
    from bench_scale import run_battery
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.query.session import QuerySession

    opts = Options()
    opts.local_staging_path = workdir / "staging"
    p = Parseable(opts, StorageOptions(backend="local-store", root=workdir / "data"))
    sess_cpu = QuerySession(p, engine="cpu")
    sess = QuerySession(p, engine="tpu")

    # 32M-row window over the 700M-row stream (minutes are 1M rows each)
    bound = "p_timestamp < '2024-05-01T00:32:00'"
    cases = [
        (
            "groupby_32m",
            "SELECT date_bin(interval '1 minute', p_timestamp) AS t, status, "
            "count(*) AS c, sum(bytes) AS b, avg(latency_ms) AS l FROM bench "
            f"WHERE {bound} GROUP BY t, status",
            32_000_000,
        ),
        (
            "regex_filter_32m",
            "SELECT status, count(*) AS c, avg(latency_ms) AS l FROM bench "
            f"WHERE message LIKE '%error%' AND {bound} GROUP BY status",
            32_000_000,
        ),
        (
            "topk_multicol_32m",
            "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM bench "
            f"WHERE {bound} GROUP BY path, host ORDER BY s DESC LIMIT 10",
            32_000_000,
        ),
        (
            "regex_filter_highcard_32m",
            "SELECT status, count(*) AS c, avg(latency_ms) AS l FROM bench_hc "
            "WHERE message LIKE '%error%' GROUP BY status",
            meta.get("hc_rows", 32_000_000),
        ),
        (
            "topk_multicol_highcard_32m",
            "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM bench_hc "
            "GROUP BY path, host ORDER BY s DESC LIMIT 10",
            meta.get("hc_rows", 32_000_000),
        ),
        (
            "topk_multicol_full_100gb",
            "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM bench "
            "GROUP BY path, host ORDER BY s DESC LIMIT 10",
            meta["rows"],
        ),
    ]
    for name, sql, rows_total in cases:
        try:
            summary = run_battery(
                p, sess_cpu, sess, sql, rows_total,
                lambda kind, **kw: emit(f"engine_{kind}", **kw), name,
            )
            emit("engine", config=name, **summary)
        except Exception as e:  # noqa: BLE001
            emit("engine", config=name, error=str(e)[:300])


def main() -> None:
    if not probe():
        emit("probe", ok=False)
        sys.exit(2)
    emit("probe", ok=True)
    bench_transfer()
    bench_kernel_matrix()
    bench_pallas()
    bench_engine_suite()
    emit("done")


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    main()
