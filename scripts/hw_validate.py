"""Hardware validation battery: run the moment the TPU tunnel answers.

Captures, in order of value-per-second (the tunnel may die again):
1. transfer bandwidth + dispatch latency;
2. fused group-by kernel matmul-vs-scatter across G (the one-hot
   materialization question, ops/kernels.py);
3. Pallas group-by vs XLA at its small-G envelope (VERDICT r2 #8);
4. warm/cold engine smoke on the persistent .benchwork dataset (config 4
   shape) — encoded-cache cold vs live cold vs hot-set warm.

Writes JSON lines to scripts/hw_results.jsonl (append; timestamped by the
caller's wall clock).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

OUT = Path(__file__).with_name("hw_results.jsonl")


def emit(kind: str, **kw) -> None:
    rec = {"kind": kind, "at": time.time(), **kw}
    with OUT.open("a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def probe(timeout_secs: float = 60.0) -> bool:
    import threading

    ok: list = []

    def go():
        try:
            import jax
            import jax.numpy as jnp

            jnp.ones(8).sum().block_until_ready()
            ok.append(jax.devices())
        except Exception as e:  # noqa: BLE001
            ok.append(e)

    t = threading.Thread(target=go, daemon=True)
    t.start()
    t.join(timeout_secs)
    return bool(ok) and not isinstance(ok[0], Exception)


def bench_transfer() -> None:
    import jax
    import numpy as np

    a = np.random.rand(32 * 1024 * 256).astype(np.float32)  # 32 MB
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_put(a).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    import jax.numpy as jnp

    f = jax.jit(lambda x: x.sum())
    x = jnp.ones(1024)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(20):
        f(x).block_until_ready()
    emit(
        "transfer",
        mb_per_s=round(32 / best, 1),
        dispatch_ms=round((time.perf_counter() - t0) / 20 * 1000, 3),
    )


def bench_kernel_matrix() -> None:
    """matmul vs scatter across G at N=1M, via the real fused kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parseable_tpu.ops import kernels as K

    n = 1 << 20
    rng = np.random.default_rng(0)
    mask = jnp.asarray(np.ones(n, bool))
    sumv = jnp.asarray(rng.random((1, n), np.float32))
    z = jnp.zeros((0, n), jnp.float32)
    valid = jnp.asarray(np.ones((2, n), bool))
    for g in (256, 1024, 4096, 8192, 16384, 65536, 1 << 20):
        ids = jnp.asarray(rng.integers(0, g, n).astype(np.int32))
        for route, max_elems in (("matmul", 1 << 62), ("scatter", 0)):
            if route == "matmul" and g > 8192:
                continue
            orig_g, orig_e = K.MATMUL_MAX_GROUPS, K.MATMUL_MAX_ONEHOT_ELEMS
            K.MATMUL_MAX_GROUPS = 8192 if route == "matmul" else 0
            K.MATMUL_MAX_ONEHOT_ELEMS = max_elems if route == "matmul" else 0
            try:
                K.fused_groupby_block.clear_cache()
                args = (ids, mask, sumv, z, z, valid, g, 1, 0, 0)
                try:
                    out = K.fused_groupby_block(*args)
                    jax.block_until_ready(out)
                    t0 = time.perf_counter()
                    for _ in range(5):
                        out = K.fused_groupby_block(*args)
                    jax.block_until_ready(out)
                    dt = (time.perf_counter() - t0) / 5
                    emit(
                        "kernel", g=g, route=route,
                        ms_per_1m_block=round(dt * 1000, 3),
                        m_rows_per_s=round(n / dt / 1e6, 1),
                    )
                except Exception as e:  # noqa: BLE001
                    emit("kernel", g=g, route=route, error=str(e)[:200])
            finally:
                K.MATMUL_MAX_GROUPS, K.MATMUL_MAX_ONEHOT_ELEMS = orig_g, orig_e
                K.fused_groupby_block.clear_cache()


def bench_pallas() -> None:
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from parseable_tpu.ops import kernels as K

    try:
        from parseable_tpu.ops.pallas_groupby import PALLAS_AVAILABLE
    except ImportError:
        PALLAS_AVAILABLE = False
    if not PALLAS_AVAILABLE:
        emit("pallas", error="pallas unavailable")
        return
    n = 1 << 20
    rng = np.random.default_rng(0)
    mask = jnp.asarray(np.ones(n, bool))
    sumv = jnp.asarray(rng.random((1, n), np.float32))
    z = jnp.zeros((0, n), jnp.float32)
    valid = jnp.asarray(np.ones((2, n), bool))
    for g in (64, 256, 512):
        ids = jnp.asarray(rng.integers(0, g, n).astype(np.int32))
        for use in ("0", "1"):
            os.environ["P_TPU_USE_PALLAS"] = use
            K.fused_groupby_block.clear_cache()
            args = (ids, mask, sumv, z, z, valid, g, 1, 0, 0)
            try:
                out = K.fused_groupby_block(*args)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(5):
                    out = K.fused_groupby_block(*args)
                jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / 5
                emit(
                    "pallas", g=g, pallas=use == "1",
                    ms_per_1m_block=round(dt * 1000, 3),
                )
            except Exception as e:  # noqa: BLE001
                emit("pallas", g=g, pallas=use == "1", error=str(e)[:200])
    os.environ.pop("P_TPU_USE_PALLAS", None)
    K.fused_groupby_block.clear_cache()


def bench_engine_smoke() -> None:
    """Config-4 shape on the persistent dataset: live cold, cache cold,
    hot warm."""
    workdir = Path("/root/repo/.benchwork")
    if not workdir.exists():
        emit("engine", error="no .benchwork dataset")
        return
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.ops import enccache as EC
    from parseable_tpu.ops.hotset import get_hotset
    from parseable_tpu.query.session import QuerySession

    opts = Options()
    opts.local_staging_path = workdir / "staging"
    p = Parseable(opts, StorageOptions(backend="local-store", root=workdir / "data"))
    sess_cpu = QuerySession(p, engine="cpu")
    sess = QuerySession(p, engine="tpu")
    rows_total = 8_000_000
    for name, sql in (
        (
            "topk_multicol",
            "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM bench "
            "GROUP BY path, host ORDER BY s DESC LIMIT 10",
        ),
        (
            "groupby",
            "SELECT date_bin(interval '1 minute', p_timestamp) AS t, status, "
            "count(*) AS c, sum(bytes) AS b, avg(latency_ms) AS l FROM bench "
            "GROUP BY t, status",
        ),
        (
            "regex_filter",
            "SELECT status, count(*) AS c, avg(latency_ms) AS l FROM bench "
            "WHERE message LIKE '%error%' GROUP BY status",
        ),
    ):
        t0 = time.perf_counter()
        sess_cpu.query(sql)
        cpu_t = time.perf_counter() - t0
        sess.query(sql)  # compile + seed caches
        get_hotset().clear()
        t0 = time.perf_counter()
        sess.query(sql)
        cache_cold_t = time.perf_counter() - t0
        t0 = time.perf_counter()
        sess.query(sql)
        warm_t = time.perf_counter() - t0
        emit(
            "engine",
            config=name,
            cpu_s=round(cpu_t, 3),
            cache_cold_s=round(cache_cold_t, 3),
            warm_s=round(warm_t, 3),
            cold_x=round(cpu_t / cache_cold_t, 2),
            warm_x=round(cpu_t / warm_t, 2),
            rows_per_s_warm=round(rows_total / warm_t),
        )


def main() -> None:
    if not probe():
        emit("probe", ok=False)
        sys.exit(2)
    emit("probe", ok=True)
    bench_transfer()
    bench_kernel_matrix()
    bench_pallas()
    bench_engine_smoke()
    emit("done")


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    main()
