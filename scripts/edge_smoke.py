"""Native ingest edge smoke check: boot a real server with P_EDGE_PORT,
prove the C++ acceptor end to end, exit nonzero on any broken link.

Asserts, against one real server process (scripts/blackbox.py):

- a keep-alive connection to the edge port acks two POST /api/v1/ingest
  batches (zero-Python happy path) with `X-P-Trace-Id` echoed;
- a forced decline on the edge port (GET of a non-hot route) relays to
  the aiohttp tier and answers byte-identical to the same request sent
  to the aiohttp port directly (modulo the per-request Date and
  X-P-Trace-Id headers);
- the acked rows are queryable through the normal SQL path;
- the conservation-law audit reports zero violations at quiesce and the
  edge section shows every claimed request responded (live == 0).

Runnable standalone (`python scripts/edge_smoke.py`); check_green.sh runs
it as the edge gate (opt out with EDGE=0).
"""

from __future__ import annotations

import socket
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from blackbox import AUTH_HEADER, ClusterHarness, free_port  # noqa: E402


def _recv_response(sock: socket.socket, buf: bytes) -> tuple[bytes, bytes]:
    """Read one Content-Length-framed response; returns (response, leftover)."""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise AssertionError("connection closed mid-response")
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    need = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            need = int(v.strip())
    while len(rest) < need:
        chunk = sock.recv(65536)
        if not chunk:
            raise AssertionError("connection closed mid-body")
        rest += chunk
    return head + b"\r\n\r\n" + rest[:need], rest[need:]


def _roundtrip(port: int, raw: bytes) -> bytes:
    with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
        s.sendall(raw)
        resp, _ = _recv_response(s, b"")
        return resp


def _strip_volatile(resp: bytes) -> bytes:
    """Drop the per-request headers (Date, X-P-Trace-Id) before comparing."""
    head, _, body = resp.partition(b"\r\n\r\n")
    lines = [
        ln
        for ln in head.split(b"\r\n")
        if not ln.lower().startswith((b"date:", b"x-p-trace-id:"))
    ]
    return b"\r\n".join(lines) + b"\r\n\r\n" + body


def run_smoke(workdir: Path) -> dict:
    auth = AUTH_HEADER["Authorization"]
    edge_port = free_port()
    body = b'[{"host": "edge-smoke", "status": 200}, {"host": "edge-smoke", "status": 500}]'
    post = (
        f"POST /api/v1/ingest HTTP/1.1\r\n"
        f"Host: 127.0.0.1:{edge_port}\r\n"
        f"Authorization: {auth}\r\n"
        f"Content-Type: application/json\r\n"
        f"X-P-Stream: edgesmoke\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode() + body
    decline = (
        f"GET /api/v1/about HTTP/1.1\r\n"
        f"Host: 127.0.0.1:{edge_port}\r\n"
        f"Authorization: {auth}\r\n\r\n"
    ).encode()

    with ClusterHarness(workdir) as cluster:
        node = cluster.spawn(
            "all",
            "edgesmoke",
            env_extra={"P_EDGE_PORT": str(edge_port), "P_LOCAL_SYNC_INTERVAL": "2"},
        )
        cluster.wait_live(node)

        # happy path: two acked batches over ONE keep-alive edge connection
        with socket.create_connection(("127.0.0.1", edge_port), timeout=30) as s:
            buf = b""
            for i in range(2):
                s.sendall(post)
                resp, buf = _recv_response(s, buf)
                assert resp.startswith(b"HTTP/1.1 200"), f"edge ack #{i}: {resp[:200]!r}"
                assert b"ingested 2 records" in resp, resp[:200]
                assert b"x-p-trace-id:" in resp.lower(), "edge ack missing trace id echo"

        # decline path: byte-identical relay vs the aiohttp port directly
        via_edge = _roundtrip(edge_port, decline)
        direct = _roundtrip(node.port, decline)
        assert _strip_volatile(via_edge) == _strip_volatile(direct), (
            f"decline relay diverged:\nedge:   {via_edge[:300]!r}\n"
            f"direct: {direct[:300]!r}"
        )

        # the acked rows land queryable through the normal path
        deadline_rows = None
        for _ in range(60):
            try:
                records, _ = cluster.query(
                    node, "SELECT count(*) c FROM edgesmoke", timeout=15
                )
                deadline_rows = records[0]["c"]
                if deadline_rows == 4:
                    break
            except RuntimeError:
                pass
            import time

            time.sleep(1)
        assert deadline_rows == 4, f"expected 4 acked rows queryable, got {deadline_rows}"

        # conservation audit: zero violations at quiesce, edge drained
        report = cluster.audit(node, scope="local", quiesce=True)
        assert report.get("violations") == [], report["violations"]
        edge = report.get("edge") or {}
        assert edge.get("live") == 0, f"edge live != 0 at quiesce: {edge}"
        assert edge.get("happy", 0) >= 2, edge
        assert edge.get("declined", 0) >= 1, edge
        return {"rows": deadline_rows, "edge": edge}


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="ptpu-edge-smoke-") as wd:
        out = run_smoke(Path(wd))
    print(f"edge smoke OK: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
