"""Settle the Pallas group-by kernel on hardware (VERDICT r2 #8).

Times the VMEM one-hot Pallas kernel (ops/pallas_groupby.py) against the
XLA one-hot matmul path it would replace, on the REAL chip, across block
sizes and group counts within the Pallas VMEM cap. Prints one JSON line
per (N, G, R) with Grows/s for both and the ratio.

Decision rule (applied by hand after a run): enable by default if the
kernel wins >=1.1x across the board, delete it if it loses — an unproven
parallel kernel is maintenance surface, not capability.

Usage: python scripts/bench_pallas.py   (requires the tunnel to answer)
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    devs = jax.devices()
    on_tpu = devs[0].platform != "cpu"
    print(f"# devices: {devs} (tpu={on_tpu})", file=sys.stderr)
    if not on_tpu:
        print("# WARNING: not on TPU — interpret-mode numbers prove nothing", file=sys.stderr)

    from parseable_tpu.ops.pallas_groupby import ROW_TILE, additive_groupby_pallas

    def xla_additive(ids, rows, num_groups):
        iota = jnp.arange(num_groups, dtype=jnp.int32)[None, :]
        onehot = (ids[:, None] == iota).astype(jnp.float32)
        return jax.lax.dot_general(
            rows, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def xla_additive_bf16(ids, rows, num_groups):
        iota = jnp.arange(num_groups, dtype=jnp.int32)[None, :]
        onehot = (ids[:, None] == iota).astype(jnp.bfloat16)
        return jax.lax.dot_general(
            rows.astype(jnp.bfloat16), onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    xla_jit = jax.jit(xla_additive, static_argnames=("num_groups",))
    xla_bf16_jit = jax.jit(xla_additive_bf16, static_argnames=("num_groups",))

    rng = np.random.default_rng(0)
    for n in (1 << 20, 1 << 21):
        for g in (128, 256, 512):
            for r in (4, 8):
                ids = jax.device_put(rng.integers(0, g, n).astype(np.int32))
                rows = jax.device_put(rng.random((r, n)).astype(np.float32))
                jax.block_until_ready((ids, rows))

                def timed(fn, *args) -> float:
                    fn(*args).block_until_ready()  # compile
                    best = float("inf")
                    for _ in range(5):
                        t0 = time.perf_counter()
                        fn(*args).block_until_ready()
                        best = min(best, time.perf_counter() - t0)
                    return best

                t_xla = timed(xla_jit, ids, rows, g)
                t_bf16 = timed(xla_bf16_jit, ids, rows, g)
                try:
                    t_pallas = timed(
                        lambda i, ro, gg=g: additive_groupby_pallas(
                            i, ro, gg, interpret=not on_tpu
                        ),
                        ids,
                        rows,
                    )
                except Exception as e:  # noqa: BLE001
                    print(f"# pallas failed N={n} G={g} R={r}: {e}", file=sys.stderr)
                    t_pallas = float("inf")
                # parity spot check
                a = np.asarray(xla_jit(ids, rows, g))
                b = np.asarray(additive_groupby_pallas(ids, rows, g, interpret=not on_tpu))
                ok = bool(np.allclose(a, b, rtol=1e-5, atol=1e-3))
                print(
                    json.dumps(
                        {
                            "n": n,
                            "g": g,
                            "r": r,
                            "xla_f32_grows_s": round(n / t_xla / 1e9, 2),
                            "xla_bf16_grows_s": round(n / t_bf16 / 1e9, 2),
                            "pallas_grows_s": round(n / t_pallas / 1e9, 2),
                            "pallas_vs_xla": round(t_xla / t_pallas, 2),
                            "parity": ok,
                        }
                    ),
                    flush=True,
                )


if __name__ == "__main__":
    main()
