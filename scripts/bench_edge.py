#!/usr/bin/env python
"""Standalone hook for the native-edge ingest benchmark.

Boots one real server process with the C++ edge acceptor enabled
(P_EDGE_PORT) and drives BOTH its ports wrk-style over loopback —
persistent keep-alive connections, fixed offered load, identical payload
bytes — reporting GB/s, rows/s-per-core and p50/p95/p99 ack latency for
the native edge next to the aiohttp tier. See bench.bench_edge for the
env knobs (BENCH_EDGE_CONNS / _REQS / _BATCH / _OFFERED_ROWS).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_edge  # noqa: E402

if __name__ == "__main__":
    bench_edge()
