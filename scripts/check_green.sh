#!/usr/bin/env bash
# Pre-snapshot gate: run the tier-1 verify command from ROADMAP.md and exit
# nonzero on ANY failure ("go green and stay green"). Run this before every
# snapshot/PR; a red tier-1 must block the commit, not ride along.
#
# Usage: scripts/check_green.sh
set -o pipefail
cd "$(dirname "$0")/.."

# With a toolchain present, a native fastpath that fails to compile must be
# a test failure, not a silent pure-Python-fallback green (the columnar
# ingest tier, xxh64, and HLL would all quietly degrade). Tests read this
# in conftest pytest_sessionstart; native/__init__.py also hard-raises.
if command -v g++ >/dev/null 2>&1; then
  export P_NATIVE_REQUIRED=1
fi

# P_DLINT=1 arms the device-path recompilation tripwire for the tier-1 run
# itself: jax.jit is wrapped session-wide and any cached program compiling
# past its per-shape-class budget turns the run red (report:
# /tmp/dlint_tripwire.json). DLINT=0 disarms it along with the static gate.
t1_dlint="${DLINT:-1}"
if [ "$t1_dlint" != "0" ]; then t1_dlint=1; fi
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu P_DLINT="$t1_dlint" python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  echo "check_green: TIER-1 RED (pytest rc=$rc)" >&2
  exit "$rc"
fi
if grep -aqE '^[0-9]+ (failed|error)|, [0-9]+ (failed|error)' /tmp/_t1.log; then
  echo "check_green: TIER-1 RED (failures in log despite rc=0)" >&2
  exit 1
fi
echo "check_green: tier-1 GREEN"

# static-analysis gate: the tree must lint clean (zero unbaselined plint
# findings) before snapshot — concurrency/invariant bugs are cheapest here.
# Default: --changed (findings reported only for files differing from
# `git merge-base HEAD main`, whole tree still analyzed) + the mtime result
# cache, so the gate stays fast as the rule count grows. PLINT_FULL=1 runs
# the authoritative full-tree report. The JSON report lands at
# /tmp/plint.json either way (gate artifact).
plint_args=(--json-out /tmp/plint.json)
if [ "${PLINT_FULL:-0}" != "1" ]; then
  plint_args+=(--changed)
fi
if ! python -m parseable_tpu.analysis "${plint_args[@]}"; then
  echo "check_green: PLINT RED (unbaselined findings; see above and /tmp/plint.json)" >&2
  exit 1
fi
echo "check_green: plint GREEN (report: /tmp/plint.json)"

# wire-contract gate: wlint (parseable_tpu/analysis/wire/) diffs both sides
# of every wire contract — client path literals vs the aiohttp route table
# (and the C++ edge classifier's route strings), X-P-* header produce/consume
# across Python AND fastpath.cpp, Flight ticket kinds and ptpu.* schema
# metadata, metric families vs ticks vs README, stats.stages.* produce/
# consume, and FFI pointer custody against the nsan ownership tables.
# Always a full-tree run (every rule is cross-file; sub-second). Opt out
# with WLINT=0; the JSON report lands at /tmp/wlint.json either way it runs.
if [ "${WLINT:-1}" != "0" ]; then
  if ! python -m parseable_tpu.analysis.wire --json-out /tmp/wlint.json; then
    echo "check_green: WLINT RED (unbaselined findings; see above and /tmp/wlint.json)" >&2
    exit 1
  fi
  echo "check_green: wlint GREEN (report: /tmp/wlint.json)"
else
  echo "check_green: wlint SKIPPED (WLINT=0)"
fi

# device-path gate: dlint (parseable_tpu/analysis/device/) — jit sites on
# query paths must ride a declared program cache, host syncs reachable from
# `# device-hot` loops must be `# sync-boundary` annotated, device_put/get
# must be priced into link accounting, plus traced-control-flow, dtype
# promotion, donation hazards and bench timing discipline. Full-tree run
# (the host-sync rule walks the cross-file call graph; sub-second). Opt out
# with DLINT=0 — which also disarms the P_DLINT tripwire on the tier-1 run
# above; the JSON report lands at /tmp/dlint.json either way it runs.
if [ "${DLINT:-1}" != "0" ]; then
  if ! python -m parseable_tpu.analysis.device --json-out /tmp/dlint.json; then
    echo "check_green: DLINT RED (unbaselined findings; see above and /tmp/dlint.json)" >&2
    exit 1
  fi
  echo "check_green: dlint GREEN (report: /tmp/dlint.json)"
else
  echo "check_green: dlint SKIPPED (DLINT=0)"
fi

# dynamic-analysis gate: the same tier-1 suite again under the psan runtime
# concurrency sanitizer (P_PSAN=1) — Eraser lockset races on guarded-by
# attrs, runtime lock-order vs the declared hierarchy, event-loop blocking,
# per-test thread/executor leaks. Opt out with PSAN=0 (e.g. on a machine
# where the double run is too slow); the JSON report lands at /tmp/psan.json
# alongside /tmp/plint.json either way the pass runs. Like PLINT_FULL=1
# above, running both full gates is the authoritative pre-snapshot check.
if [ "${PSAN:-1}" != "0" ]; then
  rm -f /tmp/_t1_psan.log
  timeout -k 10 870 env JAX_PLATFORMS=cpu P_PSAN=1 python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1_psan.log
  rc=${PIPESTATUS[0]}
  if [ "$rc" -ne 0 ]; then
    echo "check_green: PSAN RED (rc=$rc; findings above and in /tmp/psan.json)" >&2
    exit "$rc"
  fi
  echo "check_green: psan GREEN (report: /tmp/psan.json)"
else
  echo "check_green: psan SKIPPED (PSAN=0)"
fi

# native gate: nsan (parseable_tpu/analysis/nsan/) — ABI drift between
# fastpath.cpp's extern "C" surface and the ctypes bindings, clang-tidy
# when installed, and the fuzz-corpus replay under the ASan/UBSan
# instrumented build; then the native-touching test files again with
# P_NSAN=1 (the same tests, loaded against the sanitized library, with a
# ptpu_cols_live==0 session gate). Opt out with NSAN=0. The CLI writes
# /tmp/nsan.json first; the pytest pass merges its section into it.
if [ "${NSAN:-1}" != "0" ]; then
  if ! python -m parseable_tpu.analysis.nsan --json-out /tmp/nsan.json; then
    echo "check_green: NSAN RED (unbaselined findings; see above and /tmp/nsan.json)" >&2
    exit 1
  fi
  # the sanitized pytest pass runs UBSan-instrumented (the only mode sound
  # under late dlopen; see analysis/nsan/__init__.py) — probe that the
  # toolchain's UBSan actually links instead of guessing from `command -v`
  if echo 'int main(){return 0;}' | g++ -fsanitize=undefined -x c++ - -o /tmp/_nsan_probe 2>/dev/null; then
    rm -f /tmp/_nsan_probe /tmp/_t1_nsan.log
    timeout -k 10 600 env JAX_PLATFORMS=cpu P_NSAN=1 python -m pytest -q -m 'not slow' \
      tests/test_native_ingest.py tests/test_native_otel.py \
      tests/test_native_parity_fuzz.py tests/test_native_and_formats.py \
      tests/test_native_telem.py \
      tests/test_hll_distinct.py tests/test_nsan_fuzz.py \
      --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
      2>&1 | tee /tmp/_t1_nsan.log
    rc=${PIPESTATUS[0]}
    if [ "$rc" -ne 0 ]; then
      echo "check_green: NSAN RED (sanitized test run rc=$rc; see /tmp/nsan.json)" >&2
      exit "$rc"
    fi
    echo "check_green: nsan GREEN (report: /tmp/nsan.json)"
    # edge smoke, same UBSan leg: one real server process booted with
    # P_EDGE_PORT against the sanitized library (P_NSAN_LIB), a keep-alive
    # happy-path ack pair, a forced decline relayed byte-identical to the
    # aiohttp tier, and the conservation audit's edge section drained at
    # quiesce. Opt out with EDGE=0 (boots 1 process; ~half a minute). Only
    # meaningful when the library exports the edge ABI — skipped otherwise.
    if [ "${EDGE:-1}" != "0" ]; then
      if python -c 'from parseable_tpu import native; import sys; sys.exit(0 if native.edge_available() else 1)' 2>/dev/null; then
        san_lib=$(python -c 'import parseable_tpu, pathlib; from parseable_tpu.analysis.nsan import build_san_lib; from parseable_tpu.config import nsan_options; p = build_san_lib(pathlib.Path(parseable_tpu.__file__).resolve().parent.parent, nsan_options()["san_mode"]); print(p or "")' 2>/dev/null)
        if ! timeout -k 10 300 env JAX_PLATFORMS=cpu P_NSAN_LIB="$san_lib" python scripts/edge_smoke.py; then
          echo "check_green: EDGE RED (native ingest edge smoke failed under UBSan)" >&2
          exit 1
        fi
        echo "check_green: edge GREEN (sanitized lib: ${san_lib:-none})"
      else
        echo "check_green: edge SKIPPED (native edge ABI unavailable)"
      fi
    else
      echo "check_green: edge SKIPPED (EDGE=0)"
    fi
  else
    echo "check_green: nsan GREEN — ABI+corpus only (no UBSan-capable toolchain for the sanitized test pass)"
  fi
else
  echo "check_green: nsan SKIPPED (NSAN=0)"
fi

# observability gate: the multi-process cluster smoke — distributed trace
# stitching (one cross-node span tree per query) and the conservation-law
# audit (zero violations at quiesce) over REAL server processes, with the
# ingestors serving the Arrow Flight data plane (the smoke asserts the
# scatter rode it). FLIGHT=0 pins the smoke to the HTTP tier — the escape
# hatch if gRPC misbehaves on a box. Opt out entirely with OBS_CLUSTER=0
# (boots 3 processes; ~half a minute on a warm cache).
if [ "${OBS_CLUSTER:-1}" != "0" ]; then
  if ! timeout -k 10 420 env JAX_PLATFORMS=cpu FLIGHT="${FLIGHT:-1}" python scripts/obs_smoke.py --cluster; then
    echo "check_green: OBS CLUSTER RED (trace stitching / audit smoke failed)" >&2
    exit 1
  fi
  echo "check_green: obs cluster GREEN"
else
  echo "check_green: obs cluster SKIPPED (OBS_CLUSTER=0)"
fi

# merged artifact: one /tmp/analysis_summary.json rolling up the five
# static/dynamic analysis reports (plint, psan, nsan, wlint, dlint) so a snapshot
# reviewer reads one file. Skipped gates simply have no section; the merge
# itself never turns the gate red.
python - <<'PY' || echo "check_green: analysis summary merge failed (non-fatal)" >&2
import json, pathlib
out = {}
for name in ("plint", "psan", "nsan", "wlint", "dlint"):
    p = pathlib.Path(f"/tmp/{name}.json")
    if not p.exists():
        continue
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError):
        continue
    findings = doc.get("findings", [])
    baselined = doc.get("baselined", [])
    out[name] = {
        "artifact": str(p),
        "files_checked": doc.get("files_checked"),
        "findings": len(findings),
        "baselined": len(baselined),
        "unbaselined": max(0, len(findings) - len(baselined)),
        "clean": bool(doc.get("clean", not findings)),
    }
pathlib.Path("/tmp/analysis_summary.json").write_text(
    json.dumps({"version": 1, "gates": out}, indent=2) + "\n"
)
print(f"check_green: analysis summary -> /tmp/analysis_summary.json ({', '.join(out) or 'no artifacts'})")
PY
