#!/usr/bin/env bash
# Pre-snapshot gate: run the tier-1 verify command from ROADMAP.md and exit
# nonzero on ANY failure ("go green and stay green"). Run this before every
# snapshot/PR; a red tier-1 must block the commit, not ride along.
#
# Usage: scripts/check_green.sh
set -o pipefail
cd "$(dirname "$0")/.."

# With a toolchain present, a native fastpath that fails to compile must be
# a test failure, not a silent pure-Python-fallback green (the columnar
# ingest tier, xxh64, and HLL would all quietly degrade). Tests read this
# in conftest pytest_sessionstart; native/__init__.py also hard-raises.
if command -v g++ >/dev/null 2>&1; then
  export P_NATIVE_REQUIRED=1
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
  echo "check_green: TIER-1 RED (pytest rc=$rc)" >&2
  exit "$rc"
fi
if grep -aqE '^[0-9]+ (failed|error)|, [0-9]+ (failed|error)' /tmp/_t1.log; then
  echo "check_green: TIER-1 RED (failures in log despite rc=0)" >&2
  exit 1
fi
echo "check_green: tier-1 GREEN"

# static-analysis gate: the tree must lint clean (zero unbaselined plint
# findings) before snapshot — concurrency/invariant bugs are cheapest here.
# Default: --changed (findings reported only for files differing from
# `git merge-base HEAD main`, whole tree still analyzed) + the mtime result
# cache, so the gate stays fast as the rule count grows. PLINT_FULL=1 runs
# the authoritative full-tree report. The JSON report lands at
# /tmp/plint.json either way (gate artifact).
plint_args=(--json-out /tmp/plint.json)
if [ "${PLINT_FULL:-0}" != "1" ]; then
  plint_args+=(--changed)
fi
if ! python -m parseable_tpu.analysis "${plint_args[@]}"; then
  echo "check_green: PLINT RED (unbaselined findings; see above and /tmp/plint.json)" >&2
  exit 1
fi
echo "check_green: plint GREEN (report: /tmp/plint.json)"

# dynamic-analysis gate: the same tier-1 suite again under the psan runtime
# concurrency sanitizer (P_PSAN=1) — Eraser lockset races on guarded-by
# attrs, runtime lock-order vs the declared hierarchy, event-loop blocking,
# per-test thread/executor leaks. Opt out with PSAN=0 (e.g. on a machine
# where the double run is too slow); the JSON report lands at /tmp/psan.json
# alongside /tmp/plint.json either way the pass runs. Like PLINT_FULL=1
# above, running both full gates is the authoritative pre-snapshot check.
if [ "${PSAN:-1}" != "0" ]; then
  rm -f /tmp/_t1_psan.log
  timeout -k 10 870 env JAX_PLATFORMS=cpu P_PSAN=1 python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1_psan.log
  rc=${PIPESTATUS[0]}
  if [ "$rc" -ne 0 ]; then
    echo "check_green: PSAN RED (rc=$rc; findings above and in /tmp/psan.json)" >&2
    exit "$rc"
  fi
  echo "check_green: psan GREEN (report: /tmp/psan.json)"
else
  echo "check_green: psan SKIPPED (PSAN=0)"
fi
