"""100GB-class scale bench over the persistent .benchwork dataset.

VERDICT r4 #2: config 4 is specified at 100 GB but had only run at 8-32M
row smoke scale, which never stresses the tiering (hot-set eviction under
budget pressure, enccache hit rates, sustained host decode). This runs
the north-star query over the FULL persistent dataset (700M rows ~= 150GB
logical NDJSON, built by scripts/build_benchwork.py) and reports, per
engine:

- cpu:       full streaming scan through the CPU engine;
- tpu first: compile + live-cold (parquet decode -> encode -> ship, with
             enccache write-behind populating);
- tpu cache-cold: hot set cleared, blocks reload via the enccache
             (zero-copy memmap) — the restart-recovery path;
- tpu warm:  whatever the 8 GiB HBM budget keeps resident (at ~11 GB
             encoded, eviction pressure is the point: the hot set churns
             and the run measures steady-state re-ship cost);

plus the tiering counters that prove the machinery engaged (hot-set
evictions, enccache hits/misses, per-route block counts).

`run_battery` is the shared measurement protocol — scripts/hw_validate.py
runs the same battery over its config list so the published numbers can
never drift between the two harnesses.

When the real chip is unreachable (tunnel down) the TPU engine runs on a
virtual 8-device CPU mesh — same executor, same tiering, CPU "HBM".
Reference: src/hottier.rs:281-432; BASELINE.json config 4.

Usage: python scripts/bench_scale.py [--real] [--max-minutes N]
Emits one JSON line per measurement; the last line is the summary the
caller (bench.py) forwards. bench.py calls main() IN-PROCESS when the
real chip is up (libtpu holds an exclusive device lock, so a --real
subprocess could never initialize while the parent owns the chip) and as
a subprocess for the virtual-mesh case (which needs its own XLA flags).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

WORK = REPO / ".benchwork"

SQL = (
    "SELECT path, host, count(*) AS c, sum(bytes) AS s FROM bench "
    "GROUP BY path, host ORDER BY s DESC LIMIT 10"
)


def rows_close(a: list, b: list) -> bool:
    """Exact on keys/counts; 1e-4 relative on floats (device sums are f32
    per block — same tolerance the test suite and bench.py use)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if abs(va - vb) > 1e-4 * max(1.0, abs(va)):
                    return False
            elif va != vb:
                return False
    return True


def run_battery(p, sess_cpu, sess, sql: str, rows_total: int, emit, label: str) -> dict:
    """The measurement protocol: cpu -> tpu first (compile + live cold) ->
    enccache settle -> hot-set clear -> cache cold -> warm, with tiering
    counters deltas. Returns the summary dict (also emitted per stage)."""
    from parseable_tpu.ops.enccache import get_enccache
    from parseable_tpu.ops.hotset import get_hotset

    ec = get_enccache(p.options)
    hs = get_hotset()

    def run(s) -> tuple[float, list, dict]:
        t0 = time.perf_counter()
        res = s.query(sql)
        dt = time.perf_counter() - t0
        rows = sorted(
            (tuple(r.values()) for r in res.to_json_rows()),
            key=lambda t: tuple(str(v) for v in t),
        )
        return dt, rows, res.stats

    cpu_t, cpu_rows, _ = run(sess_cpu)
    emit("cpu", config=label, secs=round(cpu_t, 2), rows_per_sec=round(rows_total / cpu_t))

    first_t, tpu_rows, stats1 = run(sess)
    emit(
        "tpu_first",
        config=label,
        secs=round(first_t, 2),
        rows_per_sec=round(rows_total / first_t),
        note="compile + live cold (decode/encode/ship + enccache write-behind)",
        routes=stats1.get("device_routes"),
    )
    if ec is not None:
        ec.wait_idle()

    hs.clear()
    ev0, h0, m0 = hs.evictions, (ec.hits if ec else 0), (ec.misses if ec else 0)
    cold_t, rows2, stats2 = run(sess)
    emit(
        "tpu_cache_cold",
        config=label,
        secs=round(cold_t, 2),
        rows_per_sec=round(rows_total / cold_t),
        enccache_hits=(ec.hits - h0) if ec else None,
        enccache_misses=(ec.misses - m0) if ec else None,
        hotset_evictions=hs.evictions - ev0,
        routes=stats2.get("device_routes"),
    )

    ev0 = hs.evictions
    warm_t, rows3, stats3 = run(sess)
    emit(
        "tpu_warm",
        config=label,
        secs=round(warm_t, 2),
        rows_per_sec=round(rows_total / warm_t),
        hotset_resident_gb=round(hs.resident_bytes / 2**30, 2),
        hotset_evictions=hs.evictions - ev0,
        routes=stats3.get("device_routes"),
    )

    match = (
        rows_close(cpu_rows, tpu_rows)
        and rows_close(cpu_rows, rows2)
        and rows_close(cpu_rows, rows3)
    )
    if not match:
        emit("mismatch", config=label, cpu=cpu_rows[:2], tpu=tpu_rows[:2])
    return {
        "rows": rows_total,
        "cpu_secs": round(cpu_t, 2),
        "first_run_secs": round(first_t, 2),
        "cache_cold_secs": round(cold_t, 2),
        "cache_cold_vs_cpu": round(cpu_t / cold_t, 3),
        "warm_secs": round(warm_t, 2),
        "warm_vs_cpu": round(cpu_t / warm_t, 3),
        "rows_per_sec_warm": round(rows_total / warm_t, 1),
        "hotset_evictions": hs.evictions,
        "hotset_resident_gb": round(hs.resident_bytes / 2**30, 2),
        "enccache_hits": ec.hits if ec else None,
        "enccache_misses": ec.misses if ec else None,
        "results_match": bool(match),
    }


def run_pressure_battery(p, sql: str, rows_total: int, emit) -> dict:
    """Memory-pressure phase (ROADMAP "make the tiering story true"): the
    SAME scale query with P_TPU_HOT_BYTES capped well below the encoded
    working set (BENCH_SCALE_HOT_BYTES, default 2 GiB vs the ~7-11 GB
    encoded working set), warm p50/p95 over >=BENCH_SCALE_PRESSURE_REPS
    (10) reps per eviction policy (P_TPU_HOT_POLICY cost vs lru A/B).
    The recorded scale runs showed hotset_evictions: 0 — the budget was
    never exceeded, so the "100 GB on a 16 GiB device" label was untested.
    This phase makes the eviction path the thing under measurement.
    BENCH_SCALE_PRESSURE=0 skips."""
    if os.environ.get("BENCH_SCALE_PRESSURE", "1") == "0":
        return {}
    import bench as _bench
    from parseable_tpu.ops.hotset import get_hotset
    from parseable_tpu.query.session import QuerySession

    budget = int(os.environ.get("BENCH_SCALE_HOT_BYTES", str(2 << 30)))
    reps = int(os.environ.get("BENCH_SCALE_PRESSURE_REPS", "10"))
    saved = {k: os.environ.get(k) for k in ("P_TPU_HOT_BYTES", "P_TPU_HOT_POLICY")}
    out: dict = {"pressure_budget_bytes": budget}
    try:
        os.environ["P_TPU_HOT_BYTES"] = str(budget)
        for policy in ("lru", "cost"):
            os.environ["P_TPU_HOT_POLICY"] = policy
            hs = get_hotset()  # re-roots onto the capped budget + policy
            hs.clear()
            sess = QuerySession(p, engine="tpu")
            sess.query(sql)  # populate up to the capped budget
            ev0, times = hs.evictions, []
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                sess.query(sql)
                times.append(time.perf_counter() - t0)
            p50 = _bench.percentile(times, 0.50)
            p95 = _bench.percentile(times, 0.95)
            emit(
                f"tpu_pressure_{policy}",
                config="scale_topk_pressure",
                budget_bytes=budget,
                warm_p50_s=round(p50, 2),
                warm_p95_s=round(p95, 2),
                rows_per_sec=round(rows_total / max(p50, 1e-9)),
                hotset_evictions=hs.evictions - ev0,
                hotset_resident_gb=round(hs.resident_bytes / 2**30, 2),
            )
            out[f"pressure_{policy}_p50_s"] = round(p50, 2)
            out[f"pressure_{policy}_p95_s"] = round(p95, 2)
            out[f"pressure_{policy}_evictions"] = hs.evictions - ev0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        get_hotset().clear()
    if "pressure_cost_p95_s" in out and "pressure_lru_p95_s" in out:
        out["pressure_cost_vs_lru_p95"] = round(
            out["pressure_lru_p95_s"] / max(out["pressure_cost_p95_s"], 1e-9), 3
        )
    return out


def main(real: bool = False, max_minutes: int = 0) -> None:
    meta_path = WORK / "meta.json"
    if not meta_path.exists():
        print(json.dumps({"error": "no .benchwork dataset"}))
        sys.exit(1)
    meta = json.loads(meta_path.read_text())

    if not real:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if not real:
        jax.config.update("jax_platforms", "cpu")

    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.query.session import QuerySession

    opts = Options()
    opts.local_staging_path = WORK / "staging"
    p = Parseable(opts, StorageOptions(backend="local-store", root=WORK / "data"))

    sql = SQL
    rows = meta["rows"]
    if max_minutes:
        # dataset minutes start 2024-05-01T00:00, 1M rows per minute
        sql = SQL.replace(
            "FROM bench ",
            "FROM bench WHERE p_timestamp < '2024-05-01T"
            f"{max_minutes // 60:02d}:{max_minutes % 60:02d}:00' ",
        )
        rows = min(rows, max_minutes * 1_000_000)

    def emit(kind: str, **kw) -> None:
        print(json.dumps({"kind": kind, **kw}), flush=True)

    sess_cpu = QuerySession(p, engine="cpu")
    sess = QuerySession(p, engine="tpu")
    result = run_battery(p, sess_cpu, sess, sql, rows, emit, "scale_topk")
    pressure = run_pressure_battery(p, sql, rows, emit)
    if pressure:
        result.update(pressure)
    summary = {
        "metric": "scale_topk_multicol_rows_per_sec",
        "value": result["rows_per_sec_warm"],
        "unit": "rows/s",
        "vs_baseline": result["warm_vs_cpu"],
        "logical_gb": meta.get("logical_gb"),
        "disk_gb": round(meta.get("disk_bytes", 0) / 1e9, 1),
        "devices": jax.device_count(),
        "platform": jax.devices()[0].platform,
        "note": "config 4 at 100GB-logical scale through the tiering "
        "(hot set under eviction pressure + enccache)",
        **result,
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--real", action="store_true", help="use the real chip")
    ap.add_argument(
        "--max-minutes",
        type=int,
        default=0,
        help="bound the scan to the first N minute-partitions (0 = full)",
    )
    args = ap.parse_args()
    main(real=args.real, max_minutes=args.max_minutes)
