"""Battery hook: run the multi-process distributed fan-out bench standalone.

`python scripts/bench_fanout.py` boots 1 querier per data plane + N ingestor
processes (scripts/blackbox.py) and emits the bench_distributed_fanout and
bench_flight_fanin lines (the latter: interleaved Arrow-Flight-vs-HTTP
fan-in A/B, GB/s + per-pull wire bytes) — the same emissions bench.py
produces inside the full battery, runnable on their own for the
hardware-watch battery and for iterating on the cluster path without
rebuilding datasets. Knobs: BENCH_DF_* (see bench.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_distributed_fanout  # noqa: E402

if __name__ == "__main__":
    bench_distributed_fanout()
