"""Build the persistent large-scale bench dataset at /root/repo/.benchwork.

VERDICT r4 #2: config 4 is specified at 100 GB and had only ever run at
8-32M-row smoke scale. This builds the dataset ONCE through the real
pipeline (staging -> parquet -> catalog) and persists it so bench.py,
scripts/hw_validate.py, and the driver's bench run can all execute the
scale config without paying the build again.

Default 700M rows of the flog-like default profile ~= 100 GB of logical
JSON (measured per-row serialization x rows, recorded in meta.json);
~26 GB parquet on disk. Resumable is not worth the complexity at ~45 min
build: if meta.json is missing the tree is wiped and rebuilt.

Usage: python scripts/build_benchwork.py [--rows N] [--hc-rows N]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402

# the axon sitecustomize initializes the tunneled TPU client on ANY
# backend touch even with JAX_PLATFORMS=cpu in env; when the tunnel is
# wedged that hangs forever (see .claude/skills/verify SKILL gotchas)
jax.config.update("jax_platforms", "cpu")

WORK = REPO / ".benchwork"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=700_000_000)
    ap.add_argument(
        "--hc-rows",
        type=int,
        default=32_000_000,
        help="rows for the high-cardinality profile stream (bench_hc)",
    )
    args = ap.parse_args()

    meta_path = WORK / "meta.json"
    if meta_path.exists():
        meta = json.loads(meta_path.read_text())
        if meta.get("rows") == args.rows and meta.get("hc_rows") == args.hc_rows:
            print(f"already built: {meta}")
            return
    shutil.rmtree(WORK, ignore_errors=True)
    WORK.mkdir(parents=True)

    import bench
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable

    opts = Options()
    opts.local_staging_path = WORK / "staging"
    # cpu engine during the build: skips the upload-time enccache seeding
    # (core.py upload_files_from_staging) so the scale bench's first TPU
    # run measures a true live-cold pass that populates the cache itself
    opts.query_engine = "cpu"
    p = Parseable(opts, StorageOptions(backend="local-store", root=WORK / "data"))

    # logical-size yardstick: the NDJSON bytes these rows would occupy on
    # the wire (what "100 GB of logs" means operationally)
    sample_row = {
        "p_timestamp": "2024-05-01T00:00:00.000",
        "host": "10.0.3.7",
        "method": "GET",
        "path": "/api/v1/resource42",
        "message": "error: upstream timeout after 350ms",
        "status": 200.0,
        "bytes": 24731.0,
        "latency_ms": 211.7,
    }
    row_bytes = len(json.dumps(sample_row)) + 1
    logical = row_bytes * args.rows

    t0 = time.perf_counter()
    bench.build_dataset(p, "bench", args.rows, sync_every=8)
    build_s = time.perf_counter() - t0
    print(f"bench: {args.rows} rows in {build_s:.0f}s ({args.rows/build_s:,.0f} rows/s)")

    t0 = time.perf_counter()
    if args.hc_rows:
        bench.build_dataset(p, "bench_hc", args.hc_rows, profile="highcard", sync_every=8)
        print(f"bench_hc: {args.hc_rows} rows in {time.perf_counter()-t0:.0f}s")

    du = sum(f.stat().st_size for f in WORK.rglob("*") if f.is_file())
    meta = {
        "rows": args.rows,
        "hc_rows": args.hc_rows,
        "logical_json_bytes": logical,
        "logical_gb": round(logical / 1e9, 1),
        "disk_bytes": du,
        "build_secs": round(build_s, 1),
        "profile": "default",
        "built_at": time.time(),
    }
    meta_path.write_text(json.dumps(meta, indent=2))
    print(json.dumps(meta))


if __name__ == "__main__":
    main()
