"""Observability smoke check: boot a server, ingest, query, scrape /metrics.

Asserts the self-observability pipeline is actually wired end to end:

- nonzero `parseable_query_execute_time` and
  `parseable_storage_request_response_time` samples in a /metrics scrape
  after one ingest + one query;
- the ingest and query requests (sent with the same W3C `traceparent`)
  produce spans sharing a trace_id with correct parentage;
- `SELECT count(*) FROM pmeta` > 0 through the normal SQL path after the
  span sink flushes.

`--cluster` runs the multi-process variant on top (scripts/blackbox.py):
a real 1-querier + 2-ingestor cluster, a distributed query whose
X-P-Trace-Id stitches into ONE cross-node span tree via
GET /api/v1/cluster/trace/{id}, an EXPLAIN ANALYZE with a per-peer fanout
row, and a conservation-law audit reporting zero violations at quiesce.

Runnable standalone (`python scripts/obs_smoke.py [--cluster]`) and from
tests/test_observability.py as a `not slow` test.
"""

from __future__ import annotations

import asyncio
import base64
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

AUTH = {"Authorization": "Basic " + base64.b64encode(b"admin:admin").decode()}
TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


def run_smoke(workdir: Path) -> dict:
    """Drive the smoke flow in-process; returns a result summary dict.
    Raises AssertionError on any broken link in the pipeline."""
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.server.app import ServerState, build_app
    from parseable_tpu.utils import telemetry

    opts = Options()
    opts.local_staging_path = workdir / "staging"
    opts.query_engine = "cpu"
    p = Parseable(opts, StorageOptions(backend="local-store", root=workdir / "data"))
    state = ServerState(p)
    telemetry.SPAN_SINK.attach(p)

    async def flow() -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        client = TestClient(TestServer(build_app(state)))
        await client.start_server()
        try:
            r = await client.post(
                "/api/v1/ingest",
                json=[{"host": f"h{i % 3}", "status": 200} for i in range(50)],
                headers={**AUTH, "X-P-Stream": "smoke", "traceparent": TRACEPARENT},
            )
            assert r.status == 200, await r.text()
            ingest_trace = r.headers.get("X-P-Trace-Id")

            # flush + object sync under one trace, like the sync loops do
            with telemetry.trace_context():
                p.local_sync(shutdown=True)
                p.sync_all_streams()

            r = await client.post(
                "/api/v1/query",
                json={"query": "SELECT host, count(*) c FROM smoke GROUP BY host"},
                headers={**AUTH, "traceparent": TRACEPARENT},
            )
            assert r.status == 200, await r.text()
            assert r.headers.get("X-P-Trace-Id") == ingest_trace == "ab" * 16

            # trace tree: ingest + query spans share the propagated trace id
            r = await client.get(
                f"/api/v1/debug/spans?trace_id={ingest_trace}", headers=AUTH
            )
            spans = (await r.json())["spans"]
            names = {s["name"] for s in spans}
            assert {"http.request", "ingest", "query"} <= names, names
            by_name = {s["name"]: s for s in spans}
            roots = [s for s in spans if s["name"] == "http.request"]
            assert by_name["ingest"]["parent_span_id"] in {s["span_id"] for s in roots}
            assert by_name["query"]["parent_span_id"] in {s["span_id"] for s in roots}

            # pmeta self-ingest: spans queryable through the SQL engine
            flushed = telemetry.SPAN_SINK.flush()
            assert flushed > 0, "span sink flushed no rows"
            p.local_sync(shutdown=True)
            p.sync_all_streams()
            r = await client.post(
                "/api/v1/query",
                json={"query": "SELECT count(*) c FROM pmeta"},
                headers=AUTH,
            )
            assert r.status == 200, await r.text()
            pmeta_rows = (await r.json())[0]["c"]
            assert pmeta_rows > 0

            # metrics scrape: the dead families must be alive
            r = await client.get("/api/v1/metrics", headers=AUTH)
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain; version=")
            text = await r.text()
            nonzero = {}
            for fam in (
                "parseable_query_execute_time",
                "parseable_storage_request_response_time",
            ):
                samples = [
                    line
                    for line in text.splitlines()
                    if line.startswith(fam)
                    and not line.startswith("#")
                    and float(line.rsplit(" ", 1)[-1]) > 0
                ]
                assert samples, f"no nonzero {fam} samples after smoke flow"
                nonzero[fam] = len(samples)
            return {
                "trace_id": ingest_trace,
                "span_names": sorted(names),
                "pmeta_rows": pmeta_rows,
                "nonzero_samples": nonzero,
            }
        finally:
            await client.close()
            telemetry.SPAN_SINK.detach()

    try:
        return asyncio.new_event_loop().run_until_complete(flow())
    finally:
        # deterministic pool shutdown (ingest/query workers, sync/upload/
        # enrichment) — psan's leak detector holds the smoke to the same
        # standard as the server's own stop path
        state.stop()


def _load_blackbox():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "blackbox", Path(__file__).resolve().parent / "blackbox.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_cluster_smoke(workdir: Path, flight: bool = True) -> dict:
    """Multi-process observability smoke: distributed trace stitching +
    conservation audit over a REAL 1-querier / 2-ingestor cluster.
    Raises AssertionError on any broken link.

    `flight=True` (default) serves the ingestors' data plane over Arrow
    Flight and additionally asserts the scatter rode it — transport in the
    fanout stage/plan row AND `flight.do_get` spans in the stitched trace.
    `flight=False` (check_green.sh's FLIGHT=0 hatch) keeps the whole smoke
    on the HTTP tier."""
    import time

    bb = _load_blackbox()
    # frozen sync: rows stay in staging, so the distributed query MUST
    # touch both peers (fan-out/fan-in) and the audit books carry a
    # nonzero staging term on both ingestors
    frozen = {
        "P_LOCAL_SYNC_INTERVAL": "3600",
        "P_STORAGE_UPLOAD_INTERVAL": "3600",
        # force the sharded native parse on every ingest so the stitched
        # trace must contain per-shard C++ spans (native-path telemetry)
        "P_INGEST_PARSE_SHARDS": "2",
        "P_INGEST_SHARD_MIN_BYTES": "0",
    }
    with bb.ClusterHarness(workdir) as cluster:
        ing0 = cluster.spawn("ingest", "ing0", env_extra=frozen, flight=flight)
        ing1 = cluster.spawn("ingest", "ing1", env_extra=frozen, flight=flight)
        q = cluster.spawn("query", "q0")
        for node in (ing0, ing1, q):
            cluster.wait_live(node)

        for ing in (ing0, ing1):
            cluster.ingest(
                ing, "csmoke", [{"host": f"h{i % 2}", "v": float(i)} for i in range(30)]
            )

        # distributed visibility first: discovery + fan-in are async
        def count_rows() -> int:
            try:
                recs, _ = cluster.query(q, "SELECT count(*) c FROM csmoke", "10m", "now")
            except RuntimeError:
                return -1
            return int(recs[0]["c"]) if recs else 0

        deadline = time.monotonic() + 90
        seen = count_rows()
        while time.monotonic() < deadline and seen != 60:
            time.sleep(0.5)
            seen = count_rows()
        assert seen == 60, f"querier saw {seen}/60 rows"

        # one distributed query -> ONE stitched cross-node trace
        recs, stats, trace_id = cluster.query_traced(
            q,
            "SELECT host, count(*) c FROM csmoke GROUP BY host ORDER BY host",
            "10m",
            "now",
        )
        assert recs == [{"host": "h0", "c": 30}, {"host": "h1", "c": 30}], recs
        assert len(trace_id) == 32, f"bad X-P-Trace-Id {trace_id!r}"
        fanout = (stats.get("stages") or {}).get("fanout") or {}
        assert fanout.get("per_peer"), f"no per-peer fanout breakdown: {stats}"
        if flight:
            # the hot tier carried the scatter, and said so
            assert fanout.get("transport", {}).get("flight", 0) >= 1, fanout
            assert all(
                pp.get("transport") == "flight"
                for pp in fanout["per_peer"].values()
                if pp.get("result") == "ok"
            ), fanout

        def walk(nodes):
            for nd in nodes:
                yield nd
                yield from walk(nd["children"])

        tree = cluster.cluster_trace(q, trace_id)
        assert tree["orphans"] == 0, tree
        assert tree["span_count"] > 0 and tree["tree"], tree
        contributing = [n for n in tree["nodes"] if n["span_count"] > 0]
        assert len(contributing) >= 3, (
            f"expected querier + both ingestors in the trace, got {tree['nodes']}"
        )
        assert tree["critical_path"], tree
        if flight:
            # the ingestors' DoGet handlers joined the querier's trace:
            # the gRPC hop propagates traceparent exactly like HTTP
            qnames = [s["name"] for s in walk(tree["tree"])]
            assert qnames.count("flight.do_get") >= 2, qnames

        # EXPLAIN ANALYZE surfaces the same breakdown as a plan row
        plan, _ = cluster.query(
            q,
            "EXPLAIN ANALYZE SELECT host, count(*) c FROM csmoke GROUP BY host",
            "10m",
            "now",
        )
        plan_types = {r.get("plan_type") for r in plan}
        assert "fanout" in plan_types, f"no fanout plan row: {plan}"
        if flight:
            fanrows = [r for r in plan if r.get("plan_type") == "fanout"]
            assert any(
                "transport=flight" in (r.get("plan") or "") for r in fanrows
            ), f"no flight transport in fanout plan row: {fanrows}"

        # native-path telemetry: a traced ingest must stitch the C++
        # per-shard parse spans (recorded below the ctypes boundary by the
        # fastpath event ring) into the cluster trace, and their row/byte
        # accounting must be exact
        import json as _json

        ing_tid = "f0" * 16
        payload = [{"host": f"h{i % 2}", "v": float(i)} for i in range(40)]
        status, _, _ = bb.http_json_headers(
            "POST",
            f"{ing0.url}/api/v1/ingest",
            payload,
            headers={
                "X-P-Stream": "csmoke",
                "traceparent": f"00-{ing_tid}-{'d1' * 8}-01",
            },
        )
        assert status == 200, f"traced ingest failed: {status}"
        itree = cluster.cluster_trace(q, ing_tid)
        ispans = list(walk(itree["tree"]))
        native_parse = [s for s in ispans if s["name"] == "native.parse"]
        assert len(native_parse) == 2, (
            f"expected 2 native shard spans, got {[s['name'] for s in ispans]}"
        )
        assert sum(s["rows"] for s in native_parse) == 40, native_parse
        assert sum(s["bytes"] for s in native_parse) == len(
            _json.dumps(payload).encode()
        ), native_parse
        assert any(s["name"] == "native.stitch" for s in ispans), (
            f"no stitch span in {[s['name'] for s in ispans]}"
        )

        # conservation audit: zero violations once the cluster is at rest
        deadline = time.monotonic() + 60
        report = cluster.audit(q, scope="cluster", quiesce=True)
        while time.monotonic() < deadline and report["total_violations"]:
            time.sleep(1.0)
            report = cluster.audit(q, scope="cluster", quiesce=True)
        assert report["total_violations"] == 0, report["violations"]
        assert len(report["nodes"]) == 3 and all(
            n.get("reachable") for n in report["nodes"]
        ), report["nodes"]
        return {
            "trace_id": trace_id,
            "trace_nodes": len(contributing),
            "span_count": tree["span_count"],
            "critical_path": [s["name"] for s in tree["critical_path"]],
            "fanout_transport": fanout.get("transport", {}),
            "audit_nodes": len(report["nodes"]),
            "violations": report["total_violations"],
        }


def main(argv: list[str] | None = None) -> int:
    import os

    argv = sys.argv[1:] if argv is None else argv
    with tempfile.TemporaryDirectory(prefix="obs-smoke-") as d:
        result = run_smoke(Path(d))
    print("obs smoke OK:", result)
    if "--cluster" in argv:
        # FLIGHT=0: escape-hatch the smoke onto the HTTP data plane
        flight = os.environ.get("FLIGHT", "1") != "0"
        with tempfile.TemporaryDirectory(prefix="obs-smoke-cluster-") as d:
            cluster_result = run_cluster_smoke(Path(d), flight=flight)
        print("obs cluster smoke OK:", cluster_result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
