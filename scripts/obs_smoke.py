"""Observability smoke check: boot a server, ingest, query, scrape /metrics.

Asserts the self-observability pipeline is actually wired end to end:

- nonzero `parseable_query_execute_time` and
  `parseable_storage_request_response_time` samples in a /metrics scrape
  after one ingest + one query;
- the ingest and query requests (sent with the same W3C `traceparent`)
  produce spans sharing a trace_id with correct parentage;
- `SELECT count(*) FROM pmeta` > 0 through the normal SQL path after the
  span sink flushes.

Runnable standalone (`python scripts/obs_smoke.py`) and from
tests/test_observability.py as a `not slow` test.
"""

from __future__ import annotations

import asyncio
import base64
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

AUTH = {"Authorization": "Basic " + base64.b64encode(b"admin:admin").decode()}
TRACEPARENT = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


def run_smoke(workdir: Path) -> dict:
    """Drive the smoke flow in-process; returns a result summary dict.
    Raises AssertionError on any broken link in the pipeline."""
    from parseable_tpu.config import Options, StorageOptions
    from parseable_tpu.core import Parseable
    from parseable_tpu.server.app import ServerState, build_app
    from parseable_tpu.utils import telemetry

    opts = Options()
    opts.local_staging_path = workdir / "staging"
    opts.query_engine = "cpu"
    p = Parseable(opts, StorageOptions(backend="local-store", root=workdir / "data"))
    state = ServerState(p)
    telemetry.SPAN_SINK.attach(p)

    async def flow() -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        client = TestClient(TestServer(build_app(state)))
        await client.start_server()
        try:
            r = await client.post(
                "/api/v1/ingest",
                json=[{"host": f"h{i % 3}", "status": 200} for i in range(50)],
                headers={**AUTH, "X-P-Stream": "smoke", "traceparent": TRACEPARENT},
            )
            assert r.status == 200, await r.text()
            ingest_trace = r.headers.get("X-P-Trace-Id")

            # flush + object sync under one trace, like the sync loops do
            with telemetry.trace_context():
                p.local_sync(shutdown=True)
                p.sync_all_streams()

            r = await client.post(
                "/api/v1/query",
                json={"query": "SELECT host, count(*) c FROM smoke GROUP BY host"},
                headers={**AUTH, "traceparent": TRACEPARENT},
            )
            assert r.status == 200, await r.text()
            assert r.headers.get("X-P-Trace-Id") == ingest_trace == "ab" * 16

            # trace tree: ingest + query spans share the propagated trace id
            r = await client.get(
                f"/api/v1/debug/spans?trace_id={ingest_trace}", headers=AUTH
            )
            spans = (await r.json())["spans"]
            names = {s["name"] for s in spans}
            assert {"http.request", "ingest", "query"} <= names, names
            by_name = {s["name"]: s for s in spans}
            roots = [s for s in spans if s["name"] == "http.request"]
            assert by_name["ingest"]["parent_span_id"] in {s["span_id"] for s in roots}
            assert by_name["query"]["parent_span_id"] in {s["span_id"] for s in roots}

            # pmeta self-ingest: spans queryable through the SQL engine
            flushed = telemetry.SPAN_SINK.flush()
            assert flushed > 0, "span sink flushed no rows"
            p.local_sync(shutdown=True)
            p.sync_all_streams()
            r = await client.post(
                "/api/v1/query",
                json={"query": "SELECT count(*) c FROM pmeta"},
                headers=AUTH,
            )
            assert r.status == 200, await r.text()
            pmeta_rows = (await r.json())[0]["c"]
            assert pmeta_rows > 0

            # metrics scrape: the dead families must be alive
            r = await client.get("/api/v1/metrics", headers=AUTH)
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain; version=")
            text = await r.text()
            nonzero = {}
            for fam in (
                "parseable_query_execute_time",
                "parseable_storage_request_response_time",
            ):
                samples = [
                    line
                    for line in text.splitlines()
                    if line.startswith(fam)
                    and not line.startswith("#")
                    and float(line.rsplit(" ", 1)[-1]) > 0
                ]
                assert samples, f"no nonzero {fam} samples after smoke flow"
                nonzero[fam] = len(samples)
            return {
                "trace_id": ingest_trace,
                "span_names": sorted(names),
                "pmeta_rows": pmeta_rows,
                "nonzero_samples": nonzero,
            }
        finally:
            await client.close()
            telemetry.SPAN_SINK.detach()

    try:
        return asyncio.new_event_loop().run_until_complete(flow())
    finally:
        # deterministic pool shutdown (ingest/query workers, sync/upload/
        # enrichment) — psan's leak detector holds the smoke to the same
        # standard as the server's own stop path
        state.stop()


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="obs-smoke-") as d:
        result = run_smoke(Path(d))
    print("obs smoke OK:", result)
    return 0


if __name__ == "__main__":
    sys.exit(main())
