"""Black-box multi-process cluster harness (ROADMAP: "multi-process
black-box cluster harness + failure-scenario suite").

Boots REAL `parseable_tpu.server` processes — query / ingest modes over a
shared LocalFS object store — and drives them purely over HTTP, the way the
reference tests against running containers (docker-compose-distributed-test).
Used by `bench.py bench_distributed_fanout` (1 querier + N ingestors with
sustained background ingest) and importable from tests / future failure
scenarios: kill a node mid-sync, rolling restarts, querier LB with a dead
peer.

Processes boot cheaply: ~a few seconds each (the JAX import dominates), and
`ClusterHarness` tears everything down with terminate -> kill escalation so
a failed run can't leak servers.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

AUTH_HEADER = {"Authorization": "Basic " + base64.b64encode(b"admin:admin").decode()}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Node:
    """One running server process."""

    def __init__(
        self,
        proc: subprocess.Popen,
        mode: str,
        port: int,
        log_path: Path,
        flight_port: int = 0,
    ):
        self.proc = proc
        self.mode = mode
        self.port = port
        self.log_path = log_path
        self.flight_port = flight_port  # 0 = HTTP-only data plane

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self, timeout: float = 10.0) -> None:
        if not self.alive():
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(5)

    def kill(self) -> None:
        """Hard kill — the crash-recovery scenarios' failure injection."""
        if self.alive():
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(5)


def http_json(
    method: str,
    url: str,
    body: dict | list | None = None,
    headers: dict | None = None,
    timeout: float = 30.0,
):
    """One JSON round trip; returns (status, parsed-or-None)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in {**AUTH_HEADER, **(headers or {})}.items():
        req.add_header(k, v)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        try:
            return resp.status, json.loads(raw) if raw else None
        except json.JSONDecodeError:
            return resp.status, None


def http_json_headers(
    method: str,
    url: str,
    body: dict | list | None = None,
    headers: dict | None = None,
    timeout: float = 30.0,
):
    """Like http_json but also returns response headers — trace-stitching
    scenarios need X-P-Trace-Id off the query response."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    for k, v in {**AUTH_HEADER, **(headers or {})}.items():
        req.add_header(k, v)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        try:
            parsed = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            parsed = None
        return resp.status, parsed, dict(resp.headers)


class ClusterHarness:
    """Spawn + drive a real multi-process cluster over one LocalFS store."""

    def __init__(self, workdir: Path):
        self.workdir = Path(workdir)
        self.store = self.workdir / "shared-store"
        self.nodes: list[Node] = []

    def spawn(
        self,
        mode: str,
        name: str,
        env_extra: dict | None = None,
        port: int | None = None,
        flight: bool = False,
    ) -> Node:
        port = port or free_port()
        flight_port = free_port() if flight else 0
        staging = self.workdir / f"staging-{name}"
        staging.mkdir(parents=True, exist_ok=True)
        log_dir = self.workdir / "logs"
        log_dir.mkdir(parents=True, exist_ok=True)
        log_path = log_dir / f"{name}.log"
        env = dict(os.environ)
        env.update(
            {
                "P_MODE": mode,
                "P_ADDR": f"127.0.0.1:{port}",
                "P_FS_DIR": str(self.store),
                "P_STAGING_DIR": str(staging),
                "P_CHECK_UPDATE": "false",
                "P_SEND_ANONYMOUS_USAGE_DATA": "false",
                "P_QUERY_ENGINE": "cpu",
                "JAX_PLATFORMS": "cpu",
                "PYTHONUNBUFFERED": "1",
            }
        )
        if flight_port:
            env["P_FLIGHT_PORT"] = str(flight_port)
        env.update(env_extra or {})
        # append: a re-spawned node (rolling restart, crash-recovery
        # scenarios) keeps its pre-kill log instead of truncating it
        log = open(log_path, "ab")
        log.write(f"--- spawn {name} mode={mode} port={port} ---\n".encode())
        log.flush()
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "parseable_tpu.server"],
                cwd=str(REPO_ROOT),
                env=env,
                stdout=log,
                stderr=subprocess.STDOUT,
            )
        finally:
            log.close()  # the child inherited the fd
        node = Node(proc, mode, port, log_path, flight_port=flight_port)
        self.nodes.append(node)
        return node

    def wait_live(self, node: Node, timeout: float = 90.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not node.alive():
                raise RuntimeError(
                    f"{node.mode} node died during boot; log tail:\n"
                    + node.log_path.read_text()[-2000:]
                )
            try:
                status, _ = http_json("GET", f"{node.url}/api/v1/liveness", timeout=2.0)
                if status == 200:
                    return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.25)
        raise TimeoutError(
            f"{node.mode} node on :{node.port} not live after {timeout}s; log tail:\n"
            + node.log_path.read_text()[-2000:]
        )

    def ingest(self, node: Node, stream: str, rows: list[dict]) -> None:
        status, _ = http_json(
            "POST",
            f"{node.url}/api/v1/ingest",
            rows,
            headers={"X-P-Stream": stream},
        )
        if status != 200:
            raise RuntimeError(f"ingest to :{node.port} failed: {status}")

    def query(
        self,
        node: Node,
        sql: str,
        start: str | None = None,
        end: str | None = None,
        timeout: float = 60.0,
    ) -> tuple[list[dict], dict]:
        """POST /api/v1/query with fields=true -> (records, stats)."""
        body: dict = {"query": sql, "fields": True}
        if start:
            body["startTime"] = start
        if end:
            body["endTime"] = end
        status, out = http_json("POST", f"{node.url}/api/v1/query", body, timeout=timeout)
        if status != 200 or out is None:
            raise RuntimeError(f"query on :{node.port} failed: {status} {out}")
        return out["records"], out.get("stats", {})

    def query_traced(
        self,
        node: Node,
        sql: str,
        start: str | None = None,
        end: str | None = None,
        timeout: float = 60.0,
    ) -> tuple[list[dict], dict, str]:
        """query() + the X-P-Trace-Id the server minted for this request."""
        body: dict = {"query": sql, "fields": True}
        if start:
            body["startTime"] = start
        if end:
            body["endTime"] = end
        status, out, headers = http_json_headers(
            "POST", f"{node.url}/api/v1/query", body, timeout=timeout
        )
        if status != 200 or out is None:
            raise RuntimeError(f"query on :{node.port} failed: {status} {out}")
        return out["records"], out.get("stats", {}), headers.get("X-P-Trace-Id", "")

    def cluster_trace(self, node: Node, trace_id: str, timeout: float = 30.0) -> dict:
        """GET the stitched cross-node span tree for one trace."""
        status, out = http_json(
            "GET", f"{node.url}/api/v1/cluster/trace/{trace_id}", timeout=timeout
        )
        if status != 200 or out is None:
            raise RuntimeError(f"cluster trace on :{node.port} failed: {status} {out}")
        return out

    def audit(
        self,
        node: Node,
        scope: str = "cluster",
        quiesce: bool = True,
        timeout: float = 60.0,
    ) -> dict:
        """Run the conservation-law audit and return its report."""
        url = (
            f"{node.url}/api/v1/cluster/audit"
            f"?scope={scope}&quiesce={'1' if quiesce else '0'}"
        )
        status, out = http_json("GET", url, timeout=timeout)
        if status != 200 or out is None:
            raise RuntimeError(f"audit on :{node.port} failed: {status} {out}")
        return out

    def log_tails(self, limit: int = 2000) -> str:
        """Per-node log tails, for attaching to failure reports."""
        chunks = []
        seen: set[Path] = set()
        for node in self.nodes:
            if node.log_path in seen:
                continue
            seen.add(node.log_path)
            try:
                text = node.log_path.read_text(errors="replace")[-limit:]
            except OSError as e:
                text = f"(log unreadable: {e})"
            chunks.append(f"--- {node.log_path.name} ({node.mode}:{node.port}) ---\n{text}")
        return "\n".join(chunks)

    def stop_all(self) -> None:
        for node in self.nodes:
            node.stop()
        self.nodes.clear()

    def __enter__(self) -> "ClusterHarness":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.nodes:
            # scenario failed: surface what every node was doing before
            # teardown destroys the processes (logs stay on disk under
            # workdir/logs/ either way)
            sys.stderr.write(
                f"\n[blackbox] scenario failed ({exc_type.__name__}); "
                f"node log tails:\n{self.log_tails()}\n"
            )
        self.stop_all()
