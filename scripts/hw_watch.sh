#!/bin/bash
# Poll the TPU; run the validation battery the moment it answers.
cd /root/repo
for i in $(seq 1 200); do
  if timeout 5400 python scripts/hw_validate.py >> scripts/hw_watch.log 2>&1; then
    echo "VALIDATION COMPLETE at $(date -u)" >> scripts/hw_watch.log
    exit 0
  fi
  rc=$?
  if [ "$rc" != "2" ]; then
    echo "validate rc=$rc at $(date -u) (partial results possible)" >> scripts/hw_watch.log
  fi
  sleep 120
done
echo "gave up after 200 probes" >> scripts/hw_watch.log
exit 1
