"""Partial-aggregate tables and their vectorized merge.

Two-phase aggregation's shared host half (reference: DataFusion's
partial/final hash-aggregate split, /root/reference/src/query/mod.rs:212-276):
each scanned block reduces to a *partial table* — group keys as `__g{i}`
columns plus `__cnt` (rows per group) and per-spec `__pac{si}` (non-null
input count), `__sum{si}`, `__min{si}`, `__max{si}` — and ONE pyarrow
group_by merges every partial at finalize. Both engines produce partials
(the TPU engine from dense device accumulators, the CPU engine from
per-block group_bys), so a 1M-group query costs one Arrow C++ hash
aggregation, never a per-group Python loop.
"""

from __future__ import annotations

from typing import Any

import pyarrow as pa
import pyarrow.compute as pc

# aggregate functions expressible in partial format (stddev/var/distinct
# need extra state and take the classic HashAggregator path)
PARTIALIZABLE_FUNCS = {"count_star", "count", "sum", "avg", "min", "max"}


def specs_partializable(specs) -> bool:
    return all(s.func in PARTIALIZABLE_FUNCS for s in specs)


def partial_from_block(table: pa.Table, group_exprs: list, specs: list) -> pa.Table | None:
    """CPU half: one block's partial aggregate via pyarrow group_by."""
    from parseable_tpu.query.executor import _arr, evaluate

    if table.num_rows == 0:
        return None
    cols: dict[str, Any] = {}
    key_names = []
    for i, g in enumerate(group_exprs):
        key_names.append(f"__g{i}")
        cols[f"__g{i}"] = _arr(evaluate(g, table), table)
    aggs: list[tuple] = [([], "count_all")]
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        cols[f"__a{si}"] = _arr(evaluate(spec.arg, table), table)
        aggs.append((f"__a{si}", "count"))
        if spec.func in ("sum", "avg"):
            aggs.append((f"__a{si}", "sum"))
        elif spec.func == "min":
            aggs.append((f"__a{si}", "min"))
        elif spec.func == "max":
            aggs.append((f"__a{si}", "max"))
    tmp = pa.table(cols) if cols else pa.table(
        {"__d": pa.nulls(table.num_rows, pa.int8())}
    )
    g = tmp.group_by(key_names, use_threads=False).aggregate(aggs)
    out: dict[str, Any] = {}
    for k in key_names:
        out[k] = g.column(k)
    out["__cnt"] = pc.cast(g.column("count_all"), pa.float64())
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        out[f"__pac{si}"] = pc.cast(g.column(f"__a{si}_count"), pa.float64())
        if spec.func in ("sum", "avg"):
            out[f"__sum{si}"] = pc.cast(g.column(f"__a{si}_sum"), pa.float64())
        elif spec.func == "min":
            out[f"__min{si}"] = g.column(f"__a{si}_min")
        elif spec.func == "max":
            out[f"__max{si}"] = g.column(f"__a{si}_max")
    return pa.table(out)


def merge_partials(partials: list[pa.Table], specs: list, nkeys: int) -> pa.Table:
    """Final half: merge partial tables -> interim (__g/__agg) table for
    finalize_from_interim. One vectorized group_by over all partials."""
    t = pa.concat_tables(partials, promote_options="permissive")
    keys = [f"__g{i}" for i in range(nkeys)]
    aggs: list[tuple] = [("__cnt", "sum")]
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        aggs.append((f"__pac{si}", "sum"))
        if spec.func in ("sum", "avg"):
            aggs.append((f"__sum{si}", "sum"))
        elif spec.func == "min":
            aggs.append((f"__min{si}", "min"))
        elif spec.func == "max":
            aggs.append((f"__max{si}", "max"))
    g = t.group_by(keys, use_threads=False).aggregate(aggs)
    cols: dict[str, Any] = {}
    for i in range(nkeys):
        cols[f"__g{i}"] = g.column(f"__g{i}")
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            cols[f"__agg{si}"] = pc.cast(g.column("__cnt_sum"), pa.int64(), safe=False)
            continue
        pacv = g.column(f"__pac{si}_sum")
        if spec.func == "count":
            cols[f"__agg{si}"] = pc.cast(pacv, pa.int64(), safe=False)
        elif spec.func in ("sum", "avg"):
            s = g.column(f"__sum{si}_sum")
            seen = pc.greater(pacv, 0)
            val = pc.divide(s, pacv) if spec.func == "avg" else s
            cols[f"__agg{si}"] = pc.if_else(seen, val, pa.scalar(None, pa.float64()))
        elif spec.func == "min":
            cols[f"__agg{si}"] = g.column(f"__min{si}_min")
        elif spec.func == "max":
            cols[f"__agg{si}"] = g.column(f"__max{si}_max")
    return pa.table(cols)
