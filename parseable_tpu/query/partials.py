"""Partial-aggregate tables and their vectorized merge.

Two-phase aggregation's shared host half (reference: DataFusion's
partial/final hash-aggregate split, /root/reference/src/query/mod.rs:212-276):
each scanned block reduces to a *partial table* — group keys as `__g{i}`
columns plus `__cnt` (rows per group) and per-spec `__pac{si}` (non-null
input count), `__sum{si}`, `__min{si}`, `__max{si}` — and ONE pyarrow
group_by merges every partial at finalize. Both engines produce partials
(the TPU engine from dense device accumulators, the CPU engine from
per-block group_bys), so a 1M-group query costs one Arrow C++ hash
aggregation, never a per-group Python loop.

Fast path: the block phase dictionary-encodes each key once and groups on
a single combined int64 code — multi-column row hashing is the expensive
part of a high-cardinality group_by; one int key is ~5x cheaper than two
string keys at 1M groups. The merge unifies per-block dictionaries into
global codes (index_in over dictionaries — dictionary-sized work, never
row-count-sized) and groups on one int64 again. String keys stay
dictionary-typed in the interim table, so `GROUP BY path, host ORDER BY s
DESC LIMIT 10` over millions of groups never materializes millions of
strings — only rows that survive LIMIT decode. Anything the fast path
can't express (combined code overflow, un-encodable key types) falls back
to the legacy multi-column group_by.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from parseable_tpu.utils.metrics import QUERY_RESULT_CACHE, QUERY_RESULT_CACHE_BYTES

# aggregate functions expressible in partial format: stddev/var carry
# (count, sum, sum-of-squares) columns; percentile/distinct need sketch /
# set state and take the classic HashAggregator path
PARTIALIZABLE_FUNCS = {
    "count_star", "count", "sum", "avg", "min", "max", "stddev", "var",
}

_MAX_COMBINED = 1 << 62  # combined-code capacity guard


def specs_partializable(specs) -> bool:
    return all(s.func in PARTIALIZABLE_FUNCS for s in specs)


class _FastPathUnavailable(Exception):
    pass


# --------------------------------------------------------------------------
# partial-aggregate result cache


class PartialResultCache:
    """LRU cache of *finalized partials* — the merged interim (__g/__agg)
    table an aggregate produces after consuming its whole scan — keyed on
    (stream, manifest-set fingerprint, plan fingerprint).

    A repeated `GROUP BY` over an unchanged snapshot then skips the scan
    entirely: the session re-runs only HAVING / projection / ORDER BY /
    LIMIT over the cached interim. Correctness comes from the key: the
    manifest-set fingerprint covers every (path, size, rows) the scan
    would read, so any snapshot commit, retention sweep, or compaction
    changes the key. update_snapshot additionally evicts the stream's
    entries eagerly (invalidate_stream) so stale interims don't squat on
    the byte budget. Arrow tables are immutable, so entries are shared
    without copies. Thread-safe: queries hit it from worker threads."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(1, max_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, pa.Table] = OrderedDict()  # guarded-by: self._lock
        self._bytes = 0  # guarded-by: self._lock
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock

    def get(self, key: tuple) -> pa.Table | None:
        with self._lock:
            table = self._entries.get(key)
            if table is None:
                self.misses += 1
            else:
                self.hits += 1
                self._entries.move_to_end(key)
        QUERY_RESULT_CACHE.labels("hit" if table is not None else "miss").inc()
        return table

    def put(self, key: tuple, table: pa.Table) -> None:
        size = table.nbytes
        if size > self.max_bytes:
            return  # one oversized interim must not wipe the whole cache
        with self._lock:
            prev = self._entries.pop(key, None)
            if prev is not None:
                self._bytes -= prev.nbytes
            self._entries[key] = table
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
            QUERY_RESULT_CACHE_BYTES.set(self._bytes)

    def invalidate_stream(self, stream: str) -> int:
        """Evict every entry for `stream` (snapshot commit / retention)."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == stream]
            for k in doomed:
                self._bytes -= self._entries.pop(k).nbytes
            QUERY_RESULT_CACHE_BYTES.set(self._bytes)
        return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            QUERY_RESULT_CACHE_BYTES.set(0)


_RESULT_CACHE: PartialResultCache | None = None
_RESULT_CACHE_LOCK = threading.Lock()


def get_result_cache(options=None) -> PartialResultCache | None:
    """Process-wide result cache sized by P_QUERY_RESULT_CACHE_BYTES
    (0 disables). Re-roots when the configured budget changes."""
    global _RESULT_CACHE
    budget = getattr(options, "query_result_cache_bytes", 64 * 1024 * 1024)
    if budget <= 0:
        return None
    with _RESULT_CACHE_LOCK:
        if _RESULT_CACHE is None or _RESULT_CACHE.max_bytes != budget:
            _RESULT_CACHE = PartialResultCache(budget)
        return _RESULT_CACHE


def invalidate_result_cache(stream: str) -> int:
    """Snapshot-commit hook (core.update_snapshot): drop the stream's
    cached interims the moment the manifest set they were built from is
    superseded."""
    with _RESULT_CACHE_LOCK:
        cache = _RESULT_CACHE
    return cache.invalidate_stream(stream) if cache is not None else 0


def manifest_fingerprint(files) -> str:
    """Content fingerprint of a scan's manifest set: (path, size, rows) of
    every file the pruned scan would read. Any upload, compaction, or
    retention change to the set changes the digest."""
    h = hashlib.blake2b(digest_size=16)
    for f in sorted(files, key=lambda f: f.file_path):
        h.update(f"{f.file_path}|{f.file_size}|{f.num_rows}\n".encode())
    return h.hexdigest()


def plan_fingerprint(lp, engine: str) -> str:
    """Semantic fingerprint of what the interim depends on: the full
    statement (WHERE/GROUP BY/aggregates), the effective time bounds, the
    projected columns, and the engine (device partial sums are f32 per
    block — close, but not bit-identical to the CPU's f64)."""
    from parseable_tpu.query import sql as S

    cols = sorted(lp.needed_columns) if lp.needed_columns is not None else ["*"]
    text = "\x1f".join(
        [
            S.format_statement(lp.select),
            str(lp.time_bounds.low),
            str(lp.time_bounds.high),
            ",".join(cols),
            engine,
        ]
    )
    return hashlib.blake2b(text.encode(), digest_size=16).hexdigest()


def _encode_key(arr: pa.ChunkedArray | pa.Array) -> tuple[np.ndarray, pa.Array]:
    """One key column -> (codes int64, dict); null rows code len(dict)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    try:
        denc = arr if pa.types.is_dictionary(arr.type) else pc.dictionary_encode(arr)
    except pa.ArrowNotImplementedError as e:
        raise _FastPathUnavailable(str(e)) from e
    if isinstance(denc, pa.ChunkedArray):
        denc = denc.combine_chunks()
    dictionary = denc.dictionary
    idx = denc.indices
    codes = pc.fill_null(idx, 0).to_numpy(zero_copy_only=False).astype(np.int64)
    if idx.null_count:
        codes = codes.copy()
        codes[~np.asarray(idx.is_valid())] = len(dictionary)
    if dictionary.null_count:
        # null VALUES inside a dictionary (TPU partials use a null slot)
        # must collapse into the same null code as masked indices, or the
        # merge would keep two unmergeable null groups
        valid = np.asarray(dictionary.is_valid())
        clean = dictionary.drop_null()
        lut = np.concatenate(
            [
                np.where(valid, np.cumsum(valid, dtype=np.int64) - 1, len(clean)),
                [len(clean)],
            ]
        )
        codes = lut[codes]
        dictionary = clean
    return codes, dictionary


def _combine_codes(codes_list: list[np.ndarray], sizes: list[int]) -> np.ndarray:
    """codes -> single int64, LAST key least-significant."""
    prod = 1
    for s in sizes:
        prod *= s
        if prod > _MAX_COMBINED:
            raise _FastPathUnavailable("combined group-code space exceeds int64")
    combined = codes_list[0]
    for codes, size in zip(codes_list[1:], sizes[1:]):
        combined = combined * size + codes
    return combined


def _split_codes(gcodes: np.ndarray, sizes: list[int]) -> list[np.ndarray]:
    n = len(sizes)
    cols: list[np.ndarray] = [None] * n  # type: ignore[list-item]
    rem = gcodes
    for i in range(n - 1, 0, -1):
        cols[i] = rem % sizes[i]
        rem = rem // sizes[i]
    cols[0] = rem
    return cols


def _group_codes_to_key_arrays(
    gcodes: np.ndarray, dicts: list[pa.Array], sizes: list[int]
) -> list[pa.Array]:
    """Combined group codes -> per-key arrays. String/binary keys come back
    dictionary-typed (no value materialization); other types decode via one
    take per key (group-count sized, not row-count sized)."""
    out: list[pa.Array] = []
    for code, d in zip(_split_codes(gcodes, sizes), dicts):
        if len(d) == 0:  # all-null key
            out.append(pa.nulls(len(code), d.type))
            continue
        null_slot = len(d)
        mask = code == null_slot
        idx = pa.array(np.where(mask, 0, code).astype(np.int32), mask=mask)
        dict_arr = pa.DictionaryArray.from_arrays(idx, d)
        if (
            pa.types.is_string(d.type)
            or pa.types.is_large_string(d.type)
            or pa.types.is_binary(d.type)
        ):
            out.append(dict_arr)
        else:
            out.append(dict_arr.cast(d.type))
    return out


def decode_dictionary_columns(table: pa.Table) -> pa.Table:
    """Materialize dictionary-typed columns as plain values (fallback for
    arrow kernels without dictionary support)."""
    cols = []
    changed = False
    for col in table.columns:
        if pa.types.is_dictionary(col.type):
            cols.append(col.cast(col.type.value_type))
            changed = True
        else:
            cols.append(col)
    if not changed:
        return table
    return pa.table(dict(zip(table.column_names, cols)))


def _agg_plan(specs: list) -> list[tuple]:
    aggs: list[tuple] = [([], "count_all")]
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        aggs.append((f"__a{si}", "count"))
        if spec.func in ("sum", "avg"):
            aggs.append((f"__a{si}", "sum"))
        elif spec.func in ("stddev", "var"):
            aggs.append((f"__a{si}", "sum"))
            aggs.append((f"__asq{si}", "sum"))
        elif spec.func == "min":
            aggs.append((f"__a{si}", "min"))
        elif spec.func == "max":
            aggs.append((f"__a{si}", "max"))
    return aggs


def _partial_out(g: pa.Table, specs: list) -> dict[str, Any]:
    out: dict[str, Any] = {"__cnt": pc.cast(g.column("count_all"), pa.float64())}
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        out[f"__pac{si}"] = pc.cast(g.column(f"__a{si}_count"), pa.float64())
        if spec.func in ("sum", "avg"):
            out[f"__sum{si}"] = pc.cast(g.column(f"__a{si}_sum"), pa.float64())
        elif spec.func in ("stddev", "var"):
            out[f"__sum{si}"] = pc.cast(g.column(f"__a{si}_sum"), pa.float64())
            out[f"__sumsq{si}"] = pc.cast(g.column(f"__asq{si}_sum"), pa.float64())
        elif spec.func == "min":
            out[f"__min{si}"] = g.column(f"__a{si}_min")
        elif spec.func == "max":
            out[f"__max{si}"] = g.column(f"__a{si}_max")
    return out


def partial_from_block(table: pa.Table, group_exprs: list, specs: list) -> pa.Table | None:
    """CPU half: one block's partial aggregate via pyarrow group_by."""
    from parseable_tpu.query.executor import _arr, evaluate

    if table.num_rows == 0:
        return None
    key_arrays = [_arr(evaluate(g, table), table) for g in group_exprs]
    agg_cols: dict[str, Any] = {}
    for si, spec in enumerate(specs):
        if spec.func != "count_star":
            agg_cols[f"__a{si}"] = _arr(evaluate(spec.arg, table), table)
        if spec.func in ("stddev", "var"):
            # float64 before squaring: int64 squares wrap silently
            fv = pc.cast(agg_cols[f"__a{si}"], pa.float64(), safe=False)
            agg_cols[f"__asq{si}"] = pc.multiply(fv, fv)

    try:
        codes_list, dicts, sizes = [], [], []
        for a in key_arrays:
            codes, d = _encode_key(a)
            codes_list.append(codes)
            dicts.append(d)
            sizes.append(len(d) + 1)  # +1: the null slot
        combined = _combine_codes(codes_list, sizes)
        tmp = pa.table({"__k": pa.array(combined), **agg_cols})
        g = tmp.group_by(["__k"], use_threads=False).aggregate(_agg_plan(specs))
        gcodes = g.column("__k").to_numpy(zero_copy_only=False)
        out: dict[str, Any] = {}
        for i, arr in enumerate(_group_codes_to_key_arrays(gcodes, dicts, sizes)):
            out[f"__g{i}"] = arr
        out.update(_partial_out(g, specs))
        return pa.table(out)
    except _FastPathUnavailable:
        pass

    # legacy: group on the key columns directly
    key_names = [f"__g{i}" for i in range(len(key_arrays))]
    cols = dict(zip(key_names, key_arrays))
    cols.update(agg_cols)
    tmp = pa.table(cols) if cols else pa.table({"__d": pa.nulls(table.num_rows, pa.int8())})
    g = tmp.group_by(key_names, use_threads=False).aggregate(_agg_plan(specs))
    out = {k: g.column(k) for k in key_names}
    out.update(_partial_out(g, specs))
    return pa.table(out)


def _global_codes(
    partials: list[pa.Table], key: str
) -> tuple[list[np.ndarray], pa.Array]:
    """Unify one key column's per-partial dictionaries into global codes
    (null -> -1). index_in runs over dictionaries, never over group rows."""
    global_vals: pa.Array | None = None
    pending: list[tuple[np.ndarray, pa.Array]] = []
    for t in partials:
        codes, d = _encode_key(t.column(key))
        codes = np.where(codes == len(d), np.int64(-1), codes)
        pending.append((codes, d))
        if global_vals is None:
            global_vals = d
        else:
            if len(d) and d.type != global_vals.type:
                try:
                    if len(global_vals) == 0:
                        global_vals = global_vals.cast(d.type)
                    else:
                        d = d.cast(global_vals.type)
                        pending[-1] = (codes, d)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
                    # incompatible-but-promotable key types (int64 block vs
                    # float64 block): the legacy merge promotes via
                    # concat_tables(permissive)
                    raise _FastPathUnavailable(str(e)) from e
            if len(d):
                lut = pc.index_in(d, global_vals)
                if lut.null_count:
                    new_vals = d.filter(pc.is_null(lut))
                    global_vals = pa.concat_arrays(
                        [global_vals, new_vals.cast(global_vals.type)]
                    )
    assert global_vals is not None
    per_partial: list[np.ndarray] = []
    for codes, d in pending:
        if len(d) == 0:
            per_partial.append(codes)
            continue
        lut = (
            pc.index_in(d.cast(global_vals.type), global_vals)
            .to_numpy(zero_copy_only=False)
            .astype(np.int64)
        )
        per_partial.append(
            np.where(codes < 0, np.int64(-1), lut[np.maximum(codes, 0)])
        )
    return per_partial, global_vals


def _merge_aggs(specs: list) -> list[tuple]:
    aggs: list[tuple] = [("__cnt", "sum")]
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        aggs.append((f"__pac{si}", "sum"))
        if spec.func in ("sum", "avg"):
            aggs.append((f"__sum{si}", "sum"))
        elif spec.func in ("stddev", "var"):
            aggs.append((f"__sum{si}", "sum"))
            aggs.append((f"__sumsq{si}", "sum"))
        elif spec.func == "min":
            aggs.append((f"__min{si}", "min"))
        elif spec.func == "max":
            aggs.append((f"__max{si}", "max"))
    return aggs


def _merge_out(g: pa.Table, specs: list) -> dict[str, Any]:
    cols: dict[str, Any] = {}
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            cols[f"__agg{si}"] = pc.cast(g.column("__cnt_sum"), pa.int64(), safe=False)
            continue
        pacv = g.column(f"__pac{si}_sum")
        if spec.func == "count":
            cols[f"__agg{si}"] = pc.cast(pacv, pa.int64(), safe=False)
        elif spec.func in ("sum", "avg"):
            s = g.column(f"__sum{si}_sum")
            seen = pc.greater(pacv, 0)
            val = pc.divide(s, pacv) if spec.func == "avg" else s
            cols[f"__agg{si}"] = pc.if_else(seen, val, pa.scalar(None, pa.float64()))
        elif spec.func in ("stddev", "var"):
            # sample variance (n-1 denominator, DataFusion semantics);
            # numpy here: masked divides are awkward in pa.compute
            n = np.asarray(pc.cast(pacv, pa.float64()).to_numpy(zero_copy_only=False))
            s = np.asarray(
                pc.cast(pc.fill_null(g.column(f"__sum{si}_sum"), 0.0), pa.float64())
                .to_numpy(zero_copy_only=False)
            )
            sq = np.asarray(
                pc.cast(pc.fill_null(g.column(f"__sumsq{si}_sum"), 0.0), pa.float64())
                .to_numpy(zero_copy_only=False)
            )
            ok = n >= 2
            var = np.divide(
                sq - np.divide(s * s, n, out=np.zeros_like(s), where=ok),
                n - 1,
                out=np.zeros_like(s),
                where=ok,
            )
            var = np.maximum(var, 0.0)  # guard f.p. negatives
            val = np.sqrt(var) if spec.func == "stddev" else var
            cols[f"__agg{si}"] = pa.array(val, mask=~ok)
        elif spec.func == "min":
            cols[f"__agg{si}"] = g.column(f"__min{si}_min")
        elif spec.func == "max":
            cols[f"__agg{si}"] = g.column(f"__max{si}_max")
    return cols


def _combine_out(g: pa.Table, specs: list) -> dict[str, Any]:
    """Re-emit the merged group table in PARTIAL format (``__cnt``/``__pac``/
    ``__sum``/``__sumsq``/``__min``/``__max``) instead of finalized ``__agg``
    slots: a per-node reduction that stays mergeable. Finalized avg/stddev
    can't be re-merged across nodes (an avg of avgs weights nodes, not
    rows), so distributed pushdown ships THIS shape over the wire and the
    querier's merge_partials treats each peer's table as one more block."""
    cols: dict[str, Any] = {"__cnt": g.column("__cnt_sum")}
    for si, spec in enumerate(specs):
        if spec.func == "count_star":
            continue
        cols[f"__pac{si}"] = g.column(f"__pac{si}_sum")
        if spec.func in ("sum", "avg"):
            cols[f"__sum{si}"] = g.column(f"__sum{si}_sum")
        elif spec.func in ("stddev", "var"):
            cols[f"__sum{si}"] = g.column(f"__sum{si}_sum")
            cols[f"__sumsq{si}"] = g.column(f"__sumsq{si}_sum")
        elif spec.func == "min":
            cols[f"__min{si}"] = g.column(f"__min{si}_min")
        elif spec.func == "max":
            cols[f"__max{si}"] = g.column(f"__max{si}_max")
    return cols


def merge_partials(partials: list[pa.Table], specs: list, nkeys: int) -> pa.Table:
    """Final half: merge partial tables -> interim (__g/__agg) table for
    finalize_from_interim."""
    return _merge_partial_tables(partials, specs, nkeys, _merge_out)


def combine_partials(partials: list[pa.Table], specs: list, nkeys: int) -> pa.Table:
    """Node-local reduction for distributed pushdown: merge this node's
    per-block partials into ONE partial-format table (same columns as
    partial_from_block output) that the querier can merge again. Keeps
    avg/stddev/var exact — the carried state is (count, sum[, sumsq])."""
    return _merge_partial_tables(partials, specs, nkeys, _combine_out)


def _merge_partial_tables(
    partials: list[pa.Table], specs: list, nkeys: int, out_fn
) -> pa.Table:
    non_key = [
        c
        for t in partials
        for c in t.column_names
        if not c.startswith("__g")
    ]
    non_key = list(dict.fromkeys(non_key))

    if nkeys:
        try:
            dicts: list[pa.Array] = []
            sizes: list[int] = []
            per_key_codes: list[list[np.ndarray]] = []
            for i in range(nkeys):
                codes_per_partial, gdict = _global_codes(partials, f"__g{i}")
                per_key_codes.append(codes_per_partial)
                dicts.append(gdict)
                sizes.append(len(gdict) + 1)
            prod = 1
            for s in sizes:
                prod *= s
                if prod > _MAX_COMBINED:
                    raise _FastPathUnavailable("combined group-code space exceeds int64")
            stripped = []
            for pi, t in enumerate(partials):
                codes_list = [
                    np.where(
                        per_key_codes[ki][pi] < 0,
                        np.int64(len(dicts[ki])),
                        per_key_codes[ki][pi],
                    )
                    for ki in range(nkeys)
                ]
                combined = _combine_codes(codes_list, sizes)
                keep = {c: t.column(c) for c in non_key if c in t.column_names}
                keep["__k"] = pa.array(combined)
                stripped.append(pa.table(keep))
            t = pa.concat_tables(stripped, promote_options="permissive")
            g = t.group_by(["__k"], use_threads=False).aggregate(_merge_aggs(specs))
            gcodes = g.column("__k").to_numpy(zero_copy_only=False)
            cols: dict[str, Any] = {}
            for i, arr in enumerate(_group_codes_to_key_arrays(gcodes, dicts, sizes)):
                cols[f"__g{i}"] = arr
            cols.update(out_fn(g, specs))
            return pa.table(cols)
        except _FastPathUnavailable:
            pass

    # legacy: group on the key columns directly (decoded to plain values)
    t = pa.concat_tables(
        [decode_dictionary_columns(p) for p in partials],
        promote_options="permissive",
    )
    keys = [f"__g{i}" for i in range(nkeys)]
    g = t.group_by(keys, use_threads=False).aggregate(_merge_aggs(specs))
    cols = {f"__g{i}": g.column(f"__g{i}") for i in range(nkeys)}
    cols.update(out_fn(g, specs))
    return pa.table(cols)
