"""Query session: SQL + time range -> plan -> scan -> execute -> JSON rows.

Parity target (reference: src/query/mod.rs QUERY_SESSION / Query::execute,
handlers/http/query.rs::query): API callers pass SQL plus startTime/endTime;
time filters are injected into the plan exactly like the reference's
`final_logical_plan`, the count(*) fast path is served from manifest row
counts, and everything else runs on the selected engine (tpu|cpu).
"""

from __future__ import annotations

import copy as _copy
import logging
import threading
import time as _time
from collections import OrderedDict
from dataclasses import dataclass, field
from datetime import UTC
from typing import Any

import pyarrow as pa

from parseable_tpu.core import Parseable
from parseable_tpu.query import sql as S
from parseable_tpu.query.executor import QueryExecutor
from parseable_tpu.query.planner import LogicalPlan, TimeBounds, plan as build_plan
from parseable_tpu.query.provider import StreamScan
from parseable_tpu.utils.arrowutil import record_batches_to_json
from parseable_tpu.utils.metrics import (
    QUERY_CACHE_HIT,
    QUERY_EXECUTE_TIME,
    QUERY_PLAN_CACHE,
)
from parseable_tpu.utils.timeutil import TimeRange

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# plan/parse cache


class PlanCache:
    """Thread-safe LRU over parsed ASTs and logical plans.

    Two entry kinds share the store: ("ast", sql) -> pristine parsed
    Select, and ("plan", sql, stream, schema_fp) -> the LogicalPlan as
    built by build_plan, before any per-request state (API time bounds,
    deadline, schema hint) is applied. Entries are stored AND returned as
    deepcopies — planning and execution mutate both structures freely, so
    the cached originals must never be reachable from a running query.

    Invalidation: the schema fingerprint in the key makes a schema change
    miss naturally; commit_schema additionally calls invalidate_stream so
    superseded plans don't squat on LRU slots."""

    def __init__(self, max_entries: int):
        self.max_entries = max(1, max_entries)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, Any] = OrderedDict()  # guarded-by: self._lock
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock

    def get(self, key: tuple):
        with self._lock:
            val = self._entries.get(key)
            if val is None:
                self.misses += 1
                return None
            self.hits += 1
            self._entries.move_to_end(key)
        return _copy.deepcopy(val)

    def put(self, key: tuple, val) -> None:
        val = _copy.deepcopy(val)
        with self._lock:
            self._entries[key] = val
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def invalidate_stream(self, stream: str) -> int:
        with self._lock:
            doomed = [
                k for k in self._entries if k[0] == "plan" and k[2] == stream
            ]
            for k in doomed:
                del self._entries[k]
        return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_PLAN_CACHE: PlanCache | None = None
_PLAN_CACHE_LOCK = threading.Lock()


def get_plan_cache(options=None) -> PlanCache | None:
    """Process-wide plan/parse cache sized by P_QUERY_PLAN_CACHE
    (0 disables). Re-roots when the configured capacity changes."""
    global _PLAN_CACHE
    entries = getattr(options, "query_plan_cache_entries", 256)
    if entries <= 0:
        return None
    with _PLAN_CACHE_LOCK:
        if _PLAN_CACHE is None or _PLAN_CACHE.max_entries != entries:
            _PLAN_CACHE = PlanCache(entries)
        return _PLAN_CACHE


def invalidate_plan_cache(stream: str) -> int:
    """Schema-change hook (core.commit_schema): evict the stream's plans.
    The parsed-AST entries stay — SQL text doesn't depend on schema."""
    with _PLAN_CACHE_LOCK:
        cache = _PLAN_CACHE
    return cache.invalidate_stream(stream) if cache is not None else 0


def _is_composite(select: S.Select) -> bool:
    """Joins/CTEs/unions/subqueries need the multi-table planner (and full
    materialization before streaming)."""
    return bool(select.ctes or select.set_ops or select.joins) or any(
        S.contains_subquery(x)
        for x in [select.where, select.having, *(i.expr for i in select.items)]
    )


def _referenced_streams(select: S.Select) -> set[str]:
    """Every physical stream the statement touches (CTE names excluded):
    main table, joins, union branches, CTE bodies, subqueries."""
    out: set[str] = set()
    cte_names: set[str] = set()

    def walk_expr(e) -> None:
        if e is None:
            return
        if isinstance(e, S.Subquery):
            walk(e.select)
            return
        for child in getattr(e, "__dict__", {}).values():
            if isinstance(child, S.Expr):
                walk_expr(child)
            elif isinstance(child, list):
                for c in child:
                    if isinstance(c, S.Expr):
                        walk_expr(c)
                    elif isinstance(c, S.OrderItem):
                        walk_expr(c.expr)
                    elif isinstance(c, tuple):
                        for cc in c:
                            if isinstance(cc, S.Expr):
                                walk_expr(cc)

    def walk(s: S.Select) -> None:
        for name, sub in s.ctes.items():
            cte_names.add(name)
            walk(sub)
        if s.table:
            out.add(s.table)
        for j in s.joins:
            out.add(j.table)
            walk_expr(j.on)
        for _, branch in s.set_ops:
            walk(branch)
        for x in [s.where, s.having, *(i.expr for i in s.items)]:
            walk_expr(x)

    walk(select)
    return out - cte_names


class QueryError(ValueError):
    pass


def collect_streams(select: S.Select) -> set[str]:
    """Every stream a query touches: FROM, JOINs, and subqueries."""
    out: set[str] = set()
    if select.table:
        out.add(select.table)
    for j in select.joins:
        out.add(j.table)

    def walk(e: S.Expr | None) -> None:
        if e is None:
            return
        if isinstance(e, S.Subquery):
            out.update(collect_streams(e.select))
            return
        for attr in ("left", "right", "operand", "expr", "low", "high", "else_expr"):
            v = getattr(e, attr, None)
            if isinstance(v, S.Expr):
                walk(v)
        for lst_attr in ("items", "args"):
            for v in getattr(e, lst_attr, []) or []:
                if isinstance(v, S.Expr):
                    walk(v)
        for w, t in getattr(e, "whens", []) or []:
            walk(w)
            walk(t)

    walk(select.where)
    walk(select.having)
    for i in select.items:
        walk(i.expr)
    for _, branch in select.set_ops:
        out.update(collect_streams(branch))
    cte_names = set(select.ctes)
    for cte_sel in select.ctes.values():
        out.update(collect_streams(cte_sel))
    return out - cte_names


def _qualified_refs(e: S.Expr | None) -> list[S.Column]:
    """All Column nodes (qualified or not) in an expression tree."""
    out: list[S.Column] = []
    if e is None:
        return out

    def walk(x) -> None:
        if isinstance(x, S.Column):
            out.append(x)
            return
        if isinstance(x, S.Subquery):
            return
        for attr in ("left", "right", "operand", "expr", "low", "high", "else_expr"):
            v = getattr(x, attr, None)
            if isinstance(v, S.Expr):
                walk(v)
        for lst_attr in ("items", "args"):
            for v in getattr(x, lst_attr, []) or []:
                if isinstance(v, S.Expr):
                    walk(v)
        for w, t in getattr(x, "whens", []) or []:
            walk(w)
            walk(t)

    walk(e)
    return out


@dataclass
class QueryResult:
    table: pa.Table
    fields: list[str]
    stats: dict[str, Any] = field(default_factory=dict)

    def to_json_rows(self) -> list[dict]:
        return record_batches_to_json(self.table.to_batches())


class _TimedIter:
    """Wraps a scan iterator, accumulating the wall time spent producing
    blocks — the scan share of the EXPLAIN ANALYZE stage breakdown (the
    executor pulls lazily, so scan and execute interleave; time inside
    next() is scan/decode, the remainder is operator work)."""

    def __init__(self, it):
        self._it = iter(it)
        self.seconds = 0.0
        self.blocks = 0

    def __iter__(self):
        return self

    def __next__(self):
        t0 = _time.perf_counter()
        try:
            return next(self._it)
        finally:
            self.seconds += _time.perf_counter() - t0
            self.blocks += 1

    def close(self) -> None:
        """Close the wrapped scan generator — cancels the parallel fetch
        pool deterministically (LIMIT early-exit, timeout, error) instead
        of waiting for GC to finalize the suspended generator."""
        close = getattr(self._it, "close", None)
        if close is not None:
            close()


class QuerySession:
    """One engine-backed session over a Parseable instance."""

    def __init__(self, parseable: Parseable, engine: str | None = None):
        self.p = parseable
        self.engine = engine or parseable.options.query_engine

    def resolve_stream(self, name: str) -> None:
        """Make sure the stream exists locally, loading from storage when a
        querier sees it for the first time (query.rs:558-618)."""
        if self.p.streams.get(name) is None:
            self.p.load_streams_from_storage()
        if self.p.streams.get(name) is None:
            raise QueryError(f"stream {name!r} does not exist")

    def query(
        self,
        sql_text: str,
        start_time: str | None = None,
        end_time: str | None = None,
        allowed_streams: set[str] | None = None,
    ) -> QueryResult:
        """Run SQL. `allowed_streams` (None = unrestricted) is the caller's
        RBAC scope, enforced on the *resolved* plan before any execution so
        unauthorized streams neither run nor leak through error messages."""
        t0 = _time.monotonic()
        from parseable_tpu.utils.telemetry import TRACER

        with TRACER.span("query", engine=self.engine) as sp:
            self._plan_cache_state = None
            self._result_cache_state = None
            tp = _time.perf_counter()
            select = self._parse_cached(sql_text)
            self._parse_ms = round((_time.perf_counter() - tp) * 1000, 3)
            self._sql_text = sql_text
            result = self._query_ast(
                select, start_time, end_time, allowed_streams, t0, sql_key=sql_text
            )
            sp["stream"] = ",".join(sorted(_referenced_streams(select))) or "?"
            sp["rows"] = result.table.num_rows
            return result

    def _parse_cached(self, sql_text: str) -> S.Select:
        """parse_sql through the plan/parse cache: the cached AST is
        pristine (stored before any planning mutation) and handed out as a
        deepcopy, so repeated dashboard statements skip the parser."""
        cache = get_plan_cache(self.p.options)
        if cache is None:
            return S.parse_sql(sql_text)
        cached = cache.get(("ast", sql_text))
        if cached is not None:
            return cached
        select = S.parse_sql(sql_text)
        cache.put(("ast", sql_text), select)
        return select

    def _schema_fingerprint(self, stream: str) -> int | None:
        """Fingerprint of the stream's committed schema — part of every
        plan-cache key so a schema change can never serve a stale plan."""
        s = self.p.streams.get(stream)
        if s is None or not s.metadata.schema:
            return None
        return hash(tuple((n, str(f.type)) for n, f in s.metadata.schema.items()))

    def _query_ast(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float | None = None,
        sql_key: str | None = None,
    ) -> QueryResult:
        t0 = t0 if t0 is not None else _time.monotonic()
        if select.explain:
            return self._explain(select, start_time, end_time, allowed_streams, t0)
        if select.ctes:
            return self._query_with_ctes(select, start_time, end_time, allowed_streams, t0)
        if select.set_ops:
            return self._query_union(select, start_time, end_time, allowed_streams, t0)
        has_sub = any(
            S.contains_subquery(x)
            for x in [select.where, select.having, *(i.expr for i in select.items)]
        )
        if select.joins or has_sub:
            return self._query_multi(select, start_time, end_time, allowed_streams, t0)
        cte_tables = getattr(self, "_cte_tables", None)
        if cte_tables is not None and select.table in cte_tables:
            return self._query_cte_table(select, cte_tables[select.table], t0)
        tplan = _time.perf_counter()
        lp = self._plan_ast(
            select, start_time, end_time, allowed_streams, t0, sql_key=sql_key
        )
        plan_ms = round((_time.perf_counter() - tplan) * 1000, 3)

        scan = StreamScan(
            self.p,
            lp,
            hot_tier_dir=self._hot_dir(lp.stream),
            use_hot_stubs=self.engine == "tpu" and lp.is_aggregate,
        )
        texec = _time.perf_counter()
        self._fanout_stats = None
        # pushdown ships the ORIGINAL statement text to peers (they re-plan
        # it locally); only the top-level single-statement path has it —
        # CTE bodies / resolved-subquery selects executed through here are
        # derived statements with no faithful text, so they stay central
        self._exec_sql = sql_key
        result, timer = self._execute(lp, scan)
        exec_s = _time.perf_counter() - texec
        elapsed = _time.monotonic() - t0
        QUERY_EXECUTE_TIME.labels(lp.stream).observe(elapsed)
        result.stats.update(
            {
                "elapsed_secs": round(elapsed, 6),
                "engine": self.engine,
                "files_total": scan.stats.files_total,
                "files_pruned": scan.stats.files_pruned,
                "bytes_scanned": scan.stats.bytes_scanned,
                "rows_scanned": scan.stats.rows_scanned,
                # nonzero = files dropped by read failures (partial result)
                "scan_errors": scan.stats.scan_errors,
                "bytes_saved_by_projection": scan.stats.bytes_saved_by_projection,
                # EXPLAIN ANALYZE-style per-stage wall-time breakdown;
                # scan = time inside the block iterator, execute = the rest
                "stages": {
                    "parse_ms": getattr(self, "_parse_ms", None),
                    "plan_ms": plan_ms,
                    "scan_ms": round(timer.seconds * 1000, 3),
                    "execute_ms": round(max(exec_s - timer.seconds, 0.0) * 1000, 3),
                    "total_ms": round(elapsed * 1000, 3),
                    "bytes_saved_by_projection": scan.stats.bytes_saved_by_projection,
                    # cross-query contention: time this query's scan tasks
                    # spent queued behind other queries on the shared pool
                    "sched_wait_ms": round(scan.stats.sched_wait_seconds * 1000, 3),
                    "plan_cache": getattr(self, "_plan_cache_state", None),
                    "result_cache": getattr(self, "_result_cache_state", None),
                    # distributed data plane: pushdown scatter-gather
                    # breakdown (per-peer latency/bytes, hedges, fallbacks)
                    # or the central pull's raw fan-in accounting
                    "fanout": self._fanout_stage(scan),
                    # tiering state for this process + this query's prefetch
                    # outcome (None on the CPU engine — no device tier)
                    "hotset": self._hotset_stage(result.stats.get("device_routes")),
                    # program-cache traffic: XLA builds vs cache hits this
                    # query, plus rebuilds of an already-built key — the
                    # dlint tripwire's budget holds "recompiles" at 0
                    # (None on the CPU engine — nothing jits)
                    "programs": self._programs_stage(
                        result.stats.get("device_routes")
                    ),
                },
            }
        )
        self._maybe_log_slow(select, elapsed, result.stats)
        return result

    def _fanout_stage(self, scan: StreamScan) -> dict | None:
        """stats.stages.fanout: the distributed data plane's share of the
        query — pushdown scatter-gather stats when it ran, otherwise the
        central pull's raw staging fan-in bytes/errors (None on non-querier
        nodes with nothing fetched)."""
        dist = getattr(self, "_fanout_stats", None)
        if dist is not None:
            snap = dict(dist)
            with scan._stats_lock:
                snap["fanin_bytes"] = scan.stats.fanin_bytes
                snap["fanin_errors"] = scan.stats.fanin_errors
                snap["files_delegated"] = scan.stats.files_delegated
                # fallback fan-in's share of the transport ladder; the
                # scatter's own flight/http split is already in "transport"
                if scan.stats.fanin_transport:
                    snap["fanin_transport"] = dict(scan.stats.fanin_transport)
            return snap
        with scan._stats_lock:
            fanin_bytes = scan.stats.fanin_bytes
            fanin_errors = scan.stats.fanin_errors
            fanin_transport = dict(scan.stats.fanin_transport)
        from parseable_tpu.config import Mode as _Mode

        if self.p.options.mode != _Mode.QUERY and not fanin_bytes and not fanin_errors:
            return None
        out = {
            "mode": "central",
            "fanin_bytes": fanin_bytes,
            "fanin_errors": fanin_errors,
        }
        if fanin_transport:
            out["transport"] = fanin_transport
        return out

    def _hotset_stage(self, routes: dict | None) -> dict | None:
        """stats.stages.hotset: first-class tier state (budget, residency,
        evictions, oversize rejections) plus this query's prefetch counters
        — previously these lived only as Python attrs on the singleton."""
        if self.engine != "tpu":
            return None
        from parseable_tpu.ops.hotset import get_hotset

        snap = get_hotset().stats_snapshot()
        for k in ("prefetch_issued", "prefetch_hits", "prefetch_wasted"):
            if routes and k in routes:
                snap[k] = routes[k]
        return snap

    def _programs_stage(self, routes: dict | None) -> dict | None:
        """stats.stages.programs: this query's program-cache traffic —
        warm queries should read built == 0 and recompiles == 0; a nonzero
        recompile means a cache key was rebuilt (eviction or key churn),
        the condition the dlint tripwire turns red on."""
        if self.engine != "tpu" or routes is None:
            return None
        return {
            "built": int(routes.get("programs_built", 0)),
            "reused": int(routes.get("programs_reused", 0)),
            "recompiles": int(routes.get("recompiles", 0)),
        }

    def _maybe_log_slow(self, select: S.Select, elapsed: float, stats: dict) -> None:
        """Slow-query log (gated by P_SLOW_QUERY_MS; 0 disables): one
        structured warning with the statement, stage breakdown, and the
        trace id so the full span tree is one /debug/spans call away."""
        threshold = getattr(self.p.options, "slow_query_ms", 0)
        if not threshold or elapsed * 1000 < threshold:
            return
        from parseable_tpu.utils.telemetry import current_trace_id

        sql_text = getattr(self, "_sql_text", None) or S.format_statement(select)
        logger.warning(
            "slow query (%.0f ms > %d ms) trace_id=%s engine=%s stages=%s sql=%s",
            elapsed * 1000,
            threshold,
            current_trace_id() or "-",
            stats.get("engine", self.engine),
            stats.get("stages"),
            sql_text,
        )

    def _explain(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> QueryResult:
        """EXPLAIN [ANALYZE]: (plan_type, plan) rows — DataFusion's explain
        shape (reference: src/query/mod.rs:212-276 exposes EXPLAIN through
        the DataFusion session)."""
        mode = select.explain
        select.explain = None
        # RBAC before anything renders: composite statements don't reach
        # _plan_ast's per-stream check, so enforce over every referenced
        # stream here (same contract as execution)
        if allowed_streams is not None:
            for stream in sorted(_referenced_streams(select)):
                if stream not in allowed_streams:
                    raise QueryError(f"unauthorized for stream {stream!r}")
        plan_types = ["logical_plan"]
        plans = [S.format_statement(select)]

        if _is_composite(select):
            plans.append(
                "CompositeExec: joins/CTEs/unions/subqueries run through the "
                "multi-table planner (query/multi.py); branch scans prune and "
                "execute like single-stream plans"
            )
            plan_types.append("physical_plan")
        else:
            try:
                lp = self._plan_ast(select, start_time, end_time, allowed_streams, t0)
                proj = (
                    ", ".join(sorted(lp.needed_columns))
                    if lp.needed_columns is not None
                    else "*"
                )
                phys = [
                    f"engine={self.engine}",
                    f"scan: stream={lp.stream} projection=[{proj}] "
                    f"time_bounds=[{lp.time_bounds.low}, {lp.time_bounds.high}]",
                ]
                if lp.is_aggregate:
                    from parseable_tpu.query.partials import specs_partializable
                    from parseable_tpu.query.executor import QueryExecutor

                    agg, _, _ = QueryExecutor(lp).build_aggregator()
                    if self.engine == "tpu":
                        phys.append(
                            "aggregate: device fused one-hot fold (dense pow2 "
                            "group space; block-local two-phase past "
                            "DENSE_G_MAX; link-adaptive CPU routing)"
                        )
                    elif specs_partializable(agg.specs):
                        phys.append(
                            "aggregate: two-phase partial/merge "
                            "(dictionary-coded keys, single int64 group code)"
                        )
                    else:
                        phys.append("aggregate: streaming hash aggregate")
                    if select.order_by and select.limit is not None:
                        phys.append(
                            f"top-k: ORDER BY/LIMIT pushdown (k={ (select.offset or 0) + select.limit })"
                        )
                plan_types.append("physical_plan")
                plans.append("\n".join(phys))
            except QueryError:
                raise
            except Exception as e:  # noqa: BLE001
                plan_types.append("physical_plan")
                plans.append(f"(plan unavailable: {e})")

        if mode == "analyze":
            # sql_key: single non-composite statements have faithful text,
            # so the analyzed run is pushdown-eligible exactly like the
            # real query it profiles (without it _exec_sql stays None and
            # EXPLAIN ANALYZE silently measured the central path only)
            res = self._query_ast(
                select,
                start_time,
                end_time,
                allowed_streams,
                sql_key=None if _is_composite(select) else S.format_statement(select),
            )
            st = res.stats
            plan_types.append("analyze")
            parts = [f"rows_out={res.table.num_rows}"]
            for k in (
                "rows_scanned",
                "files_total",
                "files_pruned",
                "bytes_scanned",
                "bytes_saved_by_projection",
                "scan_errors",
                "elapsed_secs",
                "engine",
            ):
                if st.get(k) is not None:  # composite paths carry no scan stats
                    parts.append(f"{k}={st[k]}")
            plans.append(" ".join(parts))
            stages = st.get("stages")
            if stages:
                # per-stage wall-time split (parse/plan/scan/execute);
                # nested stage dicts (fanout/hotset) get their own rows
                plan_types.append("stage_timing")
                plans.append(
                    " ".join(
                        f"{k}={v}"
                        for k, v in stages.items()
                        if v is not None and not isinstance(v, dict)
                    )
                )
            fanout = (stages or {}).get("fanout")
            if fanout:
                # distributed data plane: scatter totals + one line per peer
                plan_types.append("fanout")

                def _fv(v):
                    # transport breakdowns are dicts: render flight:2,http:1
                    if isinstance(v, dict):
                        return ",".join(f"{k}:{v[k]}" for k in sorted(v))
                    return v

                lines = [
                    " ".join(
                        f"{k}={_fv(fanout[k])}"
                        for k in (
                            "mode",
                            "peers",
                            "ok",
                            "fallback",
                            "hedged",
                            "retries",
                            "bytes",
                            "transport",
                            "fanin_bytes",
                            "fanin_errors",
                            "fanin_transport",
                        )
                        if fanout.get(k) not in (None, {})
                    )
                ]
                for domain, pp in sorted((fanout.get("per_peer") or {}).items()):
                    lines.append(
                        f"peer {domain}: " + " ".join(
                            f"{k}={pp.get(k)}"
                            for k in (
                                "result", "ms", "rows", "bytes",
                                "attempts", "hedged", "transport",
                            )
                        )
                    )
                plans.append("\n".join(lines))
            routes = st.get("device_routes")
            if routes is not None:
                # adaptive dispatch, observable without a profiler
                # (VERDICT r3 #10): where each block ran and what the
                # link actually carried, plus the measured link profile
                # the routing decisions priced against
                plan_types.append("device_routes")
                plans.append(
                    " ".join(f"{k}={v}" for k, v in sorted(routes.items()))
                )
                from parseable_tpu.ops.link import get_link

                snap = get_link(self.p.options).snapshot()
                plan_types.append("link_profile")
                plans.append(
                    " ".join(f"{k}={v:.4g}" for k, v in sorted(snap.items()))
                )

        table = pa.table({"plan_type": plan_types, "plan": plans})
        return QueryResult(
            table,
            ["plan_type", "plan"],
            stats={"elapsed_secs": round(_time.monotonic() - t0, 6), "explain": mode},
        )

    def _plan(
        self,
        sql_text: str,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> LogicalPlan:
        return self._plan_ast(
            S.parse_sql(sql_text), start_time, end_time, allowed_streams, t0
        )

    def _plan_ast(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
        sql_key: str | None = None,
    ) -> LogicalPlan:
        # plan cache: keyed on (sql, stream, schema fingerprint), storing
        # the plan as built — RBAC, stream resolution, API time bounds and
        # the safety rails are per-request and re-applied below on a copy
        lp = None
        cache_key = None
        cache = get_plan_cache(self.p.options) if sql_key is not None else None
        if cache is not None and select.table:
            fp = self._schema_fingerprint(select.table)
            if fp is not None:
                cache_key = ("plan", sql_key, select.table, fp)
                lp = cache.get(cache_key)
        if cache_key is not None:
            state = "hit" if lp is not None else "miss"
            QUERY_PLAN_CACHE.labels(state).inc()
            self._plan_cache_state = state
        if lp is None:
            lp = build_plan(select)
            if cache_key is not None:
                cache.put(cache_key, lp)
        if allowed_streams is not None and lp.stream not in allowed_streams:
            raise QueryError(f"unauthorized for stream {lp.stream!r}")
        self.resolve_stream(lp.stream)
        stream = self.p.streams.get(lp.stream)
        if stream is not None and stream.metadata.schema:
            lp.schema_hint = pa.schema(list(stream.metadata.schema.values()))

        if start_time and end_time:
            tr = TimeRange.parse_human_time(start_time, end_time)
            api_bounds = TimeBounds(low=tr.start, high=tr.end)
            lp.time_bounds = lp.time_bounds.intersect(api_bounds)

        # safety rails (reference: query/mod.rs:92,152-165 + :216-226)
        timeout = self.p.options.query_timeout_secs
        if timeout:
            lp.deadline = t0 + timeout
        lp.memory_limit_bytes = self.p.options.query_memory_limit_bytes
        lp.execution_batch_size = self.p.options.execution_batch_size
        return lp

    def query_stream(
        self,
        sql_text: str,
        start_time: str | None = None,
        end_time: str | None = None,
        allowed_streams: set[str] | None = None,
        on_close=None,
    ):
        """Streaming variant (reference: handlers/http/query.rs:325-407):
        returns an iterator of pyarrow Tables, emitted as the scan
        progresses, so `SELECT *` over a huge range never materializes in
        full. Row export is IO-bound, so it always runs the CPU engine —
        the device path exists for aggregation.

        `on_close` fires exactly once when the returned generator finishes
        OR is closed/abandoned mid-stream — the admission-control hook: an
        abandoned HTTP export must hand its concurrency permit back, not
        hold it until GC. (If the generator is never started, on_close
        never fires — callers keep their own idempotent backstop.)"""
        t0 = _time.monotonic()
        select = self._parse_cached(sql_text)
        if _is_composite(select) or select.explain:
            # set operations / CTEs / joins need the full result before the
            # first row can stream (and EXPLAIN emits plan rows, never a
            # scan); materialize through the normal path, one chunk out
            result = self._query_ast(select, start_time, end_time, allowed_streams, t0)

            def single():
                try:
                    yield result.table
                finally:
                    if on_close is not None:
                        on_close()

            return single()
        lp = self._plan_ast(
            select, start_time, end_time, allowed_streams, t0, sql_key=sql_text
        )
        # streaming exports are paced by the client (resp.write backpressure
        # counts as wall time); the SQL timeout would truncate every large
        # download, so it doesn't apply here — memory stays bounded by the
        # per-block emission instead
        lp.deadline = None
        scan = StreamScan(self.p, lp, hot_tier_dir=self._hot_dir(lp.stream))
        executor = QueryExecutor(lp)
        tables = scan.tables()

        def streamed():
            # explicit close so an abandoned HTTP export cancels the scan
            # pool deterministically instead of waiting for GC — and
            # releases the admission slot on the same close path
            try:
                yield from executor.execute_select_stream(tables)
            finally:
                tables.close()
                if on_close is not None:
                    on_close()

        return streamed()

    # ------------------------------------------------------- CTE / UNION

    def _query_with_ctes(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> QueryResult:
        """WITH bindings: materialize each CTE in declaration order (later
        CTEs and the main body see earlier ones), then run the body.
        Reference parity: DataFusion CTE inlining (src/query/mod.rs)."""
        import copy

        prev = getattr(self, "_cte_tables", None)
        tables = dict(prev or {})
        self._cte_tables = tables
        try:
            for name, cte_sel in select.ctes.items():
                sub = copy.deepcopy(cte_sel)
                # RBAC applies to the CTE's underlying streams, not its name
                tables[name] = self._query_ast(
                    sub, start_time, end_time, allowed_streams, t0
                ).table
            body = copy.copy(select)
            body.ctes = {}
            return self._query_ast(body, start_time, end_time, allowed_streams, t0)
        finally:
            if prev is None:
                del self._cte_tables
            else:
                self._cte_tables = prev

    def _query_union(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> QueryResult:
        """UNION [ALL]: branches execute independently (RBAC/time range per
        branch), match by position, fold left with distinct at each non-ALL
        step (standard SQL associativity); the hoisted ORDER BY/LIMIT apply
        to the combined result."""
        import copy

        from parseable_tpu.query.executor import QueryExecutor as _QE
        from parseable_tpu.utils.arrowutil import adapt_batch, merge_schemas

        head = copy.copy(select)
        head.set_ops = []
        head.order_by = []
        head.limit = None
        head.offset = None
        acc = self._query_ast(head, start_time, end_time, allowed_streams, t0).table
        n_cols = acc.num_columns
        out_names = acc.column_names

        def distinct(t: pa.Table) -> pa.Table:
            return t.group_by(t.column_names, use_threads=False).aggregate([])

        for is_all, branch in select.set_ops:
            bt = self._query_ast(
                copy.copy(branch), start_time, end_time, allowed_streams, t0
            ).table
            if bt.num_columns != n_cols:
                raise QueryError(
                    f"UNION branches have {n_cols} vs {bt.num_columns} columns"
                )
            bt = bt.rename_columns(out_names)
            schema = merge_schemas([acc.schema, bt.schema])
            batches = [adapt_batch(schema, b) for t in (acc, bt) for b in t.to_batches()]
            acc = pa.Table.from_batches(batches, schema=schema)
            if not is_all:
                acc = distinct(acc)

        if select.order_by or select.limit is not None or select.offset is not None:
            from parseable_tpu.query.planner import LogicalPlan, TimeBounds

            shim = S.Select(
                items=[S.SelectItem(S.Star())],
                table="__union",
                order_by=select.order_by,
                limit=select.limit,
                offset=select.offset,
            )
            lp = LogicalPlan(
                select=shim, stream="__union", time_bounds=TimeBounds(),
                constraints=[], needed_columns=None,
            )
            acc = _QE(lp)._order_limit(acc)
        elapsed = _time.monotonic() - t0
        return QueryResult(
            acc,
            acc.column_names,
            {"elapsed_secs": round(elapsed, 6), "engine": self.engine, "set_op": "union"},
        )

    def _query_cte_table(self, select: S.Select, table: pa.Table, t0: float) -> QueryResult:
        """FROM <cte>: run the remaining SELECT over the materialized CTE
        output with the CPU executor (time bounds were applied when the CTE
        scanned its streams; they do not re-apply to derived rows)."""
        import copy

        from parseable_tpu.query.planner import TimeBounds, plan as build_plan

        sel = copy.deepcopy(select)  # joins/subqueries were routed to _query_multi already
        lp = build_plan(sel)
        lp.time_bounds = TimeBounds()
        timeout = self.p.options.query_timeout_secs
        if timeout:
            lp.deadline = t0 + timeout
        lp.memory_limit_bytes = self.p.options.query_memory_limit_bytes
        executor = QueryExecutor(lp)
        out = executor.execute(iter([table]))
        elapsed = _time.monotonic() - t0
        return QueryResult(
            out,
            out.column_names,
            {"elapsed_secs": round(elapsed, 6), "engine": "cpu", "cte": select.table},
        )

    # ------------------------------------------------------- multi-stream

    def _query_multi(
        self,
        select: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> QueryResult:
        """Joins + subqueries (reference gets these from DataFusion;
        query/multi.py documents the design). The API time range applies to
        every stream scan; the WHERE tree applies post-join."""
        import copy

        from parseable_tpu.query import multi as M

        sel = copy.deepcopy(select)

        # bounded nesting: run_select re-enters this method for nested
        # subqueries, so the depth lives on the session, not the recursion
        depth = getattr(self, "_multi_depth", 0)
        if depth > 4:
            raise QueryError("subqueries nested too deeply")
        self._multi_depth = depth + 1
        try:
            return self._query_multi_inner(
                sel, start_time, end_time, allowed_streams, t0, M
            )
        finally:
            self._multi_depth = depth

    def _query_multi_inner(
        self,
        sel: S.Select,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
        M,
    ) -> QueryResult:
        # RBAC over every referenced stream, before anything executes
        # (CTE names are session-local bindings, not streams)
        cte_tables = getattr(self, "_cte_tables", None) or {}
        streams = collect_streams(sel) - set(cte_tables)
        if allowed_streams is not None:
            for name in streams:
                if name not in allowed_streams:
                    raise QueryError(f"unauthorized for stream {name!r}")

        def run_select(sub: S.Select) -> pa.Table:
            # share the outer query's t0 so all subqueries burn the SAME
            # timeout window, not a fresh one each
            return self._query_ast(sub, start_time, end_time, allowed_streams, t0).table

        sel.where = M.resolve_subqueries(sel.where, run_select)
        sel.having = M.resolve_subqueries(sel.having, run_select)
        sel.items = [
            S.SelectItem(M.resolve_subqueries(i.expr, run_select), i.alias)
            for i in sel.items
        ]

        if not sel.joins:
            # subqueries resolved; the remainder is a single-stream query
            return self._query_ast(sel, start_time, end_time, allowed_streams, t0)

        # --- materialize each side through the normal single-stream scan ---
        refs = [(sel.table, sel.table_alias or sel.table)] + [
            (j.table, j.alias or j.table) for j in sel.joins
        ]
        exprs = [sel.where, sel.having, *(i.expr for i in sel.items)]
        exprs += [g for g in sel.group_by] + [o.expr for o in sel.order_by]
        exprs += [j.on for j in sel.joins]
        needed_all = set()
        needed_by_alias: dict[str, set[str]] = {a: set() for _, a in refs}
        star = any(isinstance(i.expr, S.Star) for i in sel.items)
        for e in exprs:
            for col in _qualified_refs(e):
                if col.table is not None and col.table in needed_by_alias:
                    needed_by_alias[col.table].add(col.name)
                elif col.table is None:
                    needed_all.add(col.name)

        # ownership from the stream SCHEMAS, not materialized columns — an
        # empty scan fabricates needed columns (_empty_like) and would make
        # ambiguity detection data-dependent
        owner_of: dict[str, str] = {}
        sides: list[tuple[str, pa.Table]] = []
        for name, alias in refs:
            needed = None if star else (needed_by_alias[alias] | needed_all)
            if name in cte_tables:
                t = cte_tables[name]
                if needed is not None:
                    keep = [c for c in t.column_names if c in needed]
                    t = t.select(keep)
                sides.append((alias, t))
                for c in t.column_names:
                    owner_of[c] = "__ambiguous__" if c in owner_of else alias
                continue
            self.resolve_stream(name)
            t = self._materialize_stream(name, needed, start_time, end_time, t0)
            sides.append((alias, t))
            stream = self.p.streams.get(name)
            schema_cols = (
                set(stream.metadata.schema.keys())
                if stream is not None and stream.metadata.schema
                else set(t.column_names)
            )
            for c in schema_cols:
                owner_of[c] = "__ambiguous__" if c in owner_of else alias

        # residual ON conditions evaluate against the alias-qualified join
        # output — bare columns in them must be qualified first
        sel.joins = [
            S.Join(j.table, j.alias, j.kind, M.qualify_unqualified(j.on, owner_of))
            for j in sel.joins
        ]
        joined = M.execute_join(
            sides[0],
            list(zip(sel.joins, [t for _, t in sides[1:]])),
            memory_limit=self.p.options.query_memory_limit_bytes,
        )

        # bare columns resolve by schema ownership; then run the remaining
        # SELECT over the joined table with the standard executor
        sel.where = M.qualify_unqualified(sel.where, owner_of)
        sel.having = M.qualify_unqualified(sel.having, owner_of)
        sel.items = [
            S.SelectItem(M.qualify_unqualified(i.expr, owner_of), i.alias) for i in sel.items
        ]
        sel.group_by = [M.qualify_unqualified(g, owner_of) for g in sel.group_by]
        sel.order_by = [
            S.OrderItem(M.qualify_unqualified(o.expr, owner_of), o.desc) for o in sel.order_by
        ]
        sel.joins = []
        sel.table = "__joined"
        lp = build_plan(sel)
        lp.time_bounds = TimeBounds()  # already applied per stream scan
        timeout = self.p.options.query_timeout_secs
        if timeout:
            lp.deadline = t0 + timeout
        lp.memory_limit_bytes = self.p.options.query_memory_limit_bytes
        executor = QueryExecutor(lp)
        table = executor.execute(iter([joined]))
        elapsed = _time.monotonic() - t0
        QUERY_EXECUTE_TIME.labels(",".join(sorted(streams))).observe(elapsed)
        stats = {
            "elapsed_secs": round(elapsed, 6),
            "engine": "cpu",
            "joined_streams": sorted(streams),
        }
        self._maybe_log_slow(sel, elapsed, stats)
        return QueryResult(table, table.column_names, stats)

    def _materialize_stream(
        self,
        name: str,
        needed: set[str] | None,
        start_time: str | None,
        end_time: str | None,
        t0: float,
    ) -> pa.Table:
        """One join side: full scan of a stream within the API time range,
        column-pruned, bounded by the memory cap."""
        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        sub = S.Select(items=[S.SelectItem(S.Star())], table=name)
        lp = self._plan_ast(sub, start_time, end_time, None, t0)
        if needed is not None:
            lp.needed_columns = needed | {DEFAULT_TIMESTAMP_KEY}
        scan = StreamScan(self.p, lp, hot_tier_dir=self._hot_dir(name))
        tables = scan.tables()
        try:
            return QueryExecutor(lp).execute(tables)
        finally:
            tables.close()

    def _hot_dir(self, stream: str):
        return (
            self.p.hot_tier.local_dir_for_scan(stream)
            if getattr(self.p, "hot_tier", None) is not None
            else self.p.options.hot_tier_storage_path
        )

    def _execute(self, lp: LogicalPlan, scan: StreamScan) -> tuple[QueryResult, _TimedIter]:
        timer = _TimedIter(iter(()))
        # count(*) fast path off manifest row counts, only when every
        # overlapping file lies fully inside the time bounds
        if lp.count_star_only:
            fast = self._try_manifest_count(lp, scan)
            if fast is not None:
                name = lp.select.items[0].alias or "count(*)"
                table = pa.table({name: pa.array([fast], pa.int64())})
                return QueryResult(table, [name], {"fast_path": "manifest_count"}), timer

        # partial-aggregate result cache: a repeated aggregate over an
        # unchanged manifest set skips the scan — only HAVING/projection/
        # ORDER BY re-run over the cached interim. Eligibility requires the
        # query range to stay clear of the staging window (staging rows are
        # invisible to the manifest fingerprint, and concurrent ingest
        # would make a cached answer stale the moment it was stored).
        from parseable_tpu.query.partials import (
            get_result_cache,
            manifest_fingerprint,
            plan_fingerprint,
        )

        self._result_cache_state = None
        result_cache = get_result_cache(self.p.options)
        result_key = None
        if (
            result_cache is not None
            and lp.is_aggregate
            and not scan._within_staging_window()
        ):
            result_key = (
                lp.stream,
                manifest_fingerprint(scan.manifest_files()),
                plan_fingerprint(lp, self.engine),
            )
            interim = result_cache.get(result_key)
            if interim is not None:
                self._result_cache_state = "hit"
                QUERY_CACHE_HIT.labels(lp.stream).inc()
                ex = QueryExecutor(lp)
                _agg, rewritten, _names = ex.build_aggregator()
                table = ex.finalize_from_interim(interim, rewritten)
                return (
                    QueryResult(table, table.column_names, {"result_cache": "hit"}),
                    timer,
                )
            self._result_cache_state = "miss"

        # distributed partial-aggregate pushdown (query/fanout.py): on a
        # dedicated querier, scatter partializable GROUP BY aggregates to
        # live ingestors — each scans its own staging + owned manifests and
        # answers with one partial table — instead of pulling raw staging
        # windows and scanning everything here. prepare() launches the
        # fan-out (overlapping the local scan) and re-scopes `scan` to
        # unowned/historical files; collection happens inside the
        # executor's merge via partials_source. Falls through to the
        # central path when ineligible (non-aggregate plans, no tagged
        # live peers, knob off).
        dist = None
        from parseable_tpu.config import Mode as _Mode

        exec_sql = getattr(self, "_exec_sql", None)
        if (
            self.p.options.mode == _Mode.QUERY
            and self.p.options.query_pushdown
            and lp.is_aggregate
            and exec_sql is not None
        ):
            from parseable_tpu.query import fanout as FO

            dist = FO.prepare(self.p, lp, scan, exec_sql)
        if dist is not None:
            # the distributed merge is host-side regardless of the session
            # engine: peer partials fold into the CPU two-phase funnel
            executor = QueryExecutor(lp)
            executor.partials_source = dist.collect
            if result_key is not None:
                def _dist_sink(interim, _key=result_key, _cache=result_cache, _scan=scan):
                    with _scan._stats_lock:
                        errors = _scan.stats.scan_errors
                    if errors == 0:
                        _cache.put(_key, interim)

                executor.interim_sink = _dist_sink
            timer = _TimedIter(scan.tables())
            try:
                table = executor.execute(timer)
            finally:
                timer.close()
            self._fanout_stats = dist.stats
            return QueryResult(table, table.column_names, {}), timer

        use_tpu = self.engine == "tpu"
        fallback = False
        if use_tpu:
            from parseable_tpu.utils.devicecheck import device_healthy

            # bound the probe by the query's own deadline so the health
            # check can never be what times the query out
            max_wait = None
            if lp.deadline is not None:
                max_wait = max(0.0, lp.deadline - _time.monotonic() - 1.0)
            if not device_healthy(max_wait=max_wait):
                # wedged/unreachable accelerator: the CPU engine is a
                # complete fallback — degrade instead of hanging a worker
                use_tpu = False
                fallback = True
        if use_tpu:
            from parseable_tpu.query.executor_tpu import TpuQueryExecutor

            if (
                lp.ts_artificial
                and lp.time_bounds.low is None
                and lp.time_bounds.high is None
                and lp.needed_columns is not None
            ):
                # no bounds and no expression touches the timestamp: skip
                # encoding/shipping it (the column is ~a third of a typical
                # scan's transfer bytes)
                from parseable_tpu import DEFAULT_TIMESTAMP_KEY

                lp.needed_columns.discard(DEFAULT_TIMESTAMP_KEY)
            self._set_scan_time_hint(lp, scan)
            executor: QueryExecutor = TpuQueryExecutor(lp, self.p.options)
            executor.source_loader = scan.read_source
            # the scan's ordered stub list drives query-aware prefetch:
            # block i+1 ships from the enccache while block i aggregates
            executor.prefetch_scan = scan
        else:
            executor = QueryExecutor(lp)
        if result_key is not None:
            # store the merged interim the moment the engine produces it —
            # but never a partial one (scan_errors means files were dropped)
            def _sink(interim, _key=result_key, _cache=result_cache, _scan=scan):
                with _scan._stats_lock:
                    errors = _scan.stats.scan_errors
                if errors == 0:
                    _cache.put(_key, interim)

            executor.interim_sink = _sink
        # both engines consume the scan's parallel fetch+decode pipeline
        # (provider.py): the pool overlaps object-store GETs and parquet
        # decode with engine compute, bounded by P_SCAN_INFLIGHT_BYTES —
        # this replaced the TPU path's single-worker depth-3 prefetcher
        timer = _TimedIter(scan.tables())
        try:
            table = executor.execute(timer)
        finally:
            timer.close()
        stats = {"engine_fallback": "device unhealthy"} if fallback else {}
        routes = getattr(executor, "route_stats", None)
        if routes is not None:
            # adaptive-dispatch observability (EXPLAIN ANALYZE surfaces
            # this): per-block route decisions + actual transfer bytes
            stats["device_routes"] = dict(routes)
        return QueryResult(table, table.column_names, stats), timer

    @staticmethod
    def _set_scan_time_hint(lp: LogicalPlan, scan: StreamScan) -> None:
        """Overall scan time range from per-file p_timestamp stats — lets the
        TPU engine pre-size time-bin group capacities exactly (a loose hint
        inflates the dense group space and with it the scatter cost)."""
        from datetime import datetime

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        lo_ms = hi_ms = None
        for f in scan.manifest_files():
            for col in f.columns:
                if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                    lo_ms = col.stats.min if lo_ms is None else min(lo_ms, col.stats.min)
                    hi_ms = col.stats.max if hi_ms is None else max(hi_ms, col.stats.max)
        if lo_ms is None:
            return
        lo = datetime.fromtimestamp(lo_ms / 1000, UTC)
        hi = datetime.fromtimestamp(hi_ms / 1000, UTC)
        if lp.time_bounds.low is not None:
            lo = max(lo, lp.time_bounds.low)
        if lp.time_bounds.high is not None:
            hi = min(hi, lp.time_bounds.high)
        if lo <= hi:
            lp.scan_time_hint = (lo, hi)

    def _try_manifest_count(self, lp: LogicalPlan, scan: StreamScan) -> int | None:
        from datetime import datetime

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        tb = lp.time_bounds
        total = 0
        partial = False
        for f in scan.manifest_files():
            lo = hi = None
            for col in f.columns:
                if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                    lo = datetime.fromtimestamp(col.stats.min / 1000, UTC)
                    hi = datetime.fromtimestamp(col.stats.max / 1000, UTC)
            if lo is None:
                partial = True
                break
            inside = (tb.low is None or lo >= tb.low) and (tb.high is None or hi < tb.high)
            if not inside:
                partial = True
                break
            total += f.num_rows
        if partial:
            return None
        # staging rows within range still need counting
        stream = self.p.streams.get(lp.stream)
        if stream is not None and scan._within_staging_window():
            for t in scan.staging_tables():
                t = scan._apply_time_filter(t)
                total += t.num_rows
        return total
