"""Query session: SQL + time range -> plan -> scan -> execute -> JSON rows.

Parity target (reference: src/query/mod.rs QUERY_SESSION / Query::execute,
handlers/http/query.rs::query): API callers pass SQL plus startTime/endTime;
time filters are injected into the plan exactly like the reference's
`final_logical_plan`, the count(*) fast path is served from manifest row
counts, and everything else runs on the selected engine (tpu|cpu).
"""

from __future__ import annotations

import logging
import time as _time
from dataclasses import dataclass, field
from datetime import UTC
from typing import Any

import pyarrow as pa

from parseable_tpu.core import Parseable
from parseable_tpu.query import sql as S
from parseable_tpu.query.executor import QueryExecutor
from parseable_tpu.query.planner import LogicalPlan, TimeBounds, plan as build_plan
from parseable_tpu.query.provider import StreamScan
from parseable_tpu.utils.arrowutil import record_batches_to_json
from parseable_tpu.utils.metrics import QUERY_EXECUTE_TIME
from parseable_tpu.utils.timeutil import TimeRange

logger = logging.getLogger(__name__)


class QueryError(ValueError):
    pass


@dataclass
class QueryResult:
    table: pa.Table
    fields: list[str]
    stats: dict[str, Any] = field(default_factory=dict)

    def to_json_rows(self) -> list[dict]:
        return record_batches_to_json(self.table.to_batches())


class QuerySession:
    """One engine-backed session over a Parseable instance."""

    def __init__(self, parseable: Parseable, engine: str | None = None):
        self.p = parseable
        self.engine = engine or parseable.options.query_engine

    def resolve_stream(self, name: str) -> None:
        """Make sure the stream exists locally, loading from storage when a
        querier sees it for the first time (query.rs:558-618)."""
        if self.p.streams.get(name) is None:
            self.p.load_streams_from_storage()
        if self.p.streams.get(name) is None:
            raise QueryError(f"stream {name!r} does not exist")

    def query(
        self,
        sql_text: str,
        start_time: str | None = None,
        end_time: str | None = None,
        allowed_streams: set[str] | None = None,
    ) -> QueryResult:
        """Run SQL. `allowed_streams` (None = unrestricted) is the caller's
        RBAC scope, enforced on the *resolved* plan before any execution so
        unauthorized streams neither run nor leak through error messages."""
        t0 = _time.monotonic()
        lp = self._plan(sql_text, start_time, end_time, allowed_streams, t0)

        scan = StreamScan(
            self.p,
            lp,
            hot_tier_dir=self._hot_dir(lp.stream),
            use_hot_stubs=self.engine == "tpu" and lp.is_aggregate,
        )
        result = self._execute(lp, scan)
        elapsed = _time.monotonic() - t0
        QUERY_EXECUTE_TIME.labels(lp.stream).observe(elapsed)
        result.stats.update(
            {
                "elapsed_secs": round(elapsed, 6),
                "engine": self.engine,
                "files_total": scan.stats.files_total,
                "files_pruned": scan.stats.files_pruned,
                "bytes_scanned": scan.stats.bytes_scanned,
                "rows_scanned": scan.stats.rows_scanned,
            }
        )
        return result

    def _plan(
        self,
        sql_text: str,
        start_time: str | None,
        end_time: str | None,
        allowed_streams: set[str] | None,
        t0: float,
    ) -> LogicalPlan:
        select = S.parse_sql(sql_text)
        lp = build_plan(select)
        if allowed_streams is not None and lp.stream not in allowed_streams:
            raise QueryError(f"unauthorized for stream {lp.stream!r}")
        self.resolve_stream(lp.stream)
        stream = self.p.streams.get(lp.stream)
        if stream is not None and stream.metadata.schema:
            lp.schema_hint = pa.schema(list(stream.metadata.schema.values()))

        if start_time and end_time:
            tr = TimeRange.parse_human_time(start_time, end_time)
            api_bounds = TimeBounds(low=tr.start, high=tr.end)
            lp.time_bounds = lp.time_bounds.intersect(api_bounds)

        # safety rails (reference: query/mod.rs:92,152-165 + :216-226)
        timeout = self.p.options.query_timeout_secs
        if timeout:
            lp.deadline = t0 + timeout
        lp.memory_limit_bytes = self.p.options.query_memory_limit_bytes
        return lp

    def query_stream(
        self,
        sql_text: str,
        start_time: str | None = None,
        end_time: str | None = None,
        allowed_streams: set[str] | None = None,
    ):
        """Streaming variant (reference: handlers/http/query.rs:325-407):
        returns an iterator of pyarrow Tables, emitted as the scan
        progresses, so `SELECT *` over a huge range never materializes in
        full. Row export is IO-bound, so it always runs the CPU engine —
        the device path exists for aggregation."""
        t0 = _time.monotonic()
        lp = self._plan(sql_text, start_time, end_time, allowed_streams, t0)
        # streaming exports are paced by the client (resp.write backpressure
        # counts as wall time); the SQL timeout would truncate every large
        # download, so it doesn't apply here — memory stays bounded by the
        # per-block emission instead
        lp.deadline = None
        scan = StreamScan(self.p, lp, hot_tier_dir=self._hot_dir(lp.stream))
        executor = QueryExecutor(lp)
        return executor.execute_select_stream(scan.tables())

    def _hot_dir(self, stream: str):
        return (
            self.p.hot_tier.local_dir_for_scan(stream)
            if getattr(self.p, "hot_tier", None) is not None
            else self.p.options.hot_tier_storage_path
        )

    def _execute(self, lp: LogicalPlan, scan: StreamScan) -> QueryResult:
        # count(*) fast path off manifest row counts, only when every
        # overlapping file lies fully inside the time bounds
        if lp.count_star_only:
            fast = self._try_manifest_count(lp, scan)
            if fast is not None:
                name = lp.select.items[0].alias or "count(*)"
                table = pa.table({name: pa.array([fast], pa.int64())})
                return QueryResult(table, [name], {"fast_path": "manifest_count"})

        if self.engine == "tpu":
            from parseable_tpu.query.executor_tpu import TpuQueryExecutor

            self._set_scan_time_hint(lp, scan)
            executor: QueryExecutor = TpuQueryExecutor(lp, self.p.options)
            executor.source_loader = scan.read_source
        else:
            executor = QueryExecutor(lp)
        table = executor.execute(scan.tables())
        return QueryResult(table, table.column_names)

    @staticmethod
    def _set_scan_time_hint(lp: LogicalPlan, scan: StreamScan) -> None:
        """Overall scan time range from per-file p_timestamp stats — lets the
        TPU engine pre-size time-bin group capacities exactly (a loose hint
        inflates the dense group space and with it the scatter cost)."""
        from datetime import datetime

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        lo_ms = hi_ms = None
        for f in scan.manifest_files():
            for col in f.columns:
                if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                    lo_ms = col.stats.min if lo_ms is None else min(lo_ms, col.stats.min)
                    hi_ms = col.stats.max if hi_ms is None else max(hi_ms, col.stats.max)
        if lo_ms is None:
            return
        lo = datetime.fromtimestamp(lo_ms / 1000, UTC)
        hi = datetime.fromtimestamp(hi_ms / 1000, UTC)
        if lp.time_bounds.low is not None:
            lo = max(lo, lp.time_bounds.low)
        if lp.time_bounds.high is not None:
            hi = min(hi, lp.time_bounds.high)
        if lo <= hi:
            lp.scan_time_hint = (lo, hi)

    def _try_manifest_count(self, lp: LogicalPlan, scan: StreamScan) -> int | None:
        from datetime import datetime

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        tb = lp.time_bounds
        total = 0
        partial = False
        for f in scan.manifest_files():
            lo = hi = None
            for col in f.columns:
                if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                    lo = datetime.fromtimestamp(col.stats.min / 1000, UTC)
                    hi = datetime.fromtimestamp(col.stats.max / 1000, UTC)
            if lo is None:
                partial = True
                break
            inside = (tb.low is None or lo >= tb.low) and (tb.high is None or hi < tb.high)
            if not inside:
                partial = True
                break
            total += f.num_rows
        if partial:
            return None
        # staging rows within range still need counting
        stream = self.p.streams.get(lp.stream)
        if stream is not None and scan._within_staging_window():
            for t in scan.staging_tables():
                t = scan._apply_time_filter(t)
                total += t.num_rows
        return total
