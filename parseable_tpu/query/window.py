"""Window-function evaluation over a materialized table.

The reference gets `row_number()/rank()/lag() OVER (...)` for free from
DataFusion (src/query/mod.rs:212-276); here windows evaluate post-scan on the
host, vectorized: one pyarrow sort per distinct (PARTITION BY, ORDER BY)
spec, then numpy segment arithmetic on the sorted order, scattered back to
the input row order. Sorting is the only O(n log n) step; every window
function itself is O(n) vectorized.

Default frames follow SQL/DataFusion semantics:
- with ORDER BY: RANGE UNBOUNDED PRECEDING..CURRENT ROW — running values
  where *peer rows* (equal order keys) share the frame result;
- without ORDER BY: the whole partition.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from parseable_tpu.query import sql as S


class WindowError(ValueError):
    pass


def window_calls(e: S.Expr | None) -> list[S.WindowCall]:
    """All WindowCall nodes in an expression tree (document order)."""
    out: list[S.WindowCall] = []
    if e is None:
        return out

    def walk(x: S.Expr) -> None:
        if isinstance(x, S.WindowCall):
            out.append(x)
            return  # window args cannot nest further windows
        if isinstance(x, S.BinaryOp):
            walk(x.left)
            walk(x.right)
        elif isinstance(x, S.UnaryOp):
            walk(x.operand)
        elif isinstance(x, S.InList):
            walk(x.expr)
            for i in x.items:
                walk(i)
        elif isinstance(x, S.Between):
            walk(x.expr)
            walk(x.low)
            walk(x.high)
        elif isinstance(x, S.IsNull):
            walk(x.expr)
        elif isinstance(x, S.FunctionCall):
            for a in x.args:
                walk(a)
        elif isinstance(x, S.Cast):
            walk(x.expr)
        elif isinstance(x, S.Case):
            for w, t in x.whens:
                walk(w)
                walk(t)
            if x.else_expr is not None:
                walk(x.else_expr)

    walk(e)
    return out


def rewrite_windows(e: S.Expr, mapping: dict[str, str]) -> S.Expr:
    """Replace WindowCall nodes with Column refs per `mapping` (repr keyed)."""
    if isinstance(e, S.WindowCall):
        return S.Column(mapping[repr(e)])
    if isinstance(e, S.BinaryOp):
        return S.BinaryOp(e.op, rewrite_windows(e.left, mapping), rewrite_windows(e.right, mapping))
    if isinstance(e, S.UnaryOp):
        return S.UnaryOp(e.op, rewrite_windows(e.operand, mapping))
    if isinstance(e, S.InList):
        return S.InList(
            rewrite_windows(e.expr, mapping),
            [rewrite_windows(i, mapping) for i in e.items],
            e.negated,
        )
    if isinstance(e, S.Between):
        return S.Between(
            rewrite_windows(e.expr, mapping),
            rewrite_windows(e.low, mapping),
            rewrite_windows(e.high, mapping),
            e.negated,
        )
    if isinstance(e, S.IsNull):
        return S.IsNull(rewrite_windows(e.expr, mapping), e.negated)
    if isinstance(e, S.FunctionCall):
        return S.FunctionCall(e.name, [rewrite_windows(a, mapping) for a in e.args], e.distinct)
    if isinstance(e, S.Cast):
        return S.Cast(rewrite_windows(e.expr, mapping), e.type_name)
    if isinstance(e, S.Case):
        return S.Case(
            [(rewrite_windows(w, mapping), rewrite_windows(t, mapping)) for w, t in e.whens],
            rewrite_windows(e.else_expr, mapping) if e.else_expr else None,
        )
    return e


def _segment_starts(cols: list[pa.Array]) -> np.ndarray:
    """starts[i] = True where row i begins a new segment (any key differs
    from row i-1; nulls compare equal to nulls)."""
    n = len(cols[0]) if cols else 0
    starts = np.zeros(n, dtype=bool)
    if n:
        starts[0] = True
    for col in cols:
        a = col.slice(1)
        b = col.slice(0, n - 1)
        neq = pc.fill_null(pc.not_equal(a, b), False).to_numpy(zero_copy_only=False)
        # null vs non-null is a boundary; null vs null is not
        an = pc.is_null(a).to_numpy(zero_copy_only=False)
        bn = pc.is_null(b).to_numpy(zero_copy_only=False)
        starts[1:] |= np.asarray(neq, bool) | (np.asarray(an, bool) != np.asarray(bn, bool))
    return starts


def _part_start_idx(starts: np.ndarray) -> np.ndarray:
    """For each row, the index of its segment's first row."""
    n = len(starts)
    idx = np.arange(n)
    return np.maximum.accumulate(np.where(starts, idx, 0))


def _peer_end_idx(peer_starts: np.ndarray) -> np.ndarray:
    """For each row, the index of its peer group's last row (the reverse
    minimum-accumulate of each peer group's closing index)."""
    n = len(peer_starts)
    idx = np.arange(n)
    is_last = np.zeros(n, bool)
    is_last[:-1] = peer_starts[1:]
    if n:
        is_last[-1] = True
    return np.minimum.accumulate(np.where(is_last, idx, n)[::-1])[::-1]


def _evaluate(e: S.Expr, table: pa.Table) -> pa.Array:
    from parseable_tpu.query.executor import _arr, evaluate

    return _arr(evaluate(e, table), table)


def compute_window(w: S.WindowCall, table: pa.Table) -> pa.Array:
    """Evaluate one window call over `table`, returning a full-length array
    aligned to the input row order."""
    n = table.num_rows
    if n == 0:
        return pa.nulls(0)

    part_cols = [_evaluate(p, table) for p in w.partition_by]
    order_cols = [_evaluate(o.expr, table) for o in w.order_by]

    # one sort arranges rows partition-major, order-minor
    sort_tbl = pa.table(
        {f"__p{i}": c for i, c in enumerate(part_cols)}
        | {f"__o{i}": c for i, c in enumerate(order_cols)}
        or {"__d": pa.nulls(n, pa.int8())}
    )
    sort_keys = [(f"__p{i}", "ascending") for i in range(len(part_cols))] + [
        (f"__o{i}", "descending" if o.desc else "ascending")
        for i, o in enumerate(w.order_by)
    ]
    if sort_keys:
        sort_idx = pc.sort_indices(sort_tbl, sort_keys=sort_keys).to_numpy(
            zero_copy_only=False
        ).astype(np.int64)
    else:
        sort_idx = np.arange(n, dtype=np.int64)

    take = pa.array(sort_idx)
    sp = [c.take(take) for c in part_cols]
    so = [c.take(take) for c in order_cols]

    part_starts = _segment_starts(sp) if sp else _one_segment(n)
    peer_starts = part_starts | (_segment_starts(sp + so) if so else part_starts)
    pstart = _part_start_idx(part_starts)
    pos = np.arange(n) - pstart  # 0-based position within partition

    cumulative = bool(w.order_by) or w.frame in ("cumulative", "rows_cumulative")
    # ROWS frames end at the row itself; RANGE frames extend to the last peer
    frame_end = (
        np.arange(n) if w.frame == "rows_cumulative" else _peer_end_idx(peer_starts)
    )

    name = w.name
    out_sorted: pa.Array
    if name == "row_number":
        out_sorted = pa.array(pos + 1, pa.int64())
    elif name == "rank":
        peer_first = _part_start_idx(peer_starts)
        out_sorted = pa.array(peer_first - pstart + 1, pa.int64())
    elif name == "dense_rank":
        dr = np.cumsum(peer_starts)
        out_sorted = pa.array(dr - dr[pstart] + 1, pa.int64())
    elif name == "ntile":
        out_sorted = pa.array(_ntile(w, table, pos, part_starts), pa.int64())
    elif name in ("lag", "lead"):
        out_sorted = _lag_lead(w, table, take, pstart, part_starts, name)
    elif name in ("first_value", "last_value"):
        if not w.args:
            raise WindowError(f"{name}(expr) requires an argument")
        v = _evaluate(w.args[0], table).take(take)
        if name == "first_value":
            out_sorted = v.take(pa.array(pstart))
        elif cumulative:
            out_sorted = v.take(pa.array(frame_end))
        else:
            # whole-partition frame: last row of the partition
            pend = _peer_end_idx(part_starts)
            out_sorted = v.take(pa.array(pend))
    elif name in ("count", "count_star", "sum", "avg", "min", "max"):
        out_sorted = _window_agg(
            w, table, take, part_starts, frame_end, pstart, pos, cumulative
        )
    else:
        raise WindowError(f"unsupported window function {name}")

    # scatter back to input order
    inv = np.empty(n, dtype=np.int64)
    inv[sort_idx] = np.arange(n)
    return out_sorted.take(pa.array(inv))


def _literal_value(e: S.Expr, what: str):
    if isinstance(e, S.Literal):
        return e.value
    if isinstance(e, S.UnaryOp) and e.op == "-" and isinstance(e.operand, S.Literal):
        return -e.operand.value
    raise WindowError(f"{what} must be a literal")


def _one_segment(n: int) -> np.ndarray:
    s = np.zeros(n, bool)
    if n:
        s[0] = True
    return s


def _ntile(w: S.WindowCall, table: pa.Table, pos: np.ndarray, part_starts: np.ndarray) -> np.ndarray:
    if not w.args:
        raise WindowError("ntile(n) requires an integer literal")
    tiles = int(_literal_value(w.args[0], "ntile(n)"))
    if tiles <= 0:
        raise WindowError("ntile(n) requires n > 0")
    n = len(pos)
    # partition sizes, broadcast to rows
    start_idx = np.nonzero(part_starts)[0]
    sizes = np.diff(np.append(start_idx, n))
    size_per_row = np.repeat(sizes, sizes)
    base = size_per_row // tiles
    rem = size_per_row % tiles
    cut = rem * (base + 1)
    big = pos < cut
    with np.errstate(divide="ignore", invalid="ignore"):
        t_big = pos // np.maximum(base + 1, 1)
        t_small = rem + (pos - cut) // np.maximum(base, 1)
    return np.where(big, t_big, t_small) + 1


def _lag_lead(
    w: S.WindowCall,
    table: pa.Table,
    take: pa.Array,
    pstart: np.ndarray,
    part_starts: np.ndarray,
    name: str,
) -> pa.Array:
    if not w.args:
        raise WindowError(f"{name}(expr[, offset[, default]])")
    v = _evaluate(w.args[0], table).take(take)
    off = 1
    if len(w.args) > 1:
        off = int(_literal_value(w.args[1], f"{name} offset"))
    default = None
    if len(w.args) > 2:
        default = _literal_value(w.args[2], f"{name} default")
    if off < 0:
        # SQL: lag(x, -n) == lead(x, n) — normalize so the partition-edge
        # checks below match the actual read direction
        name = "lead" if name == "lag" else "lag"
        off = -off
    n = len(pstart)
    pos = np.arange(n)
    pend = _peer_end_idx(part_starts)  # last index of partition
    if name == "lag":
        src = pos - off
        bad = src < pstart
    else:
        src = pos + off
        bad = src > pend
    src = np.clip(src, 0, max(n - 1, 0))
    out = v.take(pa.array(src))
    if bad.any():
        mask = pa.array(~bad)
        if default is None:
            out = pc.if_else(mask, out, pa.scalar(None, out.type))
        else:
            out = pc.if_else(mask, out, pa.scalar(default, type=out.type))
    return out


def _cum_with_resets(vals: np.ndarray, part_starts: np.ndarray, op: str) -> np.ndarray:
    """Running sum/min/max with resets at partition starts, vectorized."""
    if op == "sum":
        cs = np.cumsum(vals)
        starts_idx = np.nonzero(part_starts)[0]
        # subtract the cumsum just before each partition's first row
        base = cs[starts_idx] - vals[starts_idx]
        seg_id = np.cumsum(part_starts) - 1
        return cs - base[seg_id]
    # min/max: loop over partitions (counts are small relative to rows;
    # each partition is a vectorized accumulate)
    out = np.empty_like(vals)
    starts = np.nonzero(part_starts)[0]
    bounds = np.append(starts, len(vals))
    fn = np.minimum.accumulate if op == "min" else np.maximum.accumulate
    for i in range(len(starts)):
        lo, hi = bounds[i], bounds[i + 1]
        out[lo:hi] = fn(vals[lo:hi])
    return out


def _window_agg(
    w: S.WindowCall,
    table: pa.Table,
    take: pa.Array,
    part_starts: np.ndarray,
    frame_end: np.ndarray,
    pstart: np.ndarray,
    pos: np.ndarray,
    cumulative: bool,
) -> pa.Array:
    n = len(pos)
    name = w.name
    star = not w.args or isinstance(w.args[0], S.Star)
    if star and name != "count":
        raise WindowError(f"{name}(*) is not valid")
    int_result = False
    if star:
        valid = np.ones(n, bool)
        vals = np.ones(n, np.float64)
    else:
        arr = _evaluate(w.args[0], table).take(take)
        valid = pc.is_valid(arr).to_numpy(zero_copy_only=False).astype(bool)
        if name == "count":
            vals = valid.astype(np.float64)
        else:
            t = arr.type
            if not (
                pa.types.is_integer(t) or pa.types.is_floating(t) or pa.types.is_boolean(t)
            ):
                raise WindowError(
                    f"windowed {name}() over a {t} column is not supported"
                )
            # integer inputs keep integer output for sum/min/max (matches
            # the non-window aggregate path); avg is always double
            int_result = pa.types.is_integer(t) and name != "avg"
            vals = np.asarray(
                pc.cast(arr, pa.float64(), safe=False).fill_null(0.0).to_numpy(
                    zero_copy_only=False
                ),
                np.float64,
            )

    def out_arr(vals_out: np.ndarray, seen: np.ndarray) -> pa.Array:
        if int_result:
            return pa.array(vals_out.astype(np.int64), mask=~seen)
        return pa.array(vals_out, mask=~seen)

    if not cumulative:
        # whole-partition aggregate broadcast to every row
        starts_idx = np.nonzero(part_starts)[0]
        bounds = np.append(starts_idx, n)
        sizes = np.diff(bounds)
        cnt = np.add.reduceat(valid.astype(np.float64), starts_idx)
        seen = np.repeat(cnt, sizes) > 0
        if name in ("count", "count_star"):
            return pa.array(np.repeat(cnt, sizes).astype(np.int64))
        if name == "sum":
            seg = np.add.reduceat(np.where(valid, vals, 0.0), starts_idx)
            return out_arr(np.repeat(seg, sizes), seen)
        if name == "avg":
            seg = np.add.reduceat(np.where(valid, vals, 0.0), starts_idx)
            with np.errstate(divide="ignore", invalid="ignore"):
                seg = np.where(cnt > 0, seg / np.maximum(cnt, 1), np.nan)
            return pa.array(np.repeat(seg, sizes), mask=~seen)
        # min/max
        fill = np.inf if name == "min" else -np.inf
        seg_fn = np.minimum.reduceat if name == "min" else np.maximum.reduceat
        seg = seg_fn(np.where(valid, vals, fill), starts_idx)
        return out_arr(np.repeat(seg, sizes), seen)

    # cumulative: running value read at the frame end (ROWS: own row;
    # RANGE: last peer)
    cnt = _cum_with_resets(valid.astype(np.float64), part_starts, "sum")[frame_end]
    seen = cnt > 0
    if name in ("count", "count_star"):
        return pa.array(cnt.astype(np.int64))
    if name == "sum":
        run = _cum_with_resets(np.where(valid, vals, 0.0), part_starts, "sum")[frame_end]
        return out_arr(run, seen)
    if name == "avg":
        run = _cum_with_resets(np.where(valid, vals, 0.0), part_starts, "sum")[frame_end]
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.where(cnt > 0, run / np.maximum(cnt, 1), np.nan)
        return pa.array(out, mask=~seen)
    fill = np.inf if name == "min" else -np.inf
    run = _cum_with_resets(np.where(valid, vals, fill), part_starts, name)[frame_end]
    return out_arr(run, seen)


def attach_window_columns(
    table: pa.Table, windows: list[S.WindowCall]
) -> tuple[pa.Table, dict[str, str]]:
    """Compute every distinct window call as a `__w{i}` column appended to
    `table`; returns (augmented table, repr(WindowCall) -> column name)."""
    mapping: dict[str, str] = {}
    for w in windows:
        key = repr(w)
        if key in mapping:
            continue
        col = f"__w{len(mapping)}"
        table = table.append_column(col, compute_window(w, table))
        mapping[key] = col
    return table, mapping
