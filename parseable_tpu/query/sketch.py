"""Mergeable quantile sketch for approx_percentile_cont / approx_median.

The reference gets approximate percentiles from DataFusion's t-digest
(`approx_percentile_cont`, /root/reference/src/query/mod.rs:212-276 —
registered with the session's function set). This implementation keeps the
same mergeable-state contract with a shape chosen for this engine:

- EXACT below 1024 values per group: raw f64 values, quantiles via
  np.quantile (linear interpolation — matches percentile_cont semantics).
  Most real group-bys over log data have modest per-group counts, so most
  results are exact, not approximate.
- Log-scale histogram beyond: 1024 bins per sign over |v| in
  [2^-40, 2^40) plus an exact-zero count — ~5.6% worst-case relative
  error per value (2^(80/1024) per bin, interpolated), constant memory,
  exact tracked min/max clamp the tails so p0/p100 are exact.
- Merge is histogram addition (small sides fold in), so block partials
  and distributed-tree merges compose associatively.
"""

from __future__ import annotations

import numpy as np

SMALL = 1024  # raw values kept before folding into the histogram
BINS = 1024
LOG_LO = -40.0  # log2 of the smallest binned magnitude
LOG_HI = 40.0
_SCALE = BINS / (LOG_HI - LOG_LO)


def _bin_of(mag_log2: np.ndarray) -> np.ndarray:
    return np.clip(
        ((mag_log2 - LOG_LO) * _SCALE).astype(np.int64), 0, BINS - 1
    )


def _bin_edges(idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    lo = idx / _SCALE + LOG_LO
    return lo, lo + 1.0 / _SCALE


class QuantileSketch:
    __slots__ = ("small", "pos", "neg", "zeros", "vmin", "vmax", "count")

    def __init__(self) -> None:
        self.small: list[np.ndarray] | None = []  # None once folded
        self.pos: np.ndarray | None = None  # f64 [BINS]
        self.neg: np.ndarray | None = None
        self.zeros = 0.0
        self.vmin = np.inf
        self.vmax = -np.inf
        self.count = 0

    # ------------------------------------------------------------------ build

    def update(self, values: np.ndarray) -> None:
        """Fold a block's non-null values (any float/int ndarray) in."""
        v = np.asarray(values, np.float64)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            return
        self.count += len(v)
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        if self.small is not None:
            self.small.append(v)
            if sum(len(a) for a in self.small) > SMALL:
                self._fold()
            return
        self._bin(v)

    def _fold(self) -> None:
        vals = np.concatenate(self.small) if self.small else np.empty(0)
        self.small = None
        self.pos = np.zeros(BINS)
        self.neg = np.zeros(BINS)
        if len(vals):
            self._bin(vals)

    def _bin(self, v: np.ndarray) -> None:
        zero = v == 0.0
        self.zeros += float(zero.sum())
        for sign, hist in ((1, self.pos), (-1, self.neg)):
            sel = (v > 0) if sign > 0 else (v < 0)
            if not sel.any():
                continue
            # clip BEFORE the int cast: +/-inf (natural in log data from
            # casts/division) must land in the extreme bins, not wrap
            # through undefined int64 conversion into bin 0
            mags = np.clip(
                np.log2(np.abs(v[sel])), LOG_LO, LOG_HI - 1e-9
            )
            np.add.at(hist, _bin_of(mags), 1.0)

    # ------------------------------------------------------------------ merge

    def merge(self, other: "QuantileSketch") -> None:
        if other.count == 0:
            return
        self.count += other.count
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        if self.small is not None and other.small is not None:
            self.small.extend(other.small)
            if sum(len(a) for a in self.small) > SMALL:
                self._fold()
            return
        if self.small is not None:
            self._fold()
        if other.small is not None:
            for a in other.small:
                self._bin(a)  # bins + zero-counts other's raw values
            return
        self.pos += other.pos
        self.neg += other.neg
        self.zeros += other.zeros

    # ---------------------------------------------------------------- queries

    def quantile(self, p: float) -> float | None:
        if self.count == 0:
            return None
        p = min(max(p, 0.0), 1.0)
        if self.small is not None:
            vals = (
                np.concatenate(self.small) if self.small else np.empty(0)
            )
            if len(vals) == 0:
                return None
            return float(np.quantile(vals, p, method="linear"))
        # histogram walk: negatives (descending magnitude), zeros, positives
        target = p * (self.count - 1)
        neg_counts = self.neg[::-1]  # most-negative first
        blocks: list[tuple[float, int, int]] = []  # (count, sign, bin_idx)
        for c, idx in zip(neg_counts, range(BINS - 1, -1, -1)):
            if c:
                blocks.append((float(c), -1, idx))
        if self.zeros:
            blocks.append((float(self.zeros), 0, 0))
        for idx in range(BINS):
            if self.pos[idx]:
                blocks.append((float(self.pos[idx]), 1, idx))
        acc = 0.0
        for c, sign, idx in blocks:
            if acc + c > target:
                frac = (target - acc) / c
                if sign == 0:
                    return 0.0
                lo, hi = _bin_edges(np.array([idx]))
                lo_v, hi_v = 2.0 ** lo[0], 2.0 ** hi[0]
                if sign < 0:
                    # negative bins walk from -hi_v toward -lo_v
                    val = -(hi_v - frac * (hi_v - lo_v))
                else:
                    val = lo_v + frac * (hi_v - lo_v)
                return float(min(max(val, self.vmin), self.vmax))
            acc += c
        return self.vmax if self.vmax > -np.inf else None
