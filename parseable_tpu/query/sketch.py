"""Mergeable quantile sketch for approx_percentile_cont / approx_median.

The reference gets approximate percentiles from DataFusion's t-digest
(`approx_percentile_cont`, /root/reference/src/query/mod.rs:212-276 —
registered with the session's function set). This implementation keeps the
same mergeable-state contract with a shape chosen for this engine:

- EXACT below 1024 values per group: raw f64 values, quantiles via
  np.quantile (linear interpolation — matches percentile_cont semantics).
  Most real group-bys over log data have modest per-group counts, so most
  results are exact, not approximate.
- Log-scale histogram beyond: 1024 bins per sign over |v| in
  [2^-40, 2^40) plus an exact-zero count — ~5.6% worst-case relative
  error per value (2^(80/1024) per bin, interpolated), constant memory,
  exact tracked min/max clamp the tails so p0/p100 are exact.
- Merge is histogram addition (small sides fold in), so block partials
  and distributed-tree merges compose associatively.
"""

from __future__ import annotations

import numpy as np

SMALL = 1024  # raw values kept before folding into the histogram
BINS = 1024
LOG_LO = -40.0  # log2 of the smallest binned magnitude
LOG_HI = 40.0
_SCALE = BINS / (LOG_HI - LOG_LO)


def _bin_of(mag_log2: np.ndarray) -> np.ndarray:
    return np.clip(
        ((mag_log2 - LOG_LO) * _SCALE).astype(np.int64), 0, BINS - 1
    )


def _bin_edges(idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    lo = idx / _SCALE + LOG_LO
    return lo, lo + 1.0 / _SCALE


# Flat device-histogram layout (query/executor_tpu.py device percentiles):
# [0, BINS) negative bins (indexed by |v| bin), [BINS, 2*BINS) positive
# bins, [2*BINS] exact-zero count — one f32 row per group, mergeable by
# addition and convertible to a QuantileSketch via `from_device_hist`.
DEVICE_NB = 2 * BINS + 1


class QuantileSketch:
    __slots__ = ("small", "pos", "neg", "zeros", "vmin", "vmax", "count")

    def __init__(self) -> None:
        self.small: list[np.ndarray] | None = []  # None once folded
        self.pos: np.ndarray | None = None  # f64 [BINS]
        self.neg: np.ndarray | None = None
        self.zeros = 0.0
        self.vmin = np.inf
        self.vmax = -np.inf
        self.count = 0

    # ------------------------------------------------------------------ build

    def update(self, values: np.ndarray) -> None:
        """Fold a block's non-null values (any float/int ndarray) in."""
        v = np.asarray(values, np.float64)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            return
        self.count += len(v)
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        if self.small is not None:
            self.small.append(v)
            if sum(len(a) for a in self.small) > SMALL:
                self._fold()
            return
        self._bin(v)

    def _fold(self) -> None:
        vals = np.concatenate(self.small) if self.small else np.empty(0)
        self.small = None
        self.pos = np.zeros(BINS)
        self.neg = np.zeros(BINS)
        if len(vals):
            self._bin(vals)

    def _bin(self, v: np.ndarray) -> None:
        zero = v == 0.0
        self.zeros += float(zero.sum())
        for sign, hist in ((1, self.pos), (-1, self.neg)):
            sel = (v > 0) if sign > 0 else (v < 0)
            if not sel.any():
                continue
            # clip BEFORE the int cast: +/-inf (natural in log data from
            # casts/division) must land in the extreme bins, not wrap
            # through undefined int64 conversion into bin 0
            mags = np.clip(
                np.log2(np.abs(v[sel])), LOG_LO, LOG_HI - 1e-9
            )
            np.add.at(hist, _bin_of(mags), 1.0)

    @classmethod
    def from_device_hist(
        cls, hist: np.ndarray, vmin: float, vmax: float
    ) -> "QuantileSketch":
        """One group's device histogram row (DEVICE_NB layout) -> sketch in
        histogram mode (device blocks always bin; exactness below SMALL is a
        host-path property only)."""
        sk = cls()
        sk.small = None
        sk.neg = np.asarray(hist[:BINS], np.float64).copy()
        sk.pos = np.asarray(hist[BINS : 2 * BINS], np.float64).copy()
        sk.zeros = float(hist[2 * BINS])
        sk.count = int(round(float(hist.sum())))
        if sk.count:
            sk.vmin = float(vmin)
            sk.vmax = float(vmax)
        return sk

    def copy(self) -> "QuantileSketch":
        """Deep-enough copy: safe to merge into without mutating the source
        (raw-value arrays are shared but never mutated in place)."""
        sk = QuantileSketch()
        sk.small = list(self.small) if self.small is not None else None
        sk.pos = None if self.pos is None else self.pos.copy()
        sk.neg = None if self.neg is None else self.neg.copy()
        sk.zeros = self.zeros
        sk.vmin = self.vmin
        sk.vmax = self.vmax
        sk.count = self.count
        return sk

    # ------------------------------------------------------------------ merge

    def merge(self, other: "QuantileSketch") -> None:
        if other.count == 0:
            return
        self.count += other.count
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        if self.small is not None and other.small is not None:
            self.small.extend(other.small)
            if sum(len(a) for a in self.small) > SMALL:
                self._fold()
            return
        if self.small is not None:
            self._fold()
        if other.small is not None:
            for a in other.small:
                self._bin(a)  # bins + zero-counts other's raw values
            return
        self.pos += other.pos
        self.neg += other.neg
        self.zeros += other.zeros

    # ---------------------------------------------------------------- queries

    def quantile(self, p: float) -> float | None:
        if self.count == 0:
            return None
        p = min(max(p, 0.0), 1.0)
        if self.small is not None:
            vals = (
                np.concatenate(self.small) if self.small else np.empty(0)
            )
            if len(vals) == 0:
                return None
            return float(np.quantile(vals, p, method="linear"))
        # histogram walk: negatives (descending magnitude), zeros, positives
        # (the vectorized device-readback twin is hist_quantile below —
        # keep their interpolation semantics in lockstep)
        target = p * (self.count - 1)
        neg_counts = self.neg[::-1]  # most-negative first
        blocks: list[tuple[float, int, int]] = []  # (count, sign, bin_idx)
        for c, idx in zip(neg_counts, range(BINS - 1, -1, -1)):
            if c:
                blocks.append((float(c), -1, idx))
        if self.zeros:
            blocks.append((float(self.zeros), 0, 0))
        for idx in range(BINS):
            if self.pos[idx]:
                blocks.append((float(self.pos[idx]), 1, idx))
        acc = 0.0
        for c, sign, idx in blocks:
            if acc + c > target:
                frac = (target - acc) / c
                if sign == 0:
                    return 0.0
                lo, hi = _bin_edges(np.array([idx]))
                lo_v, hi_v = 2.0 ** lo[0], 2.0 ** hi[0]
                if sign < 0:
                    # negative bins walk from -hi_v toward -lo_v
                    val = -(hi_v - frac * (hi_v - lo_v))
                else:
                    val = lo_v + frac * (hi_v - lo_v)
                return float(min(max(val, self.vmin), self.vmax))
            acc += c
        return self.vmax if self.vmax > -np.inf else None


def hist_quantile(
    hists: np.ndarray,
    vmins: np.ndarray,
    vmaxs: np.ndarray,
    p: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized quantiles over device histogram rows.

    `hists` is (n, DEVICE_NB) in the device layout; returns (values f64[n],
    valid bool[n]). Semantically identical to QuantileSketch.quantile on a
    folded sketch: blocks walk in ascending value order (negatives by
    descending magnitude, zeros, positives), linear interpolation inside the
    landing bin, result clamped to the group's exact [vmin, vmax].
    """
    n = hists.shape[0]
    p = min(max(float(p), 0.0), 1.0)
    counts = hists.sum(axis=1)
    valid = counts > 0
    if not valid.any():
        return np.zeros(n), valid
    # ascending-value order: reversed neg bins | zeros | pos bins
    ordered = np.concatenate(
        [hists[:, BINS - 1 :: -1], hists[:, 2 * BINS : 2 * BINS + 1], hists[:, BINS : 2 * BINS]],
        axis=1,
    ).astype(np.float64)
    cum = np.cumsum(ordered, axis=1)
    target = p * (counts - 1.0)
    # first ordered block where the cumulative count exceeds the target
    j = np.argmax(cum > target[:, None], axis=1)
    before = np.where(j > 0, np.take_along_axis(cum, np.maximum(j - 1, 0)[:, None], 1)[:, 0], 0.0)
    c = np.take_along_axis(ordered, j[:, None], 1)[:, 0]
    frac = np.divide(target - before, c, out=np.zeros(n), where=c > 0)
    # map ordered index back to (sign, magnitude bin)
    neg = j < BINS
    zero = j == BINS
    pos_bin = np.clip(j - BINS - 1, 0, BINS - 1)
    neg_bin = np.clip(BINS - 1 - j, 0, BINS - 1)
    idx = np.where(neg, neg_bin, pos_bin)
    lo, hi = _bin_edges(idx)
    lo_v, hi_v = 2.0**lo, 2.0**hi
    val = np.where(
        neg,
        -(hi_v - frac * (hi_v - lo_v)),
        lo_v + frac * (hi_v - lo_v),
    )
    val = np.where(zero, 0.0, val)
    # groups whose cumulative never exceeds target (p == 1 edge): vmax
    overrun = np.take_along_axis(cum, np.full((n, 1), ordered.shape[1] - 1), 1)[:, 0] <= target
    val = np.where(overrun, vmaxs, val)
    val = np.minimum(np.maximum(val, vmins), vmaxs)
    return val, valid
