"""Multi-stream execution: joins + subquery materialization.

The reference gets arbitrary SQL (joins, subqueries) from DataFusion
(src/query/mod.rs:212-276), which is what makes saved correlations
(src/correlation.rs) executable. Here:

- **Subqueries** (uncorrelated, the dialect's need) materialize first:
  each inner SELECT runs as its own single-stream query; IN-subqueries
  become literal IN-lists, scalar subqueries become literals.
- **Joins** materialize each side through the normal single-stream scan
  (staging + hot tier + manifest-pruned parquet, with the API time range
  applied per stream), qualify columns as `alias.col`, and hash-join via
  Arrow's C++ join kernel (pa.Table.join). Equality conditions drive the
  hash join; residual ON conditions apply as a post-join filter.

Joins run on the CPU engine: they're row-level merges feeding projections,
not the dense aggregation shape the TPU path accelerates. An aggregation
OVER a join still benefits — the joined table feeds the standard executor,
which the session can point at either engine.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

import pyarrow as pa

from parseable_tpu.query import sql as S
from parseable_tpu.query.executor import ExecError, MemoryLimitExceeded, _arr, evaluate

if TYPE_CHECKING:
    from parseable_tpu.query.session import QuerySession

logger = logging.getLogger(__name__)

MAX_SUBQUERY_ROWS = 100_000


class MultiStreamError(ValueError):
    pass


# ------------------------------------------------------------- subqueries


def resolve_subqueries(e: S.Expr | None, run_select) -> S.Expr | None:
    """Replace Subquery nodes with materialized literals.

    `run_select(select) -> pa.Table` executes an inner SELECT (the session
    bounds nesting depth there, since nested subqueries re-enter through
    it). IN-subqueries become literal lists (capped at MAX_SUBQUERY_ROWS);
    scalar subqueries must yield exactly one column and at most one row.
    """
    if e is None:
        return None

    def rec(x):
        return resolve_subqueries(x, run_select)

    if isinstance(e, S.Subquery):
        table = run_select(e.select)
        if table.num_columns != 1:
            raise MultiStreamError("scalar subquery must select exactly one column")
        if table.num_rows > 1:
            raise MultiStreamError("scalar subquery returned more than one row")
        v = table.column(0).to_pylist()[0] if table.num_rows else None
        return S.Literal(v)
    if isinstance(e, S.InList):
        if len(e.items) == 1 and isinstance(e.items[0], S.Subquery):
            table = run_select(e.items[0].select)
            if table.num_columns != 1:
                raise MultiStreamError("IN subquery must select exactly one column")
            if table.num_rows > MAX_SUBQUERY_ROWS:
                raise MultiStreamError(
                    f"IN subquery produced {table.num_rows} rows (max {MAX_SUBQUERY_ROWS})"
                )
            values = [v for v in table.column(0).to_pylist() if v is not None]
            return S.InList(rec(e.expr), [S.Literal(v) for v in values], e.negated)
        return S.InList(rec(e.expr), [rec(i) for i in e.items], e.negated)
    if isinstance(e, S.BinaryOp):
        return S.BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, S.UnaryOp):
        return S.UnaryOp(e.op, rec(e.operand))
    if isinstance(e, S.Between):
        return S.Between(rec(e.expr), rec(e.low), rec(e.high), e.negated)
    if isinstance(e, S.IsNull):
        return S.IsNull(rec(e.expr), e.negated)
    if isinstance(e, S.FunctionCall):
        return S.FunctionCall(e.name, [rec(a) for a in e.args], e.distinct)
    if isinstance(e, S.Cast):
        return S.Cast(rec(e.expr), e.type_name)
    if isinstance(e, S.Case):
        return S.Case(
            [(rec(w), rec(t)) for w, t in e.whens],
            rec(e.else_expr) if e.else_expr else None,
        )
    return e


# ------------------------------------------------------------------- joins


def _split_on(on: S.Expr | None, left_aliases: set[str], right_alias: str):
    """Split an ON tree into equality key pairs (left_col, right_col) and a
    residual expression applied post-join."""
    eq_pairs: list[tuple[S.Column, S.Column]] = []
    residual: list[S.Expr] = []

    def side(col: S.Column) -> str | None:
        if col.table is None:
            return None
        if col.table == right_alias:
            return "right"
        if col.table in left_aliases:
            return "left"
        return None

    def walk(e: S.Expr) -> None:
        if isinstance(e, S.BinaryOp) and e.op == "and":
            walk(e.left)
            walk(e.right)
            return
        if (
            isinstance(e, S.BinaryOp)
            and e.op == "="
            and isinstance(e.left, S.Column)
            and isinstance(e.right, S.Column)
        ):
            ls, rs = side(e.left), side(e.right)
            if ls == "left" and rs == "right":
                eq_pairs.append((e.left, e.right))
                return
            if ls == "right" and rs == "left":
                eq_pairs.append((e.right, e.left))
                return
        residual.append(e)

    if on is not None:
        walk(on)
    return eq_pairs, residual


def _qualify(table: pa.Table, alias: str) -> pa.Table:
    return table.rename_columns([f"{alias}.{c}" for c in table.column_names])


def execute_join(
    base: tuple[str, pa.Table],
    joins: list[tuple[S.Join, pa.Table]],
    memory_limit: int | None = None,
) -> pa.Table:
    """Fold joins left-to-right with Arrow's hash join."""
    alias0, t0 = base
    out = _qualify(t0, alias0)
    left_aliases = {alias0}
    for join, right_raw in joins:
        ralias = join.alias or join.table
        if ralias in left_aliases:
            raise MultiStreamError(f"duplicate table alias {ralias!r}")
        right = _qualify(right_raw, ralias)
        if join.kind == "cross":
            out = _cross_join(out, right)
        else:
            eq_pairs, residual = _split_on(join.on, left_aliases, ralias)
            if not eq_pairs:
                raise MultiStreamError(
                    "JOIN ... ON needs at least one equality between the two sides"
                )
            left_keys = [f"{c.table}.{c.name}" for c, _ in eq_pairs]
            right_keys = [f"{c.table}.{c.name}" for _, c in eq_pairs]
            # keep the right key columns through the join (Arrow drops
            # right_keys from the output): duplicate under temp names so
            # LEFT-join null semantics survive, then restore.
            tmp_names = [f"__rk{i}" for i in range(len(right_keys))]
            for tmp, rk in zip(tmp_names, right_keys):
                right = right.append_column(tmp, right.column(rk))
            join_type = "left outer" if join.kind == "left" else "inner"
            out = out.join(
                right,
                keys=left_keys,
                right_keys=right_keys,
                join_type=join_type,
            )
            for tmp, rk in zip(tmp_names, right_keys):
                idx = out.column_names.index(tmp)
                out = out.set_column(idx, rk, out.column(tmp))
            if residual:
                mask = None
                import pyarrow.compute as pc

                for r in residual:
                    m = _arr(evaluate(r, out), out)
                    mask = m if mask is None else pc.and_kleene(mask, m)
                if mask is not None:
                    if join.kind == "left":
                        # rows with no match keep NULL right side; Kleene
                        # nulls (unknown) must not drop them
                        mask = pc.fill_null(mask, True)
                    out = out.filter(mask)
        left_aliases.add(ralias)
        if memory_limit is not None and out.nbytes > memory_limit:
            raise MemoryLimitExceeded(
                f"join intermediate holds {out.nbytes} bytes (limit {memory_limit})"
            )
    return out


def _cross_join(left: pa.Table, right: pa.Table) -> pa.Table:
    if left.num_rows * right.num_rows > 5_000_000:
        raise MultiStreamError("cross join too large")
    import numpy as np

    li = np.repeat(np.arange(left.num_rows), right.num_rows)
    ri = np.tile(np.arange(right.num_rows), left.num_rows)
    lt = left.take(pa.array(li))
    rt = right.take(pa.array(ri))
    cols = {n: lt.column(n) for n in lt.column_names}
    cols.update({n: rt.column(n) for n in rt.column_names})
    return pa.table(cols)


def qualify_unqualified(e: S.Expr | None, owner_of: dict[str, str]) -> S.Expr | None:
    """Attach table qualifiers to bare columns using schema ownership
    (unambiguous columns only; ambiguous bare refs raise)."""
    if e is None:
        return None

    def rec(x):
        return qualify_unqualified(x, owner_of)

    if isinstance(x := e, S.Column):
        if x.table is None:
            owner = owner_of.get(x.name)
            if owner == "__ambiguous__":
                raise MultiStreamError(f"ambiguous column {x.name!r}; qualify it")
            if owner is not None:
                return S.Column(x.name, table=owner)
        return x
    if isinstance(e, S.BinaryOp):
        return S.BinaryOp(e.op, rec(e.left), rec(e.right))
    if isinstance(e, S.UnaryOp):
        return S.UnaryOp(e.op, rec(e.operand))
    if isinstance(e, S.InList):
        return S.InList(rec(e.expr), [rec(i) for i in e.items], e.negated)
    if isinstance(e, S.Between):
        return S.Between(rec(e.expr), rec(e.low), rec(e.high), e.negated)
    if isinstance(e, S.IsNull):
        return S.IsNull(rec(e.expr), e.negated)
    if isinstance(e, S.FunctionCall):
        return S.FunctionCall(e.name, [rec(a) for a in e.args], e.distinct)
    if isinstance(e, S.Cast):
        return S.Cast(rec(e.expr), e.type_name)
    if isinstance(e, S.Case):
        return S.Case(
            [(rec(w), rec(t)) for w, t in e.whens],
            rec(e.else_expr) if e.else_expr else None,
        )
    return e
