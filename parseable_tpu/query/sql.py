"""SQL front end: lexer + recursive-descent parser -> AST.

The reference embeds DataFusion for SQL (src/query/mod.rs); this build has no
embeddable SQL engine available, so we parse the observability SQL dialect
ourselves. Coverage targets every query shape the reference's handlers,
alerts and benchmarks issue:

    SELECT [DISTINCT] exprs FROM stream
      [WHERE expr] [GROUP BY exprs] [HAVING expr]
      [ORDER BY expr [ASC|DESC], ...] [LIMIT n [OFFSET m]]

with operators AND/OR/NOT, comparisons, arithmetic, IN, BETWEEN, LIKE/ILIKE,
IS [NOT] NULL, CASE WHEN, CAST, and functions (count/sum/avg/min/max,
count(distinct), approx_distinct, approx_percentile_cont, approx_median,
stddev/var, date_bin, date_trunc, to_timestamp, lower/upper/length/
coalesce, ...). `EXPLAIN [ANALYZE]` prefixes any statement.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# --------------------------------------------------------------------- lexer

KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order",
    "limit", "offset", "and", "or", "not", "in", "between", "like", "ilike",
    "is", "null", "as", "asc", "desc", "case", "when", "then", "else", "end",
    "cast", "true", "false", "interval",
}

# Contextual words recognized only inside the FROM clause — log fields named
# "left"/"on"/"join" must keep parsing as plain columns elsewhere.
JOIN_WORDS = {"join", "inner", "left", "right", "full", "outer", "cross", "on"}

# contextual words that terminate an implicit alias position ("FROM t UNION"
# must not read UNION as t's alias)
NON_ALIAS_WORDS = JOIN_WORDS | {"union"}


@dataclass
class Token:
    kind: str  # kw | ident | number | string | op | eof
    value: Any
    pos: int


class SqlError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # line comment
            while i < n and sql[i] != "\n":
                i += 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot) or sql[j] in "eE" or (sql[j] in "+-" and sql[j - 1] in "eE")):
                if sql[j] == ".":
                    seen_dot = True
                j += 1
            text = sql[i:j]
            try:
                value = int(text)
            except ValueError:
                try:
                    value = float(text)
                except ValueError:
                    # malformed exponent like "1.5e" must surface as a
                    # parse error, not an unhandled 500
                    raise SqlError(f"invalid number literal {text!r} at {i}") from None
            tokens.append(Token("number", value, i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            lw = word.lower()
            if lw in KEYWORDS:
                tokens.append(Token("kw", lw, i))
            else:
                tokens.append(Token("ident", word, i))
            i = j
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise SqlError(f"unterminated string at {i}")
            tokens.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlError(f"unterminated quoted identifier at {i}")
            tokens.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise SqlError(f"unterminated quoted identifier at {i}")
            tokens.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        two = sql[i : i + 2]
        if two in ("<=", ">=", "!=", "<>", "||"):
            tokens.append(Token("op", "!=" if two == "<>" else two, i))
            i += 2
            continue
        if c in "+-*/%(),.<>=;":
            tokens.append(Token("op", c, i))
            i += 1
            continue
        raise SqlError(f"unexpected character {c!r} at {i}")
    tokens.append(Token("eof", None, n))
    return tokens


# ----------------------------------------------------------------------- AST


@dataclass
class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any  # int | float | str | bool | None


@dataclass
class Column(Expr):
    name: str
    table: str | None = None


@dataclass
class Star(Expr):
    table: str | None = None  # `alias.*` keeps its qualifier


@dataclass
class UnaryOp(Expr):
    op: str  # "-" | "not"
    operand: Expr


@dataclass
class BinaryOp(Expr):
    op: str  # + - * / % = != < <= > >= and or like ilike ||
    left: Expr
    right: Expr


@dataclass
class InList(Expr):
    expr: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass
class FunctionCall(Expr):
    name: str  # lowercase
    args: list[Expr]
    distinct: bool = False


@dataclass
class Cast(Expr):
    expr: Expr
    type_name: str


@dataclass
class Case(Expr):
    whens: list[tuple[Expr, Expr]]
    else_expr: Expr | None = None


@dataclass
class IntervalLit(Expr):
    text: str  # e.g. "1 minute"


@dataclass
class Subquery(Expr):
    """A nested SELECT used as a scalar or IN-list source. Resolved
    (materialized) by the session before execution — the executors never
    see one (reference: DataFusion subquery decorrelation; here the
    observability dialect only needs uncorrelated subqueries)."""

    select: "Select"


@dataclass
class WindowCall(Expr):
    """`fn(args) OVER (PARTITION BY ... ORDER BY ... [frame])`.

    Reference parity: the DataFusion window functions dashboards and the
    queryContext handler lean on (src/query/mod.rs:212-276 gives the
    reference the full window surface; src/handlers/http/query_context.rs
    pages rows around an anchor — expressible as a row_number window).
    Frames: only UNBOUNDED PRECEDING..CURRENT ROW — implicit (RANGE
    semantics when ORDER BY is present, whole partition otherwise) or
    explicit. `frame` is "cumulative" (RANGE: peers share the frame) |
    "rows_cumulative" (ROWS: each row ends its own frame) | None
    (default-by-order-presence).
    """

    name: str  # lowercase function name
    args: list[Expr]
    partition_by: list[Expr] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    frame: str | None = None


@dataclass
class Join:
    table: str
    alias: str | None
    kind: str  # "inner" | "left" | "cross"
    on: Expr | None


@dataclass
class SelectItem:
    expr: Expr
    alias: str | None = None


@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False


@dataclass
class Select:
    items: list[SelectItem]
    table: str | None = None
    where: Expr | None = None
    group_by: list[Expr] = field(default_factory=list)
    having: Expr | None = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None
    distinct: bool = False
    table_alias: str | None = None
    joins: list[Join] = field(default_factory=list)
    # UNION [ALL] branches: (is_all, branch); ORDER BY/LIMIT parsed after
    # the last branch are hoisted up here and apply to the union result
    set_ops: list[tuple[bool, "Select"]] = field(default_factory=list)
    # WITH name AS (...) bindings, in declaration order; later CTEs (and
    # the main body) may reference earlier ones
    ctes: dict[str, "Select"] = field(default_factory=dict)
    # EXPLAIN [ANALYZE] prefix: None | "plan" | "analyze" (top level only)
    explain: str | None = None


def format_statement(sel: "Select") -> str:
    """Indented logical-plan rendering for EXPLAIN (shape follows
    DataFusion's logical plan display the reference exposes through its
    EXPLAIN support, /root/reference/src/query/mod.rs:212-276)."""
    lines: list[str] = []

    def emit(depth: int, text: str) -> None:
        lines.append("  " * depth + text)

    def fmt(s: "Select", depth: int) -> None:
        for name, sub in s.ctes.items():
            emit(depth, f"CTE: {name}")
            fmt(sub, depth + 1)
        if s.set_ops:
            # hoisted ORDER BY/LIMIT apply to the union result: render
            # them ABOVE the Union node
            if s.limit is not None or s.offset:
                emit(depth, f"Limit: {s.limit}" + (f" OFFSET {s.offset}" if s.offset else ""))
                depth += 1
            if s.order_by:
                keys = ", ".join(
                    expr_name(o.expr) + (" DESC" if o.desc else " ASC")
                    for o in s.order_by
                )
                emit(depth, f"Sort: {keys}")
                depth += 1
            emit(depth, "Union" + ("" if all(a for a, _ in s.set_ops) else " (distinct fold)"))
            base = _strip_set_ops(s)
            fmt(base, depth + 1)
            for _, branch in s.set_ops:
                fmt(branch, depth + 1)
            return
        if s.limit is not None or s.offset:
            lim = f"Limit: {s.limit}" + (f" OFFSET {s.offset}" if s.offset else "")
            emit(depth, lim)
            depth += 1
        if s.order_by:
            keys = ", ".join(
                expr_name(o.expr) + (" DESC" if o.desc else " ASC") for o in s.order_by
            )
            emit(depth, f"Sort: {keys}")
            depth += 1
        proj = ", ".join(
            expr_name(i.expr) + (f" AS {i.alias}" if i.alias else "") for i in s.items
        )
        emit(depth, ("Distinct " if s.distinct else "") + f"Projection: {proj}")
        depth += 1
        # HAVING filters the aggregate's OUTPUT: deeper means earlier, so
        # it renders above Aggregate (DataFusion order)
        if s.having is not None:
            emit(depth, f"Having: {expr_name(s.having)}")
            depth += 1
        if s.group_by:
            emit(
                depth,
                f"Aggregate: groupBy=[{', '.join(expr_name(g) for g in s.group_by)}]",
            )
            depth += 1
        if s.where is not None:
            emit(depth, f"Filter: {expr_name(s.where)}")
            depth += 1
        scan = f"TableScan: {s.table}" + (f" AS {s.table_alias}" if s.table_alias else "")
        emit(depth, scan)
        for j in s.joins:
            emit(
                depth + 1,
                f"Join[{j.kind}]: {j.table}"
                + (f" AS {j.alias}" if j.alias else "")
                + (f" ON {expr_name(j.on)}" if j.on is not None else ""),
            )

    def _strip_set_ops(s: "Select") -> "Select":
        import copy

        out = copy.copy(s)
        out.set_ops = []
        out.ctes = {}
        out.order_by = []
        out.limit = None
        out.offset = None
        return out

    fmt(sel, 0)
    return "\n".join(lines)


def contains_subquery(e: Expr | None) -> bool:
    if e is None:
        return False
    if isinstance(e, Subquery):
        return True
    if isinstance(e, BinaryOp):
        return contains_subquery(e.left) or contains_subquery(e.right)
    if isinstance(e, UnaryOp):
        return contains_subquery(e.operand)
    if isinstance(e, InList):
        return contains_subquery(e.expr) or any(contains_subquery(i) for i in e.items)
    if isinstance(e, Between):
        return any(contains_subquery(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, IsNull):
        return contains_subquery(e.expr)
    if isinstance(e, FunctionCall):
        return any(contains_subquery(a) for a in e.args)
    if isinstance(e, Cast):
        return contains_subquery(e.expr)
    if isinstance(e, Case):
        return any(contains_subquery(w) or contains_subquery(t) for w, t in e.whens) or contains_subquery(e.else_expr)
    if isinstance(e, WindowCall):
        return (
            any(contains_subquery(a) for a in e.args)
            or any(contains_subquery(p) for p in e.partition_by)
            or any(contains_subquery(o.expr) for o in e.order_by)
        )
    return False


AGGREGATE_FUNCS = {
    "count", "sum", "min", "max", "avg", "approx_distinct", "count_distinct",
    "stddev", "var", "approx_percentile_cont", "approx_median",
}

# pure window functions (aggregate names also work windowed: sum(...) OVER)
WINDOW_FUNCS = {
    "row_number", "rank", "dense_rank", "ntile", "lag", "lead",
    "first_value", "last_value",
}


def contains_window(e: Expr | None) -> bool:
    if e is None:
        return False
    if isinstance(e, WindowCall):
        return True
    if isinstance(e, BinaryOp):
        return contains_window(e.left) or contains_window(e.right)
    if isinstance(e, UnaryOp):
        return contains_window(e.operand)
    if isinstance(e, InList):
        return contains_window(e.expr) or any(contains_window(i) for i in e.items)
    if isinstance(e, Between):
        return any(contains_window(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, IsNull):
        return contains_window(e.expr)
    if isinstance(e, FunctionCall):
        return any(contains_window(a) for a in e.args)
    if isinstance(e, Cast):
        return contains_window(e.expr)
    if isinstance(e, Case):
        return any(contains_window(w) or contains_window(t) for w, t in e.whens) or contains_window(
            e.else_expr
        )
    return False


def is_aggregate(e: Expr) -> bool:
    if isinstance(e, WindowCall):
        # a window call is NOT itself an aggregate — but its inputs may be
        # (`rank() OVER (ORDER BY sum(b))` in a GROUP BY query runs over
        # the aggregated output)
        return (
            any(is_aggregate(a) for a in e.args)
            or any(is_aggregate(p) for p in e.partition_by)
            or any(is_aggregate(o.expr) for o in e.order_by)
        )
    if isinstance(e, FunctionCall):
        if e.name in AGGREGATE_FUNCS:
            return True
        return any(is_aggregate(a) for a in e.args)
    if isinstance(e, BinaryOp):
        return is_aggregate(e.left) or is_aggregate(e.right)
    if isinstance(e, UnaryOp):
        return is_aggregate(e.operand)
    if isinstance(e, Cast):
        return is_aggregate(e.expr)
    if isinstance(e, Case):
        return any(is_aggregate(w) or is_aggregate(t) for w, t in e.whens) or (
            e.else_expr is not None and is_aggregate(e.else_expr)
        )
    return False


# -------------------------------------------------------------------- parser


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers -------------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> str | None:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            self.i += 1
            return t.value
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()} near position {self.peek().pos}")

    def accept_op(self, *ops: str) -> str | None:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            self.i += 1
            return t.value
        return None

    def accept_word(self, *words: str) -> str | None:
        """Contextual (non-reserved) word match, e.g. JOIN inside FROM."""
        t = self.peek()
        if t.kind == "ident" and t.value.lower() in words:
            self.i += 1
            return t.value.lower()
        return None

    def peek_word(self) -> str | None:
        t = self.peek()
        return t.value.lower() if t.kind == "ident" else None

    def expect_word(self, word: str) -> None:
        if not self.accept_word(word):
            raise SqlError(f"expected {word.upper()} near position {self.peek().pos}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r} near position {self.peek().pos}, got {self.peek().value!r}")

    # -- entry ---------------------------------------------------------------
    def parse(self) -> Select:
        # EXPLAIN [ANALYZE] prefix ("explain" is contextual: a column named
        # explain keeps working everywhere else)
        explain: str | None = None
        if self.peek().kind == "ident" and self.peek().value.lower() == "explain":
            self.next()
            explain = "plan"
            if self.peek().kind == "ident" and self.peek().value.lower() == "analyze":
                self.next()
                explain = "analyze"
        # WITH name AS (SELECT ...)[, ...] — CTEs bind for the whole
        # statement; "with" is contextual (a column named "with" stays a
        # column everywhere else)
        ctes: dict[str, Select] = {}
        if self.peek().kind == "ident" and self.peek().value.lower() == "with":
            self.next()
            while True:
                name_t = self.next()
                if name_t.kind != "ident":
                    raise SqlError(f"expected CTE name at {name_t.pos}")
                self.expect_kw("as")
                self.expect_op("(")
                sub = self._parse_set_expr()
                self.expect_op(")")
                if name_t.value in ctes:
                    raise SqlError(f"duplicate CTE name {name_t.value!r}")
                ctes[name_t.value] = sub
                if not self.accept_op(","):
                    break
        sel = self._parse_set_expr()
        sel.ctes = ctes
        sel.explain = explain
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise SqlError(f"trailing tokens at {self.peek().pos}")
        return sel

    def _parse_set_expr(self) -> Select:
        """SELECT ... [UNION [ALL] SELECT ...]*; trailing ORDER BY / LIMIT
        bind to the whole union (standard SQL: branches can't carry them)."""
        self.expect_kw("select")
        first = self.parse_select_body()
        branches: list[tuple[bool, Select]] = []
        while self.peek().kind == "ident" and self.peek().value.lower() == "union":
            self.next()
            is_all = bool(self.accept_word("all"))
            self.expect_kw("select")
            branches.append((is_all, self.parse_select_body()))
        if branches:
            if first.order_by or first.limit is not None:
                raise SqlError("ORDER BY/LIMIT before UNION is not supported")
            for _, b in branches[:-1]:
                if b.order_by or b.limit is not None:
                    raise SqlError("ORDER BY/LIMIT inside a UNION branch is not supported")
            # the trailing ORDER BY/LIMIT parsed into the last branch apply
            # to the union result: hoist them to the head select
            last = branches[-1][1]
            first.order_by, last.order_by = last.order_by, []
            first.limit, last.limit = last.limit, None
            first.offset, last.offset = last.offset, None
            first.set_ops = branches
        return first

    def parse_select_body(self) -> Select:
        distinct = bool(self.accept_kw("distinct"))
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        table = table_alias = None
        joins: list[Join] = []
        if self.accept_kw("from"):
            table, table_alias = self.parse_table_ref()
            while True:
                kind = None
                nxt = self.peek_word()
                after = (
                    self.tokens[self.i + 1]
                    if self.i + 1 < len(self.tokens)
                    else self.tokens[-1]
                )
                after_word = after.value.lower() if after.kind == "ident" else None
                if nxt == "cross" and after_word == "join":
                    self.next()
                    self.next()
                    kind = "cross"
                elif nxt == "inner" and after_word == "join":
                    self.next()
                    self.next()
                    kind = "inner"
                elif nxt == "left":
                    self.next()
                    self.accept_word("outer")
                    self.expect_word("join")
                    kind = "left"
                elif nxt == "join":
                    self.next()
                    kind = "inner"
                elif nxt in ("right", "full") and after_word in ("join", "outer"):
                    raise SqlError("RIGHT/FULL joins are not supported; rewrite as LEFT")
                if kind is None:
                    break
                jt, ja = self.parse_table_ref()
                on = None
                if kind != "cross":
                    self.expect_word("on")
                    on = self.parse_expr()
                joins.append(Join(jt, ja, kind, on))
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group_by: list[Expr] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        order_by: list[OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        limit = offset = None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise SqlError(f"expected LIMIT count at {t.pos}")
            limit = int(t.value)
        if self.accept_kw("offset"):
            t = self.next()
            if t.kind != "number":
                raise SqlError(f"expected OFFSET count at {t.pos}")
            offset = int(t.value)
        return Select(
            items=items,
            table=table,
            where=where,
            group_by=group_by,
            having=having,
            order_by=order_by,
            limit=limit,
            offset=offset,
            distinct=distinct,
            table_alias=table_alias,
            joins=joins,
        )

    def parse_table_ref(self) -> tuple[str, str | None]:
        t = self.next()
        if t.kind != "ident":
            raise SqlError(f"expected table name at {t.pos}")
        alias = None
        if self.accept_kw("as"):
            a = self.next()
            if a.kind != "ident":
                raise SqlError(f"expected alias at {a.pos}")
            alias = a.value
        elif self.peek().kind == "ident" and self.peek().value.lower() not in NON_ALIAS_WORDS:
            alias = self.next().value
        return t.value, alias

    def parse_select_item(self) -> SelectItem:
        if self.accept_op("*"):
            return SelectItem(Star())
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            t = self.next()
            if t.kind not in ("ident", "string"):
                raise SqlError(f"expected alias at {t.pos}")
            alias = t.value
        elif self.peek().kind == "ident" and self.peek().value.lower() != "union":
            alias = self.next().value
        return SelectItem(e, alias)

    def parse_order_item(self) -> OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        elif self.accept_kw("asc"):
            desc = False
        return OrderItem(e, desc)

    # -- expressions (precedence climbing) ----------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.accept_kw("or"):
            left = BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.accept_kw("and"):
            left = BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> Expr:
        if self.accept_kw("not"):
            return UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        left = self.parse_additive()
        negated = bool(self.accept_kw("not"))
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek().kind == "kw" and self.peek().value == "select":
                self.next()
                sub = self.parse_select_body()
                self.expect_op(")")
                return InList(left, [Subquery(sub)], negated)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return InList(left, items, negated)
        if self.accept_kw("between"):
            low = self.parse_additive()
            self.expect_kw("and")
            high = self.parse_additive()
            return Between(left, low, high, negated)
        if self.accept_kw("like"):
            return BinaryOp("not_like" if negated else "like", left, self.parse_additive())
        if self.accept_kw("ilike"):
            return BinaryOp("not_ilike" if negated else "ilike", left, self.parse_additive())
        if negated:
            raise SqlError(f"unexpected NOT at {self.peek().pos}")
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNull(left, neg)
        op = self.accept_op("=", "!=", "<", "<=", ">", ">=")
        if op:
            return BinaryOp(op, left, self.parse_additive())
        return left

    def parse_additive(self) -> Expr:
        left = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                return left
            left = BinaryOp(op, left, self.parse_multiplicative())

    def parse_multiplicative(self) -> Expr:
        left = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return left
            left = BinaryOp(op, left, self.parse_unary())

    def parse_unary(self) -> Expr:
        if self.accept_op("-"):
            return UnaryOp("-", self.parse_unary())
        self.accept_op("+")
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            return Literal(t.value)
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if t.kind == "kw":
            if t.value == "null":
                self.next()
                return Literal(None)
            if t.value == "true":
                self.next()
                return Literal(True)
            if t.value == "false":
                self.next()
                return Literal(False)
            if t.value == "interval":
                self.next()
                lit = self.next()
                if lit.kind != "string":
                    raise SqlError(f"expected interval string at {lit.pos}")
                return IntervalLit(lit.value)
            if t.value == "case":
                return self.parse_case()
            if t.value == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                ty = self.next()
                if ty.kind not in ("ident", "kw"):
                    raise SqlError(f"expected type name at {ty.pos}")
                type_name = str(ty.value).lower()
                # types like timestamp(3) / varchar(10)
                if self.accept_op("("):
                    while not self.accept_op(")"):
                        self.next()
                self.expect_op(")")
                return Cast(e, type_name)
            if t.value == "distinct":
                # inside count(DISTINCT x) handled in function parse; bare =error
                raise SqlError(f"unexpected DISTINCT at {t.pos}")
        if t.kind == "op" and t.value == "(":
            self.next()
            if self.peek().kind == "kw" and self.peek().value == "select":
                self.next()
                sub = self.parse_select_body()
                self.expect_op(")")
                return Subquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.value == "*":
            self.next()
            return Star()
        if t.kind == "ident":
            self.next()
            name = t.value
            if self.accept_op("("):
                return self.parse_function(name)
            if self.accept_op("."):
                col = self.next()
                if col.kind == "op" and col.value == "*":
                    return Star(table=name)
                if col.kind != "ident":
                    raise SqlError(f"expected column after '.' at {col.pos}")
                return Column(col.value, table=name)
            return Column(name)
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_function(self, name: str) -> Expr:
        lname = name.lower()
        distinct = bool(self.accept_kw("distinct"))
        args: list[Expr] = []
        if not self.accept_op(")"):
            if self.accept_op("*"):
                args.append(Star())
            else:
                args.append(self.parse_expr())
            while self.accept_op(","):
                if self.accept_op("*"):
                    args.append(Star())
                else:
                    args.append(self.parse_expr())
            self.expect_op(")")
        if self.peek().kind == "ident" and self.peek().value.lower() == "over":
            self.next()
            if distinct:
                raise SqlError("DISTINCT window aggregates are not supported")
            return self.parse_over(lname, args)
        if lname == "count" and distinct:
            return FunctionCall("count_distinct", args)
        return FunctionCall(lname, args, distinct)

    def parse_over(self, fname: str, args: list[Expr]) -> Expr:
        """OVER ([PARTITION BY ...] [ORDER BY ...] [frame]) — frames beyond
        the SQL defaults are rejected (DataFusion-default parity)."""
        if fname not in WINDOW_FUNCS and fname not in AGGREGATE_FUNCS:
            raise SqlError(f"{fname}() cannot be used as a window function")
        self.expect_op("(")
        partition_by: list[Expr] = []
        order_by: list[OrderItem] = []
        frame: str | None = None
        if self.accept_word("partition"):
            self.expect_kw("by")
            partition_by.append(self.parse_expr())
            while self.accept_op(","):
                partition_by.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        unit = self.accept_word("rows", "range")
        if unit:
            # only the UNBOUNDED PRECEDING..CURRENT ROW frames are
            # expressible; ROWS and RANGE differ on tied order keys (peers
            # share the frame under RANGE, not under ROWS)
            self.expect_kw("between")
            self.expect_word("unbounded")
            self.expect_word("preceding")
            self.expect_kw("and")
            self.expect_word("current")
            self.expect_word("row")
            frame = "rows_cumulative" if unit == "rows" else "cumulative"
        self.expect_op(")")
        return WindowCall(fname, args, partition_by, order_by, frame)

    def parse_case(self) -> Expr:
        self.expect_kw("case")
        whens: list[tuple[Expr, Expr]] = []
        base: Expr | None = None
        if not (self.peek().kind == "kw" and self.peek().value == "when"):
            base = self.parse_expr()
        while self.accept_kw("when"):
            cond = self.parse_expr()
            if base is not None:
                cond = BinaryOp("=", base, cond)
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        else_expr = None
        if self.accept_kw("else"):
            else_expr = self.parse_expr()
        self.expect_kw("end")
        return Case(whens, else_expr)


def parse_sql(sql: str) -> Select:
    return Parser(sql).parse()


def expr_name(e: Expr) -> str:
    """Display name for an unaliased select expression."""
    if isinstance(e, Column):
        return e.name
    if isinstance(e, Star):
        return "*"
    if isinstance(e, FunctionCall):
        if e.name == "count" and e.args and isinstance(e.args[0], Star):
            return "count(*)"
        return f"{e.name}({','.join(expr_name(a) for a in e.args)})"
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, BinaryOp):
        return f"{expr_name(e.left)} {e.op} {expr_name(e.right)}"
    if isinstance(e, Cast):
        return expr_name(e.expr)
    if isinstance(e, IntervalLit):
        return f"interval '{e.text}'"
    if isinstance(e, WindowCall):
        return f"{e.name}({','.join(expr_name(a) for a in e.args)}) over"
    return e.__class__.__name__.lower()
