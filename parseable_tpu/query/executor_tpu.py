"""TPU query executor: predicate + group-by aggregation on device.

This is the "TPU execution backend" the whole build centers on (SURVEY §7
step 5). Per scanned block:

1. columns encode host-side once (ops/device.py): numerics -> f32, strings ->
   batch-local dictionary codes, timestamps -> canonical int32 epoch-2020
   seconds — and the encoded block can then live in the **device hot set**
   (ops/hotset.py), so repeated queries over hot data ship zero bytes;
2. the WHERE tree compiles to a device boolean mask; string predicates become
   dictionary LUT gathers (the regex runs once per unique value, not per
   row), numeric/time predicates are branchless compares;
3. group keys combine into one dense int32 id: dict codes go through a
   per-batch device-side remap (batch-local -> global dictionary), time bins
   are epoch-aligned; capacities are powers of two so XLA sees few shapes;
4. ONE jitted program per (plan, layout, block-shape) runs mask + remap +
   group ids + `fused_groupby_block` in a single dispatch per block, folding
   into a device accumulator; the host syncs once per flush and accumulates
   G-sized partials in float64.

The single-dispatch + async + resident-data design is what makes the path
fast: device round-trips cost O(100ms) on tunneled setups and the fused
kernel sustains >10 G rows/s, so per-query host<->device traffic — not
FLOPs — is the budget.

Anything the device path can't express (nested types, aggregates over
expressions, date_bin with custom origin or sub-millisecond bins, exact
distinct beyond the bitmap budget) falls
back to the CPU executor — whole-query when detected at plan time, per-table
otherwise — merging into the same aggregator, so results stay complete and
exact.

Precision: per-block reductions run in f32 (blocks <= 2^22 rows keep counts
exact; sums carry ~1e-5 relative error vs the CPU engine's f64); cross-block
accumulation is f64 on host. Device timestamps encode as exact int32
milliseconds relative to the block origin (see ops/device.py), so EVERY
comparison op — `<`, `>=`, `>`, `<=`, `=`, `!=`, including sub-second
literals — evaluates exactly on device with no second-granularity fallback;
sub-millisecond literals floor to ms, matching the CPU engine's coercion
(the two engines agree row-for-row). Columns with sub-ms residue decline
device encoding and take the CPU path instead.
"""

from __future__ import annotations

import logging
import re
import time as _time
from dataclasses import dataclass, field as dc_field
from datetime import UTC, datetime, timedelta
from typing import Any, Callable, Iterator

import numpy as np
import pyarrow as pa

from parseable_tpu.config import Options
from parseable_tpu.ops import kernels
from parseable_tpu.ops.device import (
    EncodedBatch,
    EncodedColumn,
    encode_table,
)
from parseable_tpu.ops.hotset import HotEntry, get_hotset
from parseable_tpu.query import sql as S
from parseable_tpu.query.executor import (
    AggSpec,
    HashAggregator,
    QueryExecutor,
)
from parseable_tpu.query.planner import LogicalPlan
from parseable_tpu.query.sketch import BINS as PCT_BINS
from parseable_tpu.query.sketch import DEVICE_NB, LOG_HI, LOG_LO
from parseable_tpu.query.sketch import _SCALE as PCT_SCALE
from parseable_tpu.utils.metrics import (
    DEVICE_BYTES_TO_DEVICE,
    DEVICE_EXECUTE_TIME,
    DEVICE_JIT_PROGRAMS,
    DEVICE_RECOMPILES,
    DEVICE_TRANSFER_BYTES,
)
from parseable_tpu.utils.timeutil import parse_duration, parse_rfc3339

logger = logging.getLogger(__name__)

SOURCE_ID_META = b"ptpu_source_id"
# pow2_block's ceiling: tables beyond this split before encoding
MAX_BLOCK_ROWS = 1 << 22
STUB_META = b"ptpu_hot_stub"

# High-cardinality group-by (VERDICT r2 #2): past this dense global group
# space the executor switches to block-local two-phase aggregation — the
# device folds each block on its OWN dictionary codes (already dense), the
# host extracts the nonzero groups as a partial table, and ONE vectorized
# pyarrow group_by merges all partials at finalize. No capacity epochs, no
# global remap (whose LUT transfer grows with the dictionary), no per-group
# Python — a 1M-distinct GROUP BY degrades gracefully instead of falling
# off a cliff (DataFusion hash-aggregate parity:
# /root/reference/src/query/mod.rs:212-276).
DENSE_G_MAX = 1 << 19
# per-block group-space ceiling in local mode (beyond -> that block folds
# on the CPU; multi-key blocks with two 1M-card keys can't product-combine)
LOCAL_G_MAX = 1 << 22
# device percentile budget: one [G, DEVICE_NB] f32 histogram per
# approx_percentile spec (64 MB at the default 2049-slot sketch layout);
# beyond it the scan stays host-side with exact sketches
PCT_MAX_ELEMS = 1 << 24


class UnsupportedOnDevice(Exception):
    pass


def dict_group_columns(select: S.Select) -> set[str]:
    """Group-by columns that device-encode as dictionaries (plain columns)."""
    out = set()
    for g in select.group_by:
        e = g.expr if isinstance(g, S.Cast) else g
        if isinstance(e, S.Column):
            out.add(e.name)
    return out


def hot_key(source_id: bytes, needed: set[str] | None, dict_cols: set[str]) -> tuple:
    return (
        source_id,
        tuple(sorted(needed)) if needed is not None else None,
        tuple(sorted(dict_cols)),
    )


def is_stub(table: pa.Table) -> bool:
    return (table.schema.metadata or {}).get(STUB_META) is not None


def make_stub(source_id: bytes, num_rows: int) -> pa.Table:
    """Zero-copy placeholder for a device-resident block."""
    return pa.table({}).replace_schema_metadata(
        {SOURCE_ID_META: source_id, STUB_META: str(num_rows).encode()}
    )


def _pow2(n: int, minimum: int = 8) -> int:
    p = minimum
    while p < n:
        p <<= 1
    return p


# ------------------------------------------------------------- global dicts


class GlobalDict:
    """Union of per-batch dictionaries for one column, plus device remaps.

    Absorb is vectorized (VERDICT r2: the per-value Python loop capped the
    engine at small dictionaries): known values resolve through ONE
    `pc.index_in` C++ hash probe against the accumulated dictionary; only
    genuinely new values take the Python append. A 100k-entry batch
    dictionary costs one hash-table probe pass, not 100k dict lookups.
    """

    def __init__(self) -> None:
        self.values: list[Any] = []
        self._chunks: list[pa.Array] = []  # same values, arrow-side

    def absorb(self, batch_dict: list[Any]) -> np.ndarray:
        """Register a batch dictionary; return the batch->global int32 remap,
        padded to pow2 with a large sentinel (nulls + padding decode as the
        null group)."""
        card = len(batch_dict)
        lut = np.full(_pow2(card + 1), np.int32(2**30), dtype=np.int32)
        if card == 0:
            return lut
        import pyarrow.compute as pc

        if self.values and not self._chunks:
            # a previous batch fell back to slow mode; the arrow-side view
            # is stale, so stay on the slow path for dictionary consistency
            return self._absorb_slow(batch_dict, lut)
        try:
            batch_arr = pa.array(batch_dict)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            return self._absorb_slow(batch_dict, lut)
        if self._chunks:
            value_set: pa.Array | pa.ChunkedArray = (
                self._chunks[0]
                if len(self._chunks) == 1
                else pa.chunked_array(self._chunks)
            )
            try:
                idx = pc.index_in(batch_arr, value_set=value_set)
            except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
                return self._absorb_slow(batch_dict, lut)
            known = idx.fill_null(-1).to_numpy(zero_copy_only=False).astype(np.int64)
        else:
            known = np.full(card, -1, dtype=np.int64)
        valid = np.asarray(pc.is_valid(batch_arr).to_numpy(zero_copy_only=False), bool)
        new_mask = (known < 0) & valid
        new_pos = np.nonzero(new_mask)[0]
        if len(new_pos):
            base = len(self.values)
            new_vals = batch_arr.take(pa.array(new_pos))
            # batch dictionaries hold unique values, so bulk-append is safe
            self.values.extend(new_vals.to_pylist())
            self._chunks.append(new_vals)
            known[new_pos] = base + np.arange(len(new_pos))
        lut[: len(known)][valid & (known >= 0)] = known[valid & (known >= 0)].astype(
            np.int32
        )
        return lut

    def _absorb_slow(self, batch_dict: list[Any], lut: np.ndarray) -> np.ndarray:
        """Mixed-type dictionaries arrow can't hash: per-value fallback."""
        index = {v: i for i, v in enumerate(self.values)}
        for i, v in enumerate(batch_dict):
            if v is None:
                continue
            gi = index.get(v)
            if gi is None:
                gi = len(self.values)
                self.values.append(v)
                index[v] = gi
            lut[i] = gi
        self._chunks = []  # arrow-side view no longer tracks .values
        return lut

    def __len__(self) -> int:
        return len(self.values)


# --------------------------------------------------------------- group keys


@dataclass
class KeySpec:
    kind: str  # "dict" | "timebin"
    column: str
    expr: S.Expr
    bin_ms: int = 0  # timebin only
    gdict: GlobalDict | None = None  # dict only
    capacity: int = 1  # current stride capacity (pow2)
    origin_rel: int | None = None  # timebin only: origin *bin index*


def _like_to_regex(pattern: str) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern) and pattern[i + 1] in ("%", "_", "\\"):
            # backslash-escaped wildcard is a literal (matches Arrow's
            # pc.match_like semantics on the CPU path)
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "^" + "".join(out) + "$"


def _interval_ms(e: S.Expr) -> int | None:
    if isinstance(e, S.IntervalLit):
        return int(parse_duration(e.text).total_seconds() * 1000)
    if isinstance(e, S.Literal) and isinstance(e.value, str):
        try:
            return int(parse_duration(e.value).total_seconds() * 1000)
        except ValueError:
            return None
    return None


_TRUNC_MS = {
    "second": 1000,
    "minute": 60_000,
    "hour": 3_600_000,
    "day": 86_400_000,
}


def classify_group_expr(e: S.Expr) -> KeySpec:
    """Map a GROUP BY expression onto a device key kind, or raise."""
    if isinstance(e, S.Column):
        return KeySpec("dict", e.name, e, gdict=GlobalDict())
    if isinstance(e, S.FunctionCall) and e.name == "date_bin" and len(e.args) >= 2:
        if len(e.args) > 2:
            # custom bin origin: device bins are epoch-aligned only
            raise UnsupportedOnDevice("date_bin with explicit origin")
        ms = _interval_ms(e.args[0])
        col = e.args[1]
        # any >=1ms bin maps exactly; the upper bound keeps the device-side
        # shift (origin % bin_ms + rel) inside int32
        if ms and ms <= (1 << 30) and isinstance(col, S.Column):
            return KeySpec("timebin", col.name, e, bin_ms=ms)
        raise UnsupportedOnDevice("sub-millisecond or >12-day date_bin")
    if isinstance(e, S.FunctionCall) and e.name == "date_trunc" and len(e.args) == 2:
        unit = e.args[0].value if isinstance(e.args[0], S.Literal) else None
        col = e.args[1]
        ms = _TRUNC_MS.get(str(unit).lower()) if unit else None
        if ms and isinstance(col, S.Column):
            return KeySpec("timebin", col.name, e, bin_ms=ms)
    if isinstance(e, S.Cast):
        return classify_group_expr(e.expr)
    raise UnsupportedOnDevice(f"group expression not device-mappable: {S.expr_name(e)}")


# ------------------------------------------------------------ mask compiler


class PredicateCompiler:
    """Compile a WHERE tree into device ops, in two phases per batch:

    - `collect_luts(e, enc)` (host): evaluate string predicates over the
      *batch* dictionary into boolean LUTs, padded to pow2. Cached on the
      EncodedBatch (lifetime == dictionary lifetime), so for hot-set-resident
      blocks the regex work happens exactly once per (pattern, block).
    - `trace(e, enc, dev, luts)` (traced or eager): emit jnp ops, consuming
      the LUT arrays positionally. Runs identically under jax.jit (LUTs as
      runtime args) and eagerly.
    """

    # ---------------------------------------------------------- phase A

    def collect_luts(self, e: S.Expr | None, enc: EncodedBatch) -> list[np.ndarray]:
        out: list[np.ndarray] = []
        if e is not None:
            self._walk_collect(e, enc, out)
        return out

    def _walk_collect(self, e: S.Expr, enc: EncodedBatch, out: list[np.ndarray]) -> None:
        if isinstance(e, S.BinaryOp):
            if e.op in ("and", "or"):
                self._walk_collect(e.left, enc, out)
                self._walk_collect(e.right, enc, out)
                return
            if e.op in ("=", "!=", "<", "<=", ">", ">="):
                col, op, lit = self._cmp_parts(e, enc)
                if col.kind == "dict":
                    out.append(self._dict_lut(enc, col, op, lit))
                elif col.kind == "time":
                    # per-block rel-ms literal as a runtime scalar: rides
                    # the LUT channel so one compiled program serves every
                    # block regardless of its time origin
                    out.append(self._time_lit(enc, op, lit))
                return
            if e.op in ("like", "ilike", "not_like", "not_ilike"):
                col = self._column_of(e.left, enc)
                raw = str(self._literal_of(e.right))
                out.append(
                    self._regex_lut(
                        enc,
                        col,
                        _like_to_regex(raw),
                        re.IGNORECASE if "ilike" in e.op else 0,
                        e.op.startswith("not_"),
                    )
                )
                return
        if isinstance(e, S.UnaryOp) and e.op == "not":
            self._walk_collect(e.operand, enc, out)
            return
        if isinstance(e, S.Between):
            self._walk_collect(S.BinaryOp(">=", e.expr, e.low), enc, out)
            self._walk_collect(S.BinaryOp("<=", e.expr, e.high), enc, out)
            return
        if isinstance(e, S.InList):
            col = self._column_of(e.expr, enc)
            if col.kind == "dict":
                out.append(self._in_lut(enc, e, col))
            return
        if isinstance(e, S.FunctionCall) and e.name in ("regexp_match", "regexp_like"):
            col = self._column_of(e.args[0], enc)
            out.append(self._regex_lut(enc, col, str(self._literal_of(e.args[1])), 0, False))
            return
        if isinstance(e, (S.IsNull, S.Literal)):
            return
        raise UnsupportedOnDevice(f"predicate not device-mappable: {type(e).__name__}")

    # ---------------------------------------------------------- phase B

    def trace(self, e: S.Expr | None, enc: EncodedBatch, dev: dict, luts: list):
        import jax.numpy as jnp

        if e is None:
            return dev["__ones"] if "__ones" in dev else jnp.ones(enc.block_rows, bool)
        it = iter(luts)
        return self._visit(e, enc, dev, it)

    def _visit(self, e: S.Expr, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        if isinstance(e, S.BinaryOp):
            if e.op == "and":
                return jnp.logical_and(
                    self._visit(e.left, enc, dev, luts), self._visit(e.right, enc, dev, luts)
                )
            if e.op == "or":
                return jnp.logical_or(
                    self._visit(e.left, enc, dev, luts), self._visit(e.right, enc, dev, luts)
                )
            if e.op in ("=", "!=", "<", "<=", ">", ">="):
                return self._cmp(e, enc, dev, luts)
            if e.op in ("like", "ilike", "not_like", "not_ilike"):
                col = self._column_of(e.left, enc)
                if col.kind != "dict":
                    raise UnsupportedOnDevice("string predicate on non-string column")
                lut = next(luts)
                return jnp.logical_and(lut[_as_index(dev[col.name])], dev[f"{col.name}__valid"])
        if isinstance(e, S.UnaryOp) and e.op == "not":
            return jnp.logical_not(self._visit(e.operand, enc, dev, luts))
        if isinstance(e, S.Between):
            m = jnp.logical_and(
                self._cmp(S.BinaryOp(">=", e.expr, e.low), enc, dev, luts),
                self._cmp(S.BinaryOp("<=", e.expr, e.high), enc, dev, luts),
            )
            return jnp.logical_not(m) if e.negated else m
        if isinstance(e, S.InList):
            return self._in_list(e, enc, dev, luts)
        if isinstance(e, S.IsNull):
            col = self._column_of(e.expr, enc)
            valid = dev[f"{col.name}__valid"]
            return valid if e.negated else jnp.logical_not(valid)
        if isinstance(e, S.FunctionCall) and e.name in ("regexp_match", "regexp_like"):
            col = self._column_of(e.args[0], enc)
            if col.kind != "dict":
                raise UnsupportedOnDevice("regex on non-string column")
            lut = next(luts)
            return jnp.logical_and(lut[_as_index(dev[col.name])], dev[f"{col.name}__valid"])
        if isinstance(e, S.Literal) and isinstance(e.value, bool):
            # size from the device array, not enc.block_rows: under
            # shard_map this trace sees the per-device row shard
            return jnp.full(dev["__ones"].shape[0], e.value)
        raise UnsupportedOnDevice(f"predicate not device-mappable: {type(e).__name__}")

    # ---------------------------------------------------------- shared bits

    def _column_of(self, e: S.Expr, enc: EncodedBatch) -> EncodedColumn:
        if isinstance(e, S.Cast):
            return self._column_of(e.expr, enc)
        if not isinstance(e, S.Column):
            raise UnsupportedOnDevice("expected a column operand")
        col = enc.columns.get(e.name)
        if col is None:
            raise UnsupportedOnDevice(f"column {e.name} not encoded")
        return col

    def _literal_of(self, e: S.Expr) -> Any:
        if isinstance(e, S.Literal):
            return e.value
        if isinstance(e, S.Cast):
            return self._literal_of(e.expr)
        if isinstance(e, S.FunctionCall) and e.name == "to_timestamp" and e.args:
            return self._literal_of(e.args[0])
        raise UnsupportedOnDevice("expected a literal operand")

    def _cmp_parts(self, e: S.BinaryOp, enc: EncodedBatch):
        left_is_col = isinstance(e.left, (S.Column, S.Cast)) and not isinstance(e.left, S.Literal)
        if left_is_col:
            return self._column_of(e.left, enc), e.op, self._literal_of(e.right)
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return self._column_of(e.right, enc), flip.get(e.op, e.op), self._literal_of(e.left)

    def _cmp(self, e: S.BinaryOp, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        col, op, lit = self._cmp_parts(e, enc)
        valid = dev[f"{col.name}__valid"]
        values = dev[col.name]
        if col.kind == "dict":
            lut = next(luts)
            mask = lut[_as_index(values)]
        elif col.kind == "time":
            # values are exact int32 ms rel to the block origin, so every
            # comparison op (incl. =, !=, <=, > and sub-second literals)
            # is exact — no more second-floor fallbacks
            mask = _num_cmp(values, op, next(luts)[0])
        elif col.kind in ("num", "bool"):
            if not isinstance(lit, (int, float, bool)):
                raise UnsupportedOnDevice("numeric compared to non-numeric literal")
            mask = _num_cmp(values, op, float(lit))
        else:
            raise UnsupportedOnDevice(f"cannot compare column kind {col.kind}")
        return jnp.logical_and(mask, valid)

    @staticmethod
    def _time_lit(enc: EncodedBatch, op: str, lit: Any) -> np.ndarray:
        """Literal as block-relative int32 ms, shipped as a runtime scalar.

        Sub-ms literals FLOOR to ms — matching the CPU engine, whose
        comparisons coerce the literal to the (ms) column type via
        pa.scalar(..., type=t) (executor.py _coerce/_bounds_filter); the
        two engines must agree row-for-row, and device rows are
        ms-quantized anyway (encode declines columns with sub-ms residue).

        Out-of-range literals clamp to just inside int32: encoded rel
        values are bounded by TIME_REL_SPAN (< 2^30), so a clamped bound
        compares uniformly true/false against every row — exactly the
        semantics of a literal beyond the block's representable window —
        and can never equal a live value."""
        del op  # same floor for every comparison op (CPU-engine parity)
        if isinstance(lit, str):
            lit_dt = parse_rfc3339(lit)
        elif isinstance(lit, datetime):
            lit_dt = lit if lit.tzinfo else lit.replace(tzinfo=UTC)
        else:
            raise UnsupportedOnDevice("timestamp compared to non-time literal")
        rel = _dt_to_us(lit_dt) // 1000 - enc.time_origin_ms
        rel = max(-(2**31) + 2, min(2**31 - 2, rel))
        return np.asarray([rel], dtype=np.int32)

    def _in_list(self, e: S.InList, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        col = self._column_of(e.expr, enc)
        valid = dev[f"{col.name}__valid"]
        if col.kind == "dict":
            lut = next(luts)
            return jnp.logical_and(lut[_as_index(dev[col.name])], valid)
        if col.kind in ("num", "bool"):
            lits = [self._literal_of(i) for i in e.items]
            mask = jnp.zeros_like(valid)
            for v in lits:
                mask = jnp.logical_or(mask, dev[col.name] == float(v))
            if e.negated:
                mask = jnp.logical_not(mask)
            return jnp.logical_and(mask, valid)
        raise UnsupportedOnDevice("IN on unsupported column kind")

    # ---------------------------------------------------------- LUT builders
    # LUTs are built over the BATCH dictionary (codes index it directly) and
    # cached on the EncodedBatch so hot blocks never re-evaluate a predicate.

    @staticmethod
    def _batch_cache(enc: EncodedBatch) -> dict:
        cache = getattr(enc, "lut_cache", None)
        if cache is None:
            cache = {}
            enc.lut_cache = cache
        return cache

    def _padded(self, lut: np.ndarray) -> np.ndarray:
        n = _pow2(len(lut))
        if n == len(lut):
            return lut
        out = np.zeros(n, dtype=bool)
        out[: len(lut)] = lut
        return out

    def _dict_lut(self, enc: EncodedBatch, col: EncodedColumn, op: str, lit: Any) -> np.ndarray:
        cache = self._batch_cache(enc)
        key = (col.name, op, repr(lit))
        hit = cache.get(key)
        if hit is not None:
            return hit
        import operator as _op

        values = col.dictionary[:-1]
        fns = {"=": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}
        f = fns[op]
        lut = np.zeros(len(values) + 1, dtype=bool)  # +1 null slot -> False
        for i, v in enumerate(values):
            if v is None:
                continue
            try:
                lut[i] = bool(f(v, lit))
            except TypeError:
                lut[i] = False
        lut = self._padded(lut)
        cache[key] = lut
        return lut

    def _regex_lut(
        self, enc: EncodedBatch, col: EncodedColumn, pattern: str, flags: int, negate: bool
    ) -> np.ndarray:
        if col.kind != "dict":
            raise UnsupportedOnDevice("string predicate on non-string column")
        cache = self._batch_cache(enc)
        key = (col.name, pattern, flags, negate)
        hit = cache.get(key)
        if hit is not None:
            return hit
        rx = re.compile(pattern, flags)
        values = col.dictionary[:-1]
        lut = np.zeros(len(values) + 1, dtype=bool)
        for i, v in enumerate(values):
            if isinstance(v, str):
                m = rx.search(v) is not None
                lut[i] = (not m) if negate else m
        lut = self._padded(lut)
        cache[key] = lut
        return lut

    def _in_lut(self, enc: EncodedBatch, e: S.InList, col: EncodedColumn) -> np.ndarray:
        cache = self._batch_cache(enc)
        lits = {self._literal_of(i) for i in e.items}
        key = (col.name, "in", repr(sorted(map(repr, lits))), e.negated)
        hit = cache.get(key)
        if hit is not None:
            return hit
        values = col.dictionary[:-1]
        lut = np.zeros(len(values) + 1, dtype=bool)
        for i, v in enumerate(values):
            inside = v in lits
            lut[i] = (not inside) if e.negated else inside
        lut = self._padded(lut)
        cache[key] = lut
        return lut


def _as_index(a):
    """Dictionary codes ship in the narrowest dtype (int8/int16) but index
    LUTs whose SIZE may exceed that dtype's range — JAX gathers materialize
    the array size in the index dtype, so upcast to int32 in-program (XLA
    fuses the convert; transfer stays narrow)."""
    import jax.numpy as jnp

    return a if a.dtype == jnp.int32 else a.astype(jnp.int32)


_EPOCH_UTC = datetime(1970, 1, 1, tzinfo=UTC)


def _dt_to_us(dt: datetime) -> int:
    """Exact integer epoch-microseconds (float .timestamp() wobbles at
    2024-era magnitudes; datetime precision is exactly us)."""
    return (dt - _EPOCH_UTC) // timedelta(microseconds=1)


def _num_cmp(values, op: str, threshold):
    import jax.numpy as jnp

    t = jnp.asarray(threshold, dtype=values.dtype)
    return {
        "=": values == t,
        "!=": values != t,
        "<": values < t,
        "<=": values <= t,
        ">": values > t,
        ">=": values >= t,
    }[op]


# ------------------------------------------------------------ dense agg state


@dataclass(frozen=True)
class AccLayout:
    """Row arithmetic of the packed device accumulator.

    Kernel stacking order (one f32 row per entry; built from the AggSpec
    list once per query):

      sums:  [sum/avg cols] [stddev/var cols: x]
      mins:  [min cols] [percentile cols (exact per-group vmin)]
      maxs:  [max cols] [percentile cols (exact per-group vmax)]
      cnts:  [count(col) cols]

    Validity rows mirror the same order (percentile dup rows are NaN-aware
    so sketch counts match the host path, which drops NaN). Accumulator
    rows: [0] count(*) mask hits | [1, 1+n_allk) per-agg counts | n_sum
    sums | n_sq sum(x) | n_sq M2 | n_mink mins | n_maxk maxs.

    stddev/var keep CENTERED second moments (M2 = sum((x - mean_g)^2), the
    per-block per-group mean), merged across blocks/devices with Chan's
    parallel update — raw f32 sum-of-squares cancels catastrophically when
    mean >> stddev; M2 magnitudes stay ~variance*n, so f32 holds. Finalize
    is M2/(n-1) (DataFusion's sample-variance semantics, ref
    query/mod.rs:212-276); host merges reconstruct raw sumsq = M2 +
    sum^2/n in f64.

    Device percentiles additionally keep one flat [G * DEVICE_NB] f32
    histogram per spec (additive, psum-able — see query/sketch.py layout).
    """

    sum_idx: tuple[int, ...]  # spec indices: sum/avg
    sq_idx: tuple[int, ...]  # stddev/var
    min_idx: tuple[int, ...]
    max_idx: tuple[int, ...]
    countcol_idx: tuple[int, ...]
    pct_idx: tuple[int, ...]  # percentile (approx_percentile_cont/median)
    distinct_idx: tuple[int, ...] = ()

    # ------------------------------------------------------------- section sizes

    @property
    def n_sum(self) -> int:
        return len(self.sum_idx)

    @property
    def n_sq(self) -> int:
        return len(self.sq_idx)

    @property
    def n_pct(self) -> int:
        return len(self.pct_idx)

    @property
    def n_sumk(self) -> int:  # acc sum-section rows: sums + sq(x) + sq(M2)
        return self.n_sum + 2 * self.n_sq

    @property
    def n_mink(self) -> int:  # kernel min rows: mins + pct vmin
        return len(self.min_idx) + self.n_pct

    @property
    def n_maxk(self) -> int:
        return len(self.max_idx) + self.n_pct

    @property
    def n_allk(self) -> int:  # validity / per-agg-count rows (kernel)
        return (
            self.n_sum + self.n_sq + self.n_mink + self.n_maxk
            + len(self.countcol_idx)
        )

    @property
    def n_rows(self) -> int:  # total packed accumulator rows
        return 1 + self.n_allk + self.n_sumk + self.n_mink + self.n_maxk

    # -------------------------------------------------- absolute acc row index

    def pac_row(self, si: int) -> int:
        """Per-agg non-null count row for spec `si` (pct specs use their
        min-dup validity row; their exact count comes from the histogram)."""
        base = self.n_sum + self.n_sq  # kernel sum rows (x only, no M2)
        if si in self.sum_idx:
            return 1 + self.sum_idx.index(si)
        if si in self.sq_idx:
            return 1 + self.n_sum + self.sq_idx.index(si)
        if si in self.min_idx:
            return 1 + base + self.min_idx.index(si)
        if si in self.pct_idx:
            return 1 + base + len(self.min_idx) + self.pct_idx.index(si)
        if si in self.max_idx:
            return 1 + base + self.n_mink + self.max_idx.index(si)
        return 1 + base + self.n_mink + self.n_maxk + self.countcol_idx.index(si)

    def sum_row(self, si: int) -> int:
        return 1 + self.n_allk + self.sum_idx.index(si)

    def sqx_row(self, si: int) -> int:  # stddev/var sum(x)
        return 1 + self.n_allk + self.n_sum + self.sq_idx.index(si)

    def sqm2_row(self, si: int) -> int:  # stddev/var centered M2
        return 1 + self.n_allk + self.n_sum + self.n_sq + self.sq_idx.index(si)

    def min_row(self, si: int) -> int:
        return 1 + self.n_allk + self.n_sumk + self.min_idx.index(si)

    def pct_min_row(self, si: int) -> int:
        return (
            1 + self.n_allk + self.n_sumk + len(self.min_idx)
            + self.pct_idx.index(si)
        )

    def max_row(self, si: int) -> int:
        return 1 + self.n_allk + self.n_sumk + self.n_mink + self.max_idx.index(si)

    def pct_max_row(self, si: int) -> int:
        return (
            1 + self.n_allk + self.n_sumk + self.n_mink + len(self.max_idx)
            + self.pct_idx.index(si)
        )

    @classmethod
    def from_specs(cls, specs: list[AggSpec]) -> "AccLayout":
        """Classify specs into packed sections; raises UnsupportedOnDevice
        for aggregates the device path cannot express."""
        sum_idx: list[int] = []
        sq_idx: list[int] = []
        min_idx: list[int] = []
        max_idx: list[int] = []
        countcol_idx: list[int] = []
        pct_idx: list[int] = []
        distinct_idx: list[int] = []
        for i, spec in enumerate(specs):
            if spec.func == "count_star":
                continue
            if not isinstance(spec.arg, S.Column):
                raise UnsupportedOnDevice(
                    f"aggregate over expression: {S.expr_name(spec.arg)}"
                )
            if spec.func in ("sum", "avg"):
                sum_idx.append(i)
            elif spec.func in ("stddev", "var"):
                sq_idx.append(i)
            elif spec.func == "min":
                min_idx.append(i)
            elif spec.func == "max":
                max_idx.append(i)
            elif spec.func == "count":
                countcol_idx.append(i)
            elif spec.func == "percentile":
                pct_idx.append(i)
            elif spec.func in ("count_distinct", "approx_distinct"):
                # both ride the flat [G * cap] segment_max machinery:
                # exact as presence bitmaps over the global dictionary,
                # approx as HLL register files (cap = HLL_M, value = rank)
                distinct_idx.append(i)
            else:
                raise UnsupportedOnDevice(f"aggregate {spec.func}")
        return cls(
            sum_idx=tuple(sum_idx),
            sq_idx=tuple(sq_idx),
            min_idx=tuple(min_idx),
            max_idx=tuple(max_idx),
            countcol_idx=tuple(countcol_idx),
            pct_idx=tuple(pct_idx),
            distinct_idx=tuple(distinct_idx),
        )


@dataclass
class PlanLayout:
    """Everything that shapes the device program for one capacity epoch."""

    key_specs: list[KeySpec]
    caps: tuple[int, ...]
    origins: tuple[int, ...]
    sum_cols: list[str]
    min_cols: list[str]
    max_cols: list[str]
    stacked_cols: list[str]
    distinct_cols: list[str] = dc_field(default_factory=list)
    distinct_caps: tuple[int, ...] = ()
    # True per distinct col when it is an approx_distinct HLL register
    # file (dremap = [2, N] idx/rank LUT; update value = rank, not 1)
    distinct_sketch: tuple[bool, ...] = ()
    sq_cols: list[str] = dc_field(default_factory=list)  # stddev/var inputs
    pct_cols: list[str] = dc_field(default_factory=list)  # percentile inputs
    cnt_cols: list[str] = dc_field(default_factory=list)  # count(col) inputs


def _kernel_stacks(dev: dict, layout: "PlanLayout", local_rows: int):
    """Build fused_groupby_block inputs per the AccLayout kernel stacking.

    sums rows:  sum_cols | sq_cols (x — M2 rows are computed separately)
    mins rows:  min_cols | pct_cols (exact vmin)
    maxs rows:  max_cols | pct_cols (exact vmax)
    valid rows mirror that order then append cnt_cols; percentile dup rows
    get NaN-aware validity (host sketches drop NaN, so must the device
    count/min/max).

    Returns (sum_values, min_values, max_values, valid, n_sumk, n_mink,
    n_maxk) — all jnp arrays shaped [rows, local_rows].
    """
    import jax.numpy as jnp

    def col(n):
        return dev[n].astype(jnp.float32)

    def valid_of(n):
        return dev[f"{n}__valid"]

    def nn_valid(n):  # NaN-aware (percentile rows)
        return jnp.logical_and(valid_of(n), ~jnp.isnan(col(n)))

    def stack(rows, dtype=jnp.float32):
        if not rows:
            return jnp.zeros((0, local_rows), dtype)
        return jnp.stack(rows)

    sum_rows = [col(n) for n in layout.sum_cols + layout.sq_cols]
    min_rows = [col(n) for n in layout.min_cols + layout.pct_cols]
    max_rows = [col(n) for n in layout.max_cols + layout.pct_cols]
    valid_rows = (
        [valid_of(n) for n in layout.sum_cols + layout.sq_cols]
        + [valid_of(n) for n in layout.min_cols]
        + [nn_valid(n) for n in layout.pct_cols]
        + [valid_of(n) for n in layout.max_cols]
        + [nn_valid(n) for n in layout.pct_cols]
        + [valid_of(n) for n in layout.cnt_cols]
    )
    return (
        stack(sum_rows),
        stack(min_rows),
        stack(max_rows),
        stack(valid_rows, bool),
        len(sum_rows),
        len(min_rows),
        len(max_rows),
    )


def _block_m2(dev, layout, ids, mask, pac, sums, kernel_groups):
    """Per-group CENTERED second moments for each stddev/var column of one
    block: M2_g = sum over the block's rows of (x - mean_g)^2, with mean_g
    from this block's own sums/counts (two segment passes). Returns
    ([n_sq, G] m2, [n_sq, G] n, [n_sq, G] sum) — the latter two are views
    into the kernel outputs for the Chan merge."""
    import jax
    import jax.numpy as jnp

    n_sum = len(layout.sum_cols)
    m2_rows = []
    n_rows = []
    s_rows = []
    for qi, colname in enumerate(layout.sq_cols):
        n_b = pac[n_sum + qi]
        s_b = sums[n_sum + qi]
        mean_g = s_b / jnp.maximum(n_b, 1.0)
        v = dev[colname].astype(jnp.float32)
        vm = jnp.logical_and(mask, dev[f"{colname}__valid"])
        centered = jnp.where(vm, v - mean_g[ids], 0.0)
        m2_rows.append(
            jax.ops.segment_sum(centered * centered, ids, num_segments=kernel_groups)
        )
        n_rows.append(n_b)
        s_rows.append(s_b)
    return m2_rows, n_rows, s_rows


def _psum_m2(m2_loc, m2_n, m2_s, sq_cols):
    """Combine per-device-shard centered moments into block totals over the
    mesh `data` axis: Chan's two-psum form — psum counts/sums first, then
    psum each shard's M2 re-centered against the block-total mean. Returns
    (m2_tot, n_tot, s_tot) lists."""
    import jax
    import jax.numpy as jnp

    m2_tot, n_tot, s_tot = [], [], []
    for qi in range(len(sq_cols)):
        n_t = jax.lax.psum(m2_n[qi], "data")
        s_t = jax.lax.psum(m2_s[qi], "data")
        mean_t = s_t / jnp.maximum(n_t, 1.0)
        mean_l = m2_s[qi] / jnp.maximum(m2_n[qi], 1.0)
        d = mean_l - mean_t
        m2_tot.append(jax.lax.psum(m2_loc[qi] + m2_n[qi] * d * d, "data"))
        n_tot.append(n_t)
        s_tot.append(s_t)
    return m2_tot, n_tot, s_tot


def _chan_merge_m2(acc_n, acc_s, acc_m2, b_n, b_s, b_m2):
    """Chan's parallel variance update: combine (n, sum, M2) partials
    without forming raw sums of squares. Guarded for empty sides."""
    import jax.numpy as jnp

    tot = acc_n + b_n
    both = jnp.logical_and(acc_n > 0, b_n > 0)
    delta = acc_s / jnp.maximum(acc_n, 1.0) - b_s / jnp.maximum(b_n, 1.0)
    corr = jnp.where(
        both, delta * delta * acc_n * b_n / jnp.maximum(tot, 1.0), 0.0
    )
    return acc_m2 + b_m2 + corr


# Jitted programs cached process-wide: two identical queries (or two
# executors in one query lifetime) reuse the compiled XLA executable.
_PROGRAM_CACHE: dict[tuple, Callable] = {}  # jit-cache: executor

# Every (program-family, cache-key) ever built. A rebuild of an identical
# key is a recompile — impossible while the cache holds the entry, so the
# recompile counter reads 0 in steady state; nonzero means eviction or
# key churn. PROGRAM_BUILDS is the plain testable total (warm-query
# regression tests assert it does not move on a second run).
_PROGRAM_KEYS_BUILT: set = set()
PROGRAM_BUILDS = [0]

_TRANSFER_COUNT = [0]


def _note_program_build(program: str, key: tuple, stats: dict | None = None) -> None:
    """Account one call-time program build for `program` under cache `key`:
    the tpu_jit_programs gauge, the per-query route_stats counters the
    stages.programs entry reads, and — when this exact key was already
    built once — the tpu_recompiles_total{program} family the dlint
    tripwire budgets."""
    PROGRAM_BUILDS[0] += 1
    DEVICE_JIT_PROGRAMS.inc()
    if stats is not None:
        stats["programs_built"] = stats.get("programs_built", 0) + 1
    try:
        marker = (program, hash(key))
    except TypeError:
        marker = (program, repr(key))
    if marker in _PROGRAM_KEYS_BUILT:
        DEVICE_RECOMPILES.labels(program).inc()
        if stats is not None:
            stats["recompiles"] = stats.get("recompiles", 0) + 1
    else:
        _PROGRAM_KEYS_BUILT.add(marker)


# the ONE declared d2h readback — waits out pending compute, times pure
# transfer, prices wire bytes into route_stats and the link-profile EWMA
# sync-boundary: every hot-path device->host read must flow through here
def _timed_readback(x, stats: dict | None = None, dtype=np.float64) -> np.ndarray:
    """Device->host readback with link-profile recording. Pending compute
    is waited out BEFORE the timer starts so the d2h sample measures pure
    transfer — compute/compile waits folded in would poison the adaptive
    cost model's latency EWMA. `stats` (a route_stats dict) gets the wire
    bytes added for EXPLAIN ANALYZE observability.

    `dtype` is the HOST-side representation (np.float64 for f32
    accumulators headed into host arithmetic; None keeps the device
    dtype — int32 indices, bool masks). Wire bytes are priced at the
    DEVICE dtype's width capped at 4: the device layer is f32/int32/bool
    end to end, so a float64 host target still crossed the link as f32."""
    if isinstance(x, np.ndarray):
        return np.asarray(x) if dtype is None else np.asarray(x, dtype)
    try:
        # wait for pending compute FIRST so the timing below is pure
        # transfer — folding compile/compute waits into the d2h latency
        # EWMA would poison the adaptive cost model
        x.block_until_ready()
    except Exception:
        pass
    t0 = _time.perf_counter()
    arr = np.asarray(x) if dtype is None else np.asarray(x, dtype)
    try:
        wire = arr.size * min(x.dtype.itemsize, 4)
    except (AttributeError, TypeError):
        wire = arr.size * 4
    if stats is not None:
        stats["d2h_bytes"] += wire
    try:
        from parseable_tpu.ops.link import get_link

        get_link().record_d2h(wire, _time.perf_counter() - t0)
    except Exception:
        pass
    return arr

# blocks the adaptive dispatcher routed to the CPU because the measured
# link made shipping a losing trade (observable in tests/metrics)
ADAPTIVE_CPU_BLOCKS = [0]

# how many programs were built with a mesh (shard_map psum path) — the
# stable signal tests/bench use to assert distributed execution happened
# (cache-key positions are an implementation detail); the second counter
# tracks programs whose ACCUMULATOR sharded over the 2D `groups` axis
MESH_PROGRAMS_BUILT = 0
GROUP_SHARDED_PROGRAMS_BUILT = 0


# ------------------------------------------------------------------- the mesh
# The reference scales queries by fanning results across querier/ingestor
# nodes and merging JSON host-side (cluster/mod.rs:1785-1964,
# stream_schema_provider.rs:566-585). Here the same reduction is a psum tree
# over the chip mesh's `data` axis (parallel/mesh.py): row blocks shard
# across devices, each device folds its shard with the same fused kernel,
# and partials combine over ICI inside the jitted program.

_MESH_CACHE: dict[str, Any] = {}


def resolve_mesh(options: Options | None = None):
    """Device mesh for distributed aggregation, or None (single chip).

    `P_TPU_MESH`: "off" disables; "data:N" / "N" pins a 1D data axis;
    "NxM" (e.g. "4x2") builds the 2D (data x groups) layout where the
    group space ALSO shards — each device owns G/M accumulator buckets,
    so giant group spaces scale past one chip's HBM (parallel/mesh.py
    distributed_groupby_2d design). Empty auto-shards a 1D data axis over
    all visible devices. Axis sizes clamp to powers of two so they always
    divide the power-of-two row blocks / group capacities.
    """
    shape = (options.mesh_shape if options is not None else "").strip().lower()
    if shape in _MESH_CACHE:
        return _MESH_CACHE[shape]
    mesh = None
    try:
        if shape != "off":
            import jax

            n_avail = jax.device_count()
            parts = shape.split("x", 1) if "x" in shape else None
            if parts is not None and all(p.isdigit() and p for p in parts):
                n_data, n_groups = (int(v) for v in parts)
                # pow2 clamp like the 1D path: row blocks and group
                # capacities are powers of two, so non-pow2 axes would
                # silently never engage
                pow2 = lambda n: 1 << (n.bit_length() - 1) if n >= 1 else 1
                cd, cg = pow2(n_data), pow2(n_groups)
                if (cd, cg) != (n_data, n_groups):
                    logger.warning(
                        "P_TPU_MESH=%s clamped to %dx%d (axes must be powers of two)",
                        shape, cd, cg,
                    )
                n_data, n_groups = cd, cg
                if n_data * n_groups <= n_avail:
                    from parseable_tpu.parallel.mesh import make_mesh, make_mesh_2d

                    if n_groups == 1:
                        mesh = make_mesh(n_data)
                    else:
                        mesh = make_mesh_2d(n_data, n_groups)
                else:
                    logger.warning(
                        "P_TPU_MESH=%s needs %d devices, have %d; single-chip",
                        shape, n_data * n_groups, n_avail,
                    )
            elif parts is not None:
                logger.warning("P_TPU_MESH=%r is malformed (want e.g. '4x2'); single-chip", shape)
            else:
                want = None
                if shape.startswith("data:"):
                    want = int(shape.split(":", 1)[1])
                elif shape.isdigit():
                    want = int(shape)
                elif n_avail > 1:
                    want = n_avail
                if want and want > 1:
                    n = min(want, n_avail)
                    n = 1 << (n.bit_length() - 1)  # largest pow2 <= n
                    if n > 1:
                        from parseable_tpu.parallel.mesh import make_mesh

                        mesh = make_mesh(n)
    except Exception:
        logger.exception("mesh resolution failed; running single-chip")
        mesh = None
    _MESH_CACHE[shape] = mesh
    return mesh


def _mesh_group_shards(mesh) -> int:
    """Size of the `groups` axis (1 on 1D meshes)."""
    return mesh.shape.get("groups", 1) if mesh is not None else 1


def _mesh_shardings(mesh):
    """(row-sharded, replicated) placement specs for a data-axis mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("data")), NamedSharding(mesh, P())


def _expr_fingerprint(e: S.Expr | None) -> str:
    return repr(e)  # dataclass repr is structural and stable


# device-resident all-true masks per (block size, mesh); eagerly computing
# jnp.ones per batch costs a full dispatch round trip on tunneled backends
_ONES_CACHE: dict[tuple, Any] = {}


def _device_ones(block_rows: int, mesh=None):
    import jax.numpy as jnp

    key = (block_rows, None if mesh is None else id(mesh))
    ones = _ONES_CACHE.get(key)
    if ones is None:
        ones = np.ones(block_rows, dtype=bool)
        if mesh is not None:
            import jax

            row_s, _ = _mesh_shardings(mesh)
            # cached once per (rows, mesh): not a data-sized ship —
            # link-priced: amortized across every block that reuses it
            ones = jax.device_put(ones, row_s)
        else:
            ones = jnp.asarray(ones)
        _ONES_CACHE[key] = ones
    return ones


class TpuQueryExecutor(QueryExecutor):
    """Device-accelerated aggregation; transparent CPU fallback."""

    def __init__(self, plan: LogicalPlan, options: Options | None = None):
        super().__init__(plan)
        self.options = options or Options()
        self.mesh = resolve_mesh(self.options)
        # per-query route observability (EXPLAIN ANALYZE surfaces this —
        # VERDICT r3 #10): how every scanned block was dispatched, plus
        # the transfer bytes each direction actually cost
        self.route_stats: dict[str, int] = {
            "device_warm": 0,  # hot-set resident: zero bytes shipped
            "device_cold": 0,  # encoded + shipped this query
            "cpu_adaptive": 0,  # link cost model routed to host
            "cpu_fallback": 0,  # unsupported-on-device / error / budget
            "h2d_bytes": 0,
            "d2h_bytes": 0,
            # program-cache traffic (stages.programs reads these): builds
            # this query, cache hits this query, rebuilds of a key that
            # was already built once (0 in steady state)
            "programs_built": 0,
            "programs_reused": 0,
            "recompiles": 0,
        }
        # query-aware prefetch (ops/prefetch.py): built lazily on the first
        # source-id'd block, once the scan has published its ordered stub
        # list; closed in execute()'s finally on every exit path
        self._prefetcher = None
        self._prefetch_tried = False

    # ------------------------------------------------------------------ main

    def execute(self, tables: Iterator[pa.Table]) -> pa.Table:
        try:
            if self.plan.is_aggregate:
                try:
                    return self._execute_aggregate_tpu(tables)
                except UnsupportedOnDevice as e:
                    # plan-time rejection: the iterator is untouched;
                    # materialize any hot stubs for the CPU engine
                    logger.info("TPU path unsupported (%s); falling back to CPU", e)
                    return super()._execute_aggregate(
                        self._materialize(t) for t in tables
                    )
            return self._execute_select_tpu(tables)
        finally:
            self._close_prefetcher()

    # ------------------------------------------------- select (mask on device)

    def _execute_select_tpu(self, tables: Iterator[pa.Table]) -> pa.Table:
        """Plain SELECT: compute the WHERE mask on device, filter host-side.

        Wrapped per-table so unsupported predicates degrade to CPU eval."""
        sel = self.plan.select

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY
        from parseable_tpu.query.planner import referenced_columns

        # the device only evaluates the WHERE mask here, so encode (and
        # cache) just the predicate's columns, not the whole projection
        mask_needed = referenced_columns(sel.where) | {DEFAULT_TIMESTAMP_KEY}

        def filtered() -> Iterator[pa.Table]:
            # bounds filtering happens once, in the inner executor's loop
            from parseable_tpu.config import env_str
            from parseable_tpu.ops.link import get_link
            from parseable_tpu.query.executor import _arr, evaluate

            adaptive = env_str("P_TPU_ADAPTIVE", "1") != "0"
            link = get_link(self.options)
            hotset_obj = get_hotset()
            compiler = PredicateCompiler()
            for table in tables:  # device-hot: per-block filter dispatch
                if sel.where is None:
                    yield table
                    continue
                if adaptive:
                    # readback here is a 1-byte-per-row filter mask
                    route, k0, rows0 = self._adaptive_gate(
                        table,
                        mask_needed,
                        set(),
                        link,
                        hotset_obj,
                        lambda r: r,
                        filter_workload=True,
                    )
                    if route:
                        ADAPTIVE_CPU_BLOCKS[0] += 1
                        self.route_stats["cpu_adaptive"] += 1
                        t0 = _time.perf_counter()
                        t = self._materialize(table)
                        mask = _arr(evaluate(sel.where, t), t)
                        out = t.filter(mask)
                        # feed the measurement back so select-heavy loads
                        # can correct a wrong routing estimate
                        link.record_cpu_filter(rows0, _time.perf_counter() - t0)
                        if k0 is not None:
                            self._warm_block(k0, table, mask_needed, set())
                        yield out
                        continue
                try:
                    enc, dev = self._encoded_block(table, mask_needed, set())
                    import jax.numpy as jnp

                    luts = [jnp.asarray(l) for l in compiler.collect_luts(sel.where, enc)]
                    mask = compiler.trace(sel.where, enc, dev, luts)
                    # bool-mask readback rides the declared, priced
                    # _timed_readback boundary (host-sync discipline)
                    mask_np = _timed_readback(mask, self.route_stats, dtype=None)[
                        : enc.num_rows
                    ]
                    # materialize defensively: projection needs row values,
                    # which a hot stub doesn't carry (selects don't receive
                    # stubs today — session gates use_hot_stubs on
                    # aggregates — but this branch must not depend on that)
                    yield self._materialize(table).filter(pa.array(mask_np))
                except UnsupportedOnDevice:
                    # evaluate against the captured (un-stripped) WHERE
                    self.route_stats["cpu_fallback"] += 1
                    mask = _arr(evaluate(sel.where, table), table)
                    yield table.filter(mask)

        # reuse CPU projection/order/limit over pre-filtered tables
        inner = QueryExecutor(self.plan)
        inner.plan.select = _strip_where(sel)
        try:
            return inner._execute_select(filtered())
        finally:
            inner.plan.select = sel

    # ----------------------------------------------------------- block cache

    # set by the session: re-reads a source when a stubbed block got evicted
    # between the provider's hot check and execution
    source_loader: Callable[[bytes], pa.Table] | None = None
    # set by the session: the StreamScan whose `prefetchable` list (ordered
    # enccache-servable stub sources) drives the query-aware prefetcher
    prefetch_scan = None

    def _ensure_prefetcher(self, needed: set[str] | None, dict_cols: set[str]) -> None:
        """Build the prefetcher once the scan has published its ordered
        stub list (first source-id'd block => the list is complete)."""
        if self._prefetch_tried or self._prefetcher is not None:
            return
        self._prefetch_tried = True
        scan = self.prefetch_scan
        sources = list(getattr(scan, "prefetchable", ()) or ())
        depth = getattr(self.options, "tpu_prefetch_depth", 2)
        if len(sources) < 2 or depth <= 0:
            return
        from parseable_tpu.ops.prefetch import ScanPrefetcher

        def ship(source_id: bytes) -> tuple | None:
            return self._prefetch_ship(source_id, needed, dict_cols)

        self._prefetcher = ScanPrefetcher(sources, ship, depth=depth)

    def _prefetch_ship(
        self, source_id: bytes, needed: set[str] | None, dict_cols: set[str]
    ) -> tuple | None:
        """Worker-thread half of the prefetcher: enccache -> device -> hot
        set. Returns the hot key on a completed ship, None when skipped."""
        from parseable_tpu.ops.enccache import get_enccache

        hotset = get_hotset()
        key = hot_key(source_id, needed, dict_cols)
        if hotset.contains(key):
            return None
        enccache = get_enccache(self.options)
        if enccache is None:
            return None
        enc = enccache.get(source_id, needed, dict_cols)
        if enc is None:
            return None
        est = sum(
            c.values.nbytes + (0 if c.all_valid else c.valid.nbytes)
            for c in enc.columns.values()
        )
        if est > hotset.budget:
            return None  # could never be admitted; don't ship it
        dev, nbytes = _transfer(enc, self.mesh)
        self.route_stats["h2d_bytes"] += nbytes
        _strip_host_values(enc)
        hotset.put(key, HotEntry(dev=dev, meta=enc, nbytes=nbytes))
        # admission control may have refused the put (probation empty,
        # candidate colder than every protected entry): only report a
        # completed ship when the entry is actually resident
        return key if hotset.contains(key) else None

    def _close_prefetcher(self) -> None:
        pf, self._prefetcher = self._prefetcher, None
        if pf is None:
            return
        counters = pf.close()
        self.route_stats.update(counters)

    def _adaptive_gate(
        self,
        table: pa.Table,
        needed: set[str] | None,
        dict_cols: set[str],
        link,
        hotset_obj,
        read_bytes: Callable[[int], int],
        filter_workload: bool = False,
    ) -> tuple[bool, tuple | None, int]:
        """Shared routing decision: (route_to_cpu, hot_key|None, rows).
        Resident blocks and small blocks always take the device path;
        otherwise estimated ship+readback cost is priced against the
        measured host rate (ops/link.py) — the filter rate for predicate
        workloads, the aggregation rate otherwise."""
        meta = table.schema.metadata or {}
        src = meta.get(SOURCE_ID_META)
        rows0 = int(meta[STUB_META]) if STUB_META in meta else table.num_rows
        if rows0 < (1 << 16):
            return False, None, rows0
        key = hot_key(src, needed, dict_cols) if src is not None else None
        if key is not None and hotset_obj.contains(key):
            return False, key, rows0
        ncols = len(needed) if needed is not None else 6
        ship = link.ship_cost(rows0 * 4 * max(ncols, 1))
        rb = read_bytes(rows0)
        if rb:  # a zero-byte readback pays no d2h latency either
            ship += link.read_cost(rb)
        cpu = (
            link.cpu_filter_cost(rows0) if filter_workload else link.cpu_cost(rows0)
        )
        return ship > cpu * 1.15, key, rows0

    def _warm_block(
        self, key: tuple, table: pa.Table, needed: set[str] | None, dict_cols: set[str]
    ) -> None:
        """Ship a CPU-routed block into the hot set off the query path."""
        from parseable_tpu.ops.link import warm_async

        try:
            warm_async(
                key, lambda t=table: self._encoded_block(t, needed, dict_cols)
            )
        except Exception:
            logger.debug("warm enqueue failed", exc_info=True)

    def _materialize(self, table: pa.Table) -> pa.Table:
        """Real rows for a table (loads the source when it's a hot stub)."""
        if not is_stub(table):
            return table
        source = (table.schema.metadata or {})[SOURCE_ID_META]
        if self.source_loader is None:
            raise UnsupportedOnDevice("stub block without a source loader")
        return self.source_loader(source)

    def _encoded_block(
        self, table: pa.Table, needed: set[str] | None, dict_cols: set[str]
    ) -> tuple[EncodedBatch, dict]:
        """Encode a table (or fetch its device-resident encoding).

        Resolution order per source-id'd block: device hot set (zero
        transfer) -> encoded-block disk cache (zero parquet decode /
        dictionary encode; ops/enccache.py) -> live encode, which
        writes-behind into the disk cache. Staging data (no source id)
        always encodes live.
        """
        hotset = get_hotset()
        meta = table.schema.metadata or {}
        source = meta.get(SOURCE_ID_META)
        key = None
        enccache = None
        if source is not None:
            key = hot_key(source, needed, dict_cols)
            # kick the lookahead BEFORE resolving this block: while it
            # encodes/ships/aggregates, the next blocks ship in background
            self._ensure_prefetcher(needed, dict_cols)
            pf = self._prefetcher
            if pf is not None:
                pf.on_block(source)
            # fetch untouched, then let the PREFETCHER decide (atomically,
            # under its condvar) whether this hit was its own ship's one
            # planned consumption — only a non-prefetch hit is proven reuse
            # and touches. The old peek-then-get(touch=...) pair had a race:
            # a ship completing between the two calls promoted prefetch
            # cargo into the protected segment (psan seed candidate).
            entry = hotset.get(key, touch=False)
            if entry is None and pf is not None and pf.claim(source):
                # the prefetcher was mid-ship on exactly this block: it
                # finished — re-check instead of shipping a second copy
                entry = hotset.get(key, touch=False)
            if entry is not None:
                if pf is None or not pf.consumed(key):
                    hotset.touch(key)
                self.route_stats["device_warm"] += 1
                return entry.meta, entry.dev
            from parseable_tpu.ops.enccache import get_enccache

            enccache = get_enccache(self.options)
            if enccache is not None:
                enc = enccache.get(source, needed, dict_cols)
                if enc is not None:
                    dev, nbytes = _transfer(enc, self.mesh)
                    self.route_stats["device_cold"] += 1
                    self.route_stats["h2d_bytes"] += nbytes
                    _strip_host_values(enc)
                    hotset.put(key, HotEntry(dev=dev, meta=enc, nbytes=nbytes))
                    return enc, dev
        table = self._materialize(table)
        enc = encode_table(table, needed, dict_columns=dict_cols)
        if enc is None:
            raise UnsupportedOnDevice("unencodable column in batch")
        dev, nbytes = _transfer(enc, self.mesh)
        self.route_stats["device_cold"] += 1
        self.route_stats["h2d_bytes"] += nbytes
        if key is not None:
            if enccache is not None:
                # snapshot-by-reference then persist off the query path
                enccache.put_async(source, enc)
            _strip_host_values(enc)
            hotset.put(key, HotEntry(dev=dev, meta=enc, nbytes=nbytes))
        return enc, dev

    # -------------------------------------------------------------- aggregate

    def _execute_aggregate_tpu(self, tables: Iterator[pa.Table]) -> pa.Table:
        import time as _t

        import jax.numpy as jnp

        sel = self.plan.select
        agg, rewritten, group_names = self.build_aggregator()
        specs = agg.specs

        key_specs = [classify_group_expr(g) for g in sel.group_by]
        lay = AccLayout.from_specs(specs)
        sum_idx = list(lay.sum_idx)
        sq_idx = list(lay.sq_idx)
        min_idx = list(lay.min_idx)
        max_idx = list(lay.max_idx)
        countcol_idx = list(lay.countcol_idx)
        pct_idx = list(lay.pct_idx)
        distinct_idx = list(lay.distinct_idx)
        stacked_idx = sum_idx + sq_idx + min_idx + max_idx + countcol_idx

        # count(distinct y): y dict-encodes like a group key; per block a
        # segment_max ORs presence bits into a [G, Vcap] device bitmap
        # (masked_distinct_bitmap design, ops/kernels.py). Exact — flush
        # decodes present codes back to values and merges them into the
        # same sets CPU-fallback blocks fill, so mixed paths stay correct.
        # approx_distinct(y) instead maxes HLL RANKS into a fixed [G,
        # HLL_M] register file (ops/hll_sketch.py): per-block dictionary
        # values hash once on host into (idx, rank) LUTs, no global
        # dictionary ever materializes, and high-cardinality distinct
        # stays on device end-to-end (VERDICT r4 #5).
        from parseable_tpu.ops.hll_sketch import HLL_M

        dkeys = [
            KeySpec("dict", specs[i].arg.name, specs[i].arg, gdict=GlobalDict())
            for i in distinct_idx
        ]
        dk_sketch = [specs[i].func == "approx_distinct" for i in distinct_idx]
        for dk, sk in zip(dkeys, dk_sketch):
            if sk:
                dk.capacity = HLL_M

        compiler = PredicateCompiler()
        dict_cols = {ks.column for ks in key_specs if ks.kind == "dict"}
        dict_cols |= {dk.column for dk in dkeys}

        acc = None  # device-resident packed accumulator (R, G) f32
        dacc: list = []  # per-distinct [G * Vcap] f32 presence bitmaps
        pacc: list = []  # per-percentile [G * DEVICE_NB] f32 histograms
        acc_groups = 0

        def new_acc(num_groups: int):
            """Packed accumulator rows (AccLayout): count | per-agg counts |
            sums (incl. stddev x and x^2) | mins (incl. pct vmin) | maxs."""
            parts = [
                np.zeros((1 + lay.n_allk + lay.n_sumk, num_groups), np.float32),
                np.full((lay.n_mink, num_groups), np.float32(3.4e38)),
                np.full((lay.n_maxk, num_groups), np.float32(-3.4e38)),
            ]
            host = np.concatenate(parts, axis=0)
            if self.mesh is not None:
                import jax

                _, rep_s = _mesh_shardings(self.mesh)
                # priced: the zeroed accumulator ships once per query
                self.route_stats["h2d_bytes"] += int(host.nbytes)
                DEVICE_BYTES_TO_DEVICE.labels("acc").inc(host.nbytes)
                return jax.device_put(host, rep_s)
            return jnp.asarray(host)

        def new_flat(size: int):
            host = np.zeros(size, np.float32)
            if self.mesh is not None:
                import jax

                _, rep_s = _mesh_shardings(self.mesh)
                # priced: once-per-query sparse accumulator ship
                self.route_stats["h2d_bytes"] += int(host.nbytes)
                DEVICE_BYTES_TO_DEVICE.labels("acc").inc(host.nbytes)
                return jax.device_put(host, rep_s)
            return jnp.asarray(host)

        def flush(acc_dev, num_groups: int) -> None:
            """ONE device->host readback per accumulator, folded into the
            sparse agg (distinct presence bitmaps and percentile histograms
            decode alongside)."""
            arr = _timed_readback(acc_dev, self.route_stats)
            dists = [
                (
                    si,
                    dk,
                    _timed_readback(d, self.route_stats, dtype=None).reshape(
                        num_groups, dk.capacity
                    ),
                )
                for si, dk, d in zip(distinct_idx, dkeys, dacc)
            ]
            pcts = [
                (si, self._read_hist(h, num_groups))
                for si, h in zip(pct_idx, pacc)
            ]
            self._flush_state(arr, key_specs, agg, specs, lay, dists, pcts)

        # Coalesce scan tables into larger device blocks: dispatch latency is
        # the budget, so fewer/bigger blocks win (Options.device_block_rows).
        # Tables carrying a source id stay un-coalesced so their encodings
        # are reusable across queries via the hot set.
        target_rows = max(1 << 16, self.options.device_block_rows)

        max_block_rows = MAX_BLOCK_ROWS

        def blocks(src: Iterator[pa.Table]) -> Iterator[pa.Table]:
            buf: list[pa.Table] = []
            rows = 0
            for t in src:
                if t.num_rows > max_block_rows:
                    # split oversized tables (giant parquet/arrow inputs);
                    # slices lose hot-set identity (a partial block must
                    # not serve future full-block reads)
                    if buf:
                        yield _concat_tables(buf)
                        buf, rows = [], 0
                    bare = t.replace_schema_metadata(None)
                    for off in range(0, t.num_rows, max_block_rows):
                        yield bare.slice(off, max_block_rows)
                    continue
                if (t.schema.metadata or {}).get(SOURCE_ID_META) is not None:
                    yield t
                    continue
                buf.append(t)
                rows += t.num_rows
                if rows >= target_rows:
                    yield _concat_tables(buf)
                    buf, rows = [], 0
            if buf:
                yield _concat_tables(buf)

        # Blocks with identical shape signatures batch into one dispatch of
        # up to GROUP_N unrolled folds — per-dispatch latency dominates on
        # tunneled backends, so 8 blocks per round trip is an 8x cut.
        GROUP_N = 8
        pending: list[tuple] = []  # (table, enc, dev, dev_luts, dev_remaps, row_mask)
        pending_sig: tuple | None = None

        def fold_pending_on_cpu() -> None:
            """Program build/trace failed: aggregate the buffered blocks'
            source tables on the CPU instead (never raises past here)."""
            self.route_stats["cpu_fallback"] += len(pending)
            for x in pending:
                t = self._bounds_filter(self._materialize(x[0]))
                agg.update(t, self._where_mask(t))
            pending.clear()

        def dispatch_pending() -> None:
            nonlocal acc, dacc, pacc
            if not pending:
                return
            enc0 = pending[0][1]
            layout = PlanLayout(
                key_specs=key_specs,
                caps=tuple(ks.capacity for ks in key_specs),
                origins=tuple(ks.origin_rel or 0 for ks in key_specs),
                sum_cols=[specs[i].arg.name for i in sum_idx],
                min_cols=[specs[i].arg.name for i in min_idx],
                max_cols=[specs[i].arg.name for i in max_idx],
                stacked_cols=[specs[i].arg.name for i in stacked_idx],
                distinct_cols=[dk.column for dk in dkeys],
                distinct_caps=tuple(dk.capacity for dk in dkeys),
                distinct_sketch=tuple(dk_sketch),
                sq_cols=[specs[i].arg.name for i in sq_idx],
                pct_cols=[specs[i].arg.name for i in pct_idx],
                cnt_cols=[specs[i].arg.name for i in countcol_idx],
            )
            try:
                program = self._get_program(
                    enc0,
                    layout,
                    acc_groups,
                    pending_sig[1],
                    pending_sig[2],
                    n_blocks=len(pending),
                    dev_keys=tuple(sorted(pending[0][2].keys())),
                    dremap_shapes=pending_sig[3],
                )
                acc, dacc_out, pacc_out = program(
                    acc,
                    tuple(dacc),
                    tuple(pacc),
                    tuple(x[2] for x in pending),
                    tuple(x[3] for x in pending),
                    tuple(x[4] for x in pending),
                    tuple(x[5] for x in pending),
                    tuple(x[6] for x in pending),
                )
                dacc = list(dacc_out)
                pacc = list(pacc_out)
                pending.clear()
            except UnsupportedOnDevice as e:
                logger.debug("pending blocks on CPU (%s)", e)
                fold_pending_on_cpu()
            except Exception:
                logger.exception("device dispatch failed; CPU fallback for pending blocks")
                fold_pending_on_cpu()

        # block-local (two-phase) state: partial-format tables awaiting the
        # vectorized host merge (high-cardinality group spaces)
        local_mode = False
        partials: list[pa.Table] = []
        local_layout = PlanLayout(
            key_specs=key_specs,
            caps=(),
            origins=(),
            sum_cols=[specs[i].arg.name for i in sum_idx],
            min_cols=[specs[i].arg.name for i in min_idx],
            max_cols=[specs[i].arg.name for i in max_idx],
            stacked_cols=[specs[i].arg.name for i in stacked_idx],
            sq_cols=[specs[i].arg.name for i in sq_idx],
            cnt_cols=[specs[i].arg.name for i in countcol_idx],
        )

        # adaptive dispatch: per non-resident block, estimated ship (+
        # local-mode readback) cost vs measured CPU aggregation cost
        # (ops/link.py) — a degraded link must not make cold scans 10x
        # slower than the host. Routed blocks still warm the device hot
        # set in the background so the NEXT query runs warm.
        import os

        from parseable_tpu.ops.link import get_link
        from parseable_tpu.query.partials import (
            partial_from_block,
            specs_partializable,
        )

        from parseable_tpu.config import env_str

        adaptive = env_str("P_TPU_ADAPTIVE", "1") != "0"
        link = get_link(self.options)
        needed = self.plan.needed_columns
        n_acc_rows = lay.n_rows
        hotset_obj = get_hotset()
        partializable = bool(sel.group_by) and specs_partializable(specs)

        def cpu_block(table: pa.Table) -> None:
            """Aggregate one block on the host, into partials when the
            specs allow (vectorized; a 1M-group block must not hit the
            per-group Python aggregator)."""
            t0 = _time.perf_counter()
            # same row basis the gate prices on (raw block rows / stub
            # meta, BEFORE the bounds filter) — recording post-bounds rows
            # while pricing pre-bounds rows skews the EWMA under heavy
            # time pruning and misroutes blocks (ADVICE r3 #2)
            meta = table.schema.metadata or {}
            rows_scanned = (
                int(meta[STUB_META]) if STUB_META in meta else table.num_rows
            )
            t = self._bounds_filter(self._materialize(table))
            mask = self._where_mask(t)
            if partializable:
                if mask is not None:
                    t = t.filter(mask)
                pt = partial_from_block(t, sel.group_by, specs)
                if pt is not None:
                    partials.append(pt)
            else:
                agg.update(t, mask)
            link.record_cpu_agg(rows_scanned, _time.perf_counter() - t0)

        t_start = _t.monotonic()
        # set when the scan discovers device percentiles/distincts can't fit
        # this query's group space: stop paying encode+transfer per block
        # just to rediscover it — the rest of the scan is host-side
        force_cpu_rest = False
        for table in blocks(tables):  # device-hot: per-block agg dispatch
            self._check_deadline()
            if force_cpu_rest:
                self.route_stats["cpu_fallback"] += 1
                cpu_block(table)
                continue
            # adaptive routing decides OUTSIDE the device-fallback try: the
            # fallback handler re-aggregates the block, and a block that
            # cpu_block already (even partially) folded must never reach it
            if adaptive and not dkeys:
                # two-phase (local) blocks read back a dense G-sized
                # partial; the dense path reads back nothing per block
                route, k0, _ = self._adaptive_gate(
                    table,
                    needed,
                    dict_cols,
                    link,
                    hotset_obj,
                    (
                        (lambda r: min(r, LOCAL_G_MAX) * n_acc_rows * 4)
                        if local_mode
                        else (lambda r: 0)
                    ),
                )
                if route:
                    ADAPTIVE_CPU_BLOCKS[0] += 1
                    self.route_stats["cpu_adaptive"] += 1
                    cpu_block(table)
                    if k0 is not None:
                        self._warm_block(k0, table, needed, dict_cols)
                    continue
            try:
                enc, dev = self._encoded_block(table, self.plan.needed_columns, dict_cols)
                for i in stacked_idx + pct_idx:
                    col = enc.columns.get(specs[i].arg.name)
                    if col is None:
                        raise UnsupportedOnDevice(f"aggregate column {specs[i].arg.name} missing")
                    if col.kind in ("dict", "time") and i not in countcol_idx:
                        raise UnsupportedOnDevice(f"numeric aggregate over {col.kind} column")
                luts = compiler.collect_luts(sel.where, enc)
                if local_mode:
                    self._local_block(
                        partials, enc, dev, luts, key_specs, specs, local_layout, lay,
                    )
                    continue
                remaps = [
                    ks.gdict.absorb(enc.columns[ks.column].dictionary)
                    if ks.kind == "dict" and ks.column in enc.columns
                    else None
                    for ks in key_specs
                ]
                if any(r is None and ks.kind == "dict" for r, ks in zip(remaps, key_specs)):
                    raise UnsupportedOnDevice("group key column missing from batch")
                dremaps_np = []
                for dk, sk in zip(dkeys, dk_sketch):
                    col = enc.columns.get(dk.column)
                    if col is None or col.kind != "dict":
                        raise UnsupportedOnDevice(f"distinct column {dk.column} not dict-encoded")
                    if sk:
                        # HLL (idx, rank) LUT over THIS block's dictionary:
                        # no global dictionary grows, cached per batch
                        dremaps_np.append(self._hll_lut(enc, col))
                    else:
                        dremaps_np.append(dk.gdict.absorb(col.dictionary))

                layouts = [self._required_layout(ks, enc) for ks in key_specs]
                caps = tuple(c for _, c in layouts)
                origins = tuple(o for o, _ in layouts)
                dlayouts = [
                    (0, HLL_M) if sk else self._required_layout(dk, enc)
                    for dk, sk in zip(dkeys, dk_sketch)
                ]
                dcaps = tuple(c for _, c in dlayouts)
                new_groups = 1
                for c in caps:
                    new_groups *= c
                new_groups = max(new_groups, 1)
                # presence bitmaps are device-resident [G, Vcap] f32 each —
                # bound the footprint, else fall back (exact) to the CPU.
                # HLL register files have a FIXED cap (HLL_M) so they get a
                # larger budget (1<<27 slots = 512 MB f32 -> G up to 32k):
                # group count, not value cardinality, is their only axis
                if any(
                    new_groups * c > ((1 << 27) if sk else (1 << 24))
                    for c, sk in zip(dcaps, dk_sketch)
                ):
                    # caps only grow (gdict.absorb is monotonic; the group
                    # space only widens): no later block can fit either,
                    # so stop paying encode+transfer
                    force_cpu_rest = True
                    raise UnsupportedOnDevice(
                        "distinct state exceeds device budget (G*V too large)"
                    )
                # percentile histograms are [G, DEVICE_NB] f32 each; past
                # the footprint budget the whole scan aggregates host-side
                # (exact sketches) rather than thrashing device HBM
                if pct_idx and new_groups * DEVICE_NB > PCT_MAX_ELEMS:
                    force_cpu_rest = True
                    raise UnsupportedOnDevice(
                        "percentile histogram exceeds device budget (G too large)"
                    )
                if new_groups > DENSE_G_MAX:
                    # the dense global group space outgrew the device budget:
                    # switch to block-local two-phase aggregation for the
                    # rest of the scan (exact; no capacity-epoch churn)
                    if dkeys or pct_idx:
                        force_cpu_rest = True
                        raise UnsupportedOnDevice(
                            "high-cardinality group space with sketch/set state"
                        )
                    dispatch_pending()
                    if acc is not None:
                        pt = self._dense_to_partial(
                            acc, acc_groups, key_specs, specs, lay,
                        )
                        if pt is not None:
                            partials.append(pt)
                        acc = None
                        dacc = []
                    local_mode = True
                    logger.info(
                        "group space %d exceeds dense budget; block-local two-phase mode",
                        new_groups,
                    )
                    self._local_block(
                        partials, enc, dev, luts, key_specs, specs, local_layout, lay,
                    )
                    continue
                current = tuple((ks.origin_rel or 0, ks.capacity) for ks in key_specs)
                dcurrent = tuple(dk.capacity for dk in dkeys)
                if acc is None or tuple(zip(origins, caps)) != current or dcaps != dcurrent:
                    dispatch_pending()  # under the old epoch's layout
                    if acc is not None:
                        if distinct_idx or pct_idx:
                            # distinct bitmaps / percentile histograms
                            # decode through the sparse agg
                            flush(acc, acc_groups)
                        else:
                            # vectorized epoch flush: no per-group Python
                            pt = self._dense_to_partial(
                                acc, acc_groups, key_specs, specs, lay,
                            )
                            if pt is not None:
                                partials.append(pt)
                    for ks, (o, c) in zip(key_specs, layouts):
                        ks.capacity = c
                        ks.origin_rel = o if ks.kind == "timebin" else None
                    for dk, c in zip(dkeys, dcaps):
                        dk.capacity = c
                    acc_groups = new_groups
                    acc = new_acc(acc_groups)
                    dacc = [new_flat(acc_groups * c) for c in dcaps]
                    pacc = [new_flat(acc_groups * DEVICE_NB) for _ in pct_idx]

                # per-block time scalars (bin shift/offset + bounds) append
                # after the predicate LUTs; the fold consumes them from the
                # tail so one compiled program serves every block origin
                luts = luts + self._time_args(
                    enc,
                    key_specs,
                    tuple(ks.origin_rel or 0 for ks in key_specs),
                    self._bounds_ms(),
                )
                kinds = tuple(sorted((n, c.kind) for n, c in enc.columns.items()))
                sig = (
                    (enc.block_rows, kinds, "__rowmask" in dev),
                    tuple(l.shape for l in luts),
                    tuple(r.shape if r is not None else None for r in remaps),
                    tuple(r.shape for r in dremaps_np),
                )
                if pending and sig != pending_sig:
                    dispatch_pending()
                pending_sig = sig
                mesh_data = (
                    self.mesh.shape.get("data", self.mesh.size)
                    if self.mesh is not None
                    else 1
                )
                if self.mesh is not None and enc.block_rows % mesh_data == 0:
                    import jax

                    _, rep_s = _mesh_shardings(self.mesh)

                    def put_rep(a, _s=rep_s, _jax=jax):
                        # priced: LUT/remap ships ride outside _transfer's
                        # packed payload, so the link accounting must see
                        # them here (no latency sample — the puts are async
                        # and a probe would serialize the batch loop)
                        n = int(getattr(a, "nbytes", 0))
                        self.route_stats["h2d_bytes"] += n
                        DEVICE_BYTES_TO_DEVICE.labels("lut").inc(n)
                        return _jax.device_put(a, _s)
                else:
                    put_rep = jnp.asarray
                dev_luts = tuple(put_rep(l) for l in luts)
                dev_remaps = tuple(put_rep(r) for r in remaps if r is not None)
                dev_dremaps = tuple(put_rep(r) for r in dremaps_np)
                row_mask = dev.get("__rowmask", dev["__ones"])
                pending.append((table, enc, dev, dev_luts, dev_remaps, dev_dremaps, row_mask))
                if len(pending) >= GROUP_N:
                    dispatch_pending()
            except UnsupportedOnDevice as e:
                logger.debug("batch on CPU (%s)", e)
                self.route_stats["cpu_fallback"] += 1
                t = self._bounds_filter(self._materialize(table))
                agg.update(t, self._where_mask(t))
            except Exception:
                logger.exception("device aggregation failed for a batch; CPU fallback")
                self.route_stats["cpu_fallback"] += 1
                t = self._bounds_filter(self._materialize(table))
                agg.update(t, self._where_mask(t))

        dispatch_pending()
        if partials or (local_mode and (acc is not None or agg.groups)):
            # two-phase finalize: dense epoch + device block partials +
            # CPU-fallback groups all merge through ONE pyarrow group_by
            if acc is not None:
                pt = self._dense_to_partial(
                    acc, acc_groups, key_specs, specs, lay,
                )
                if pt is not None:
                    partials.append(pt)
                acc = None
            apt = self._agg_groups_to_partial(agg, specs, len(key_specs))
            if apt is not None:
                partials.append(apt)
            interim = self._merge_partials(partials, specs, len(key_specs))
            DEVICE_EXECUTE_TIME.labels("groupby").observe(_t.monotonic() - t_start)
            return self.finalize_from_interim(interim, rewritten)
        # vectorized dense finalize: when the run stayed fully on device
        # (no CPU-fallback partials, no distinct sets), skip the per-group
        # Python fold entirely — at G=32k the sparse path is ~80% of query
        # time (VERDICT Weak#5)
        if acc is not None and not agg.groups and not distinct_idx:
            # the K-gather reads only the packed accumulator; percentile
            # histograms live beside it, so top-K pushdown requires a
            # histogram gather too — not worth it, take the full readback
            topk_req = (
                self._device_topk_plan(rewritten)
                if sel.group_by and not pct_idx
                else None
            )
            if (
                topk_req is not None
                and acc_groups >= self.TOPK_MIN_GROUPS
                and topk_req[2] < acc_groups
            ):
                interim = None
                try:
                    tsi, tdesc, tk = topk_req
                    arr_k, ids = self._run_topk_program(
                        acc, tsi, tdesc, tk, lay, specs,
                    )
                    interim = self._dense_interim(
                        arr_k, acc_groups, key_specs, specs, lay,
                        group_ids=ids,
                    )
                except Exception:
                    logger.exception(
                        "device top-k gather failed; full readback fallback"
                    )
                if interim is not None:
                    DEVICE_EXECUTE_TIME.labels("groupby").observe(
                        _t.monotonic() - t_start
                    )
                    return self.finalize_from_interim(interim, rewritten)
            pcts = [
                (si, self._read_hist(h, acc_groups))
                for si, h in zip(pct_idx, pacc)
            ]
            interim = self._dense_interim(
                _timed_readback(acc, self.route_stats), acc_groups, key_specs,
                specs, lay, pcts=pcts,
            )
            DEVICE_EXECUTE_TIME.labels("groupby").observe(_t.monotonic() - t_start)
            if interim.num_rows == 0 and not sel.group_by:
                return self.finalize_aggregate(agg, rewritten, group_names)
            return self.finalize_from_interim(interim, rewritten)
        if acc is not None:
            flush(acc, acc_groups)
        DEVICE_EXECUTE_TIME.labels("groupby").observe(_t.monotonic() - t_start)
        return self.finalize_aggregate(agg, rewritten, group_names)

    def _dense_interim(
        self,
        arr: np.ndarray,
        num_groups: int,
        key_specs: list[KeySpec],
        specs: list[AggSpec],
        lay: AccLayout,
        group_ids: np.ndarray | None = None,
        pcts: list[tuple[int, np.ndarray]] | None = None,
    ) -> pa.Table:
        """Dense device accumulator -> interim table (__g/__agg columns),
        fully vectorized: key decode by divmod over capacities, aggregate
        finalize by numpy masking (stddev/var from the packed sum/sumsq
        rows; percentiles via the vectorized histogram walk). One readback,
        zero per-group Python.

        With `group_ids`, `arr` is a device-side top-K GATHER (R, K) and
        group_ids[j] is column j's global group index — the readback is
        K-sized instead of G-sized (ORDER BY <agg> LIMIT pushdown)."""
        count = arr[0]
        if group_ids is None:
            idxs = np.nonzero(count > 0)[0]
            sel_pos = idxs
        else:
            sel_pos = np.nonzero(count > 0)[0]  # positions into the K gather
            idxs = group_ids[sel_pos]  # global ids, for key decode

        cols: dict[str, pa.Array] = {}
        rem = idxs.copy()
        for i, ks in enumerate(key_specs):
            codes = rem % ks.capacity
            rem = rem // ks.capacity
            if ks.kind == "dict":
                gd = ks.gdict
                values = np.empty(len(gd) + 1, dtype=object)
                values[:-1] = gd.values
                values[-1] = None  # null / overflow slot
                cols[f"__g{i}"] = pa.array(values[np.minimum(codes, len(gd))].tolist())
            else:
                abs_ms = ((ks.origin_rel or 0) + codes) * ks.bin_ms
                cols[f"__g{i}"] = pa.array(
                    abs_ms.astype("datetime64[ms]"), pa.timestamp("ms")
                )
        pct_hists = dict(pcts or [])
        for si, spec in enumerate(specs):
            if spec.func == "count_star":
                cols[f"__agg{si}"] = pa.array(count[sel_pos].astype(np.int64))
                continue
            pac = arr[lay.pac_row(si)][sel_pos]
            seen = pac > 0
            if spec.func == "count":
                cols[f"__agg{si}"] = pa.array(pac.astype(np.int64))
            elif spec.func in ("sum", "avg"):
                v = arr[lay.sum_row(si)][sel_pos]
                if spec.func == "avg":
                    v = np.divide(v, pac, out=np.zeros_like(v), where=seen)
                cols[f"__agg{si}"] = pa.array(v, mask=~seen)
            elif spec.func in ("stddev", "var"):
                n = pac
                m2 = arr[lay.sqm2_row(si)][sel_pos]
                ok = n >= 2
                var = np.divide(m2, n - 1, out=np.zeros_like(m2), where=ok)
                var = np.maximum(var, 0.0)  # guard f.p. negatives
                v = np.sqrt(var) if spec.func == "stddev" else var
                cols[f"__agg{si}"] = pa.array(v, mask=~ok)
            elif spec.func == "percentile":
                from parseable_tpu.query.sketch import hist_quantile

                hist = pct_hists[si][idxs]
                vmins = arr[lay.pct_min_row(si)][sel_pos]
                vmaxs = arr[lay.pct_max_row(si)][sel_pos]
                v, ok = hist_quantile(
                    hist, vmins, vmaxs,
                    spec.param if spec.param is not None else 0.5,
                )
                cols[f"__agg{si}"] = pa.array(v, mask=~ok)
            elif spec.func == "min":
                v = arr[lay.min_row(si)][sel_pos]
                cols[f"__agg{si}"] = pa.array(v, mask=~seen)
            elif spec.func == "max":
                v = arr[lay.max_row(si)][sel_pos]
                cols[f"__agg{si}"] = pa.array(v, mask=~seen)
        if not cols:
            return pa.table({"__dummy": pa.array([None] * len(idxs))})
        return pa.table(cols)

    # --------------------------------------------- ORDER BY <agg> LIMIT K

    TOPK_MIN_GROUPS = 1 << 13  # below this the full readback is cheap
    TOPK_MAX_K = 4096

    def _device_topk_plan(self, rewritten: list[S.SelectItem]) -> tuple | None:
        """(spec_index, desc, k) when the query's ORDER BY/LIMIT can run as
        a device top_k over the dense accumulator: single ORDER BY key that
        resolves to one of the aggregates, LIMIT (+OFFSET) small, no HAVING
        (DataFusion's TopK pushdown; reference planner gets it from
        /root/reference/src/query/mod.rs:212-276)."""
        sel = self.plan.select
        if (
            len(sel.order_by) != 1
            or sel.limit is None
            or getattr(self, "_having", None) is not None
        ):
            return None
        if any(S.contains_window(i.expr) for i in sel.items):
            # a window over the aggregate output (rank() OVER, percent-of-
            # total) must see ALL groups, not the K gathered ones
            return None
        k = (sel.offset or 0) + sel.limit
        if k <= 0 or k > self.TOPK_MAX_K:
            return None
        o = sel.order_by[0]
        for item, ritem in zip(sel.items, rewritten):
            if not (
                isinstance(ritem.expr, S.Column) and ritem.expr.name.startswith("__agg")
            ):
                continue
            alias_match = (
                isinstance(o.expr, S.Column)
                and o.expr.table is None
                and ritem.alias == o.expr.name
            )
            if alias_match or repr(item.expr) == repr(o.expr):
                return int(ritem.expr.name[5:]), o.desc, k
        return None

    def _run_topk_program(
        self,
        acc,
        si: int,
        desc: bool,
        k: int,
        lay: AccLayout,
        specs: list[AggSpec],
    ) -> tuple[np.ndarray, np.ndarray]:
        """Select the top-k groups by one aggregate ON DEVICE and read back
        only the (R, k) gather + k group ids — the G-sized accumulator
        never crosses the link (readback is the slow direction on a
        tunneled chip: ~9 MB/s vs 750 MB/s in)."""
        import jax
        import jax.numpy as jnp

        spec = specs[si]
        kind = spec.func
        pac_row = lay.pac_row(si) if kind != "count_star" else 0
        if kind in ("sum", "avg"):
            val_row = lay.sum_row(si)
        elif kind in ("stddev", "var"):
            val_row = lay.sqx_row(si)  # variance computed in-program
        elif kind == "min":
            val_row = lay.min_row(si)
        elif kind == "max":
            val_row = lay.max_row(si)
        else:  # count / count_star
            val_row = pac_row
        sq_row = lay.sqm2_row(si) if kind in ("stddev", "var") else 0
        key = ("topk", acc.shape, kind, val_row, pac_row, sq_row, desc, k)
        program = _PROGRAM_CACHE.get(key)
        if program is None:

            def run(a):
                count = a[0]
                pacv = a[pac_row]
                if kind == "avg":
                    keyv = a[val_row] / jnp.maximum(pacv, 1.0)
                elif kind in ("stddev", "var"):
                    n = jnp.maximum(pacv, 2.0)
                    keyv = jnp.maximum(a[sq_row] / (n - 1.0), 0.0)
                    if kind == "stddev":
                        keyv = jnp.sqrt(keyv)
                else:
                    keyv = a[val_row]
                if kind in ("sum", "avg", "min", "max"):
                    notnull = pacv > 0
                elif kind in ("stddev", "var"):
                    notnull = pacv > 1  # n < 2 -> NULL variance
                else:
                    notnull = count > 0
                occupied = count > 0
                live = occupied & notnull
                # Exact composite order in int32 (ADVICE r3 #1: a finite
                # f32 sentinel let -inf/-3.4e38 real keys be displaced by
                # NULL groups). The f32 bit pattern maps to a monotonic
                # int32 whose range [-2139095040, 2139095040] (-inf..+inf)
                # leaves headroom below for NaN keys, NULL-agg groups and
                # empty slots — in that (nulls-last) order. top_k over the
                # int32 scores is then a true three-class lexicographic
                # sort with zero collisions against real keys.
                kf = keyv.astype(jnp.float32)
                nan = jnp.isnan(kf)
                bits = jax.lax.bitcast_convert_type(kf, jnp.int32)
                u = jnp.where(bits >= 0, bits, jnp.int32(-2147483648) - bits)
                o = u if desc else jnp.where(
                    u == jnp.int32(-2147483648), jnp.int32(2147483647), -u
                )
                score = jnp.where(
                    live & ~nan,
                    o,
                    jnp.where(
                        live, jnp.int32(-2139095339),  # NaN key: below reals
                        jnp.where(
                            occupied, jnp.int32(-2147483647),  # NULL agg
                            jnp.int32(-2147483648),  # empty slot
                        ),
                    ),
                )
                _, idx = jax.lax.top_k(score, k)
                return a[:, idx], idx

            # no donate_argnums: `acc` outlives the top-k (the flush path
            # reads it) and donation round-trips on tunneled PJRT backends
            # (see the executor.dense note in _get_program)
            program = jax.jit(run)  # jit-cache: executor.topk
            _note_program_build("executor.topk", key, self.route_stats)
            _PROGRAM_CACHE[key] = program
        else:
            self.route_stats["programs_reused"] += 1
        gathered, idx = program(acc)
        return (
            _timed_readback(gathered, self.route_stats),
            _timed_readback(idx, self.route_stats, dtype=None),
        )

    # ----------------------------------------------- high-card (block-local)

    def _local_block(
        self,
        partials: list[pa.Table],
        enc: EncodedBatch,
        dev: dict,
        luts: list[np.ndarray],
        key_specs: list[KeySpec],
        specs: list[AggSpec],
        layout: PlanLayout,
        lay: AccLayout,
    ) -> None:
        """Two-phase step: fold one block on its OWN dictionary codes (no
        global remap), read back the dense [G_block] partial, extract the
        nonzero groups as a partial-format table."""
        import jax.numpy as jnp

        caps: list[int] = []
        origins: list[int] = []
        keyinfo: list[tuple] = []
        for ks in key_specs:
            col = enc.columns.get(ks.column)
            if col is None:
                raise UnsupportedOnDevice(f"group key column {ks.column} missing")
            if ks.kind == "dict":
                if col.kind != "dict":
                    raise UnsupportedOnDevice(f"group key {ks.column} not dict-encoded")
                cap = _pow2(max(2, len(col.dictionary)))
                caps.append(cap)
                origins.append(0)
                keyinfo.append(("dict", list(col.dictionary), cap))
            else:
                if col.vmin is None or col.vmax is None:
                    raise UnsupportedOnDevice("time-bin key over all-null column")
                lo_bin = (enc.time_origin_ms + col.vmin) // ks.bin_ms
                hi_bin = (enc.time_origin_ms + col.vmax) // ks.bin_ms
                span = int(hi_bin - lo_bin + 1)
                cap = _pow2(max(2, span))
                if cap > LOCAL_G_MAX:
                    raise UnsupportedOnDevice("time-bin span exceeds device capacity")
                caps.append(cap)
                origins.append(int(lo_bin))
                keyinfo.append(("timebin", int(lo_bin), ks.bin_ms, cap))
        num_groups = 1
        for c in caps:
            num_groups *= c

        mesh = self.mesh
        n_data = mesh.shape.get("data", mesh.size) if mesh is not None else 1
        use_mesh = mesh is not None and enc.block_rows % n_data == 0
        if use_mesh:
            import jax

            row_s, rep_s = _mesh_shardings(self.mesh)

            def put_rep(a, _s=rep_s, _jax=jax):
                # priced: local-fold LUT ships bypass _transfer's packed
                # payload, so the link accounting happens at the ship
                n = int(getattr(a, "nbytes", 0))
                self.route_stats["h2d_bytes"] += n
                DEVICE_BYTES_TO_DEVICE.labels("lut").inc(n)
                return _jax.device_put(a, _s)

            def put_row(a, _s=row_s, _jax=jax):
                n = int(getattr(a, "nbytes", 0))
                self.route_stats["h2d_bytes"] += n
                DEVICE_BYTES_TO_DEVICE.labels("lut").inc(n)
                return _jax.device_put(a, _s)
        else:
            put_rep = jnp.asarray
            put_row = jnp.asarray
        row_mask = dev.get("__rowmask", dev["__ones"])

        composite_vals: np.ndarray | None = None
        if num_groups > LOCAL_G_MAX:
            # cap product exceeds the budget, but the block's ACTUAL key
            # combos can't exceed its rows: compact (c0..ck) tuples with one
            # np.unique and fold on dense pair codes instead
            comp = None
            for ks, cap, origin in zip(key_specs, caps, origins):
                vals = self._host_codes(enc, dev, ks.column)
                if ks.kind == "dict":
                    codes = np.minimum(vals.astype(np.int64), cap - 1)
                else:
                    abs_ms = vals.astype(np.int64) + enc.time_origin_ms
                    codes = np.clip(abs_ms // ks.bin_ms - origin, 0, cap - 1)
                comp = codes if comp is None else comp * cap + codes
            uniq, inv = np.unique(comp, return_inverse=True)
            num_groups = _pow2(max(2, len(uniq)))
            if num_groups > LOCAL_G_MAX:
                raise UnsupportedOnDevice(
                    "distinct key combos exceed the device group budget"
                )
            composite_vals = uniq
            dev = dict(dev)
            dev["__pairkey"] = put_row(inv.astype(np.int32))

        if composite_vals is None:
            key_sig = tuple((ks.kind, ks.column, ks.bin_ms) for ks in key_specs)
            full_luts = luts + self._time_args(enc, key_specs, origins, self._bounds_ms())
        else:
            key_sig = (("pair", "__pairkey", 0),)
            full_luts = luts + self._time_args(enc, [], (), self._bounds_ms())
        dev_luts = tuple(put_rep(l) for l in full_luts)

        program = self._get_local_program(
            enc,
            tuple(caps),
            tuple(origins),
            key_sig,
            layout,
            tuple(l.shape for l in full_luts),
            tuple(sorted(dev.keys())),
            num_groups,
        )
        out = _timed_readback(program(dev, dev_luts, row_mask), self.route_stats)
        pt = self._partial_from_arrays(
            out, lay, keyinfo, specs, composite_vals=composite_vals,
        )
        if pt is not None:
            partials.append(pt)

    @staticmethod
    def _hll_lut(enc: EncodedBatch, col: EncodedColumn) -> np.ndarray:
        """[2, N] (idx, rank) HLL LUT over the block's dictionary, cached
        on the batch (lifetime == dictionary lifetime) so hot-set-resident
        blocks hash their values exactly once."""
        cache = getattr(enc, "lut_cache", None)
        if cache is None:
            cache = {}
            enc.lut_cache = cache
        key = ("__hll", col.name, len(col.dictionary))
        hit = cache.get(key)
        if hit is None:
            from parseable_tpu.ops.hll_sketch import luts_for_dictionary

            idx, rank = luts_for_dictionary(col.dictionary)
            hit = np.stack([idx, rank]).astype(np.int32)
            cache[key] = hit
        return hit

    @staticmethod
    def _host_codes(enc: EncodedBatch, dev: dict, column: str) -> np.ndarray:
        """A column's encoded codes on host: the encode-time array when it
        still exists, else a readback (hot-set entries strip host copies)."""
        col = enc.columns.get(column)
        if col is None:
            raise UnsupportedOnDevice(f"group key column {column} missing")
        if col.values is not None and len(col.values):
            return col.values
        # rare readback — hot-set entries strip host copies, so
        # sync-boundary: re-materializing the codes is the only source left
        return np.asarray(dev[column])

    def _get_local_program(
        self,
        enc: EncodedBatch,
        caps: tuple[int, ...],
        origins: tuple[int, ...],
        key_sig: tuple,
        layout: PlanLayout,
        lut_shapes: tuple,
        dev_keys: tuple,
        num_groups: int,
    ) -> Callable:
        """One jitted dispatch for a block-local partial: mask + own-code
        group ids + fused aggregate; partials psum over the mesh data axis."""
        mesh = self.mesh
        n_data = mesh.shape.get("data", mesh.size) if mesh is not None else 1
        if mesh is not None and enc.block_rows % n_data:
            mesh = None
        kinds = tuple(sorted((n, c.kind) for n, c in enc.columns.items()))
        bounds_ms = self._bounds_ms()
        key = (
            "local",
            _expr_fingerprint(self.plan.select.where),
            (bounds_ms[0] is not None, bounds_ms[1] is not None),
            key_sig,
            caps,
            # origins deliberately NOT in the key: the block's bin offset
            # ships as a runtime scalar, so one program serves every block
            num_groups,
            tuple(layout.stacked_cols),
            tuple(layout.sum_cols),
            tuple(layout.min_cols),
            tuple(layout.max_cols),
            tuple(layout.sq_cols),
            tuple(layout.cnt_cols),
            enc.block_rows,
            kinds,
            lut_shapes,
            dev_keys,
            None if mesh is None else id(mesh),
        )
        prog = _PROGRAM_CACHE.get(key)
        if prog is not None:
            self.route_stats["programs_reused"] += 1
            return prog

        import jax
        import jax.numpy as jnp

        sel_where = self.plan.select.where
        compiler = PredicateCompiler()
        n_timebin = sum(1 for k in key_sig if k[0] == "timebin")
        n_bounds = sum(1 for b in bounds_ms if b is not None)
        n_time_args = 2 * n_timebin + n_bounds

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        def fold(dev: dict, luts: tuple, row_mask):
            local_rows = row_mask.shape[0]
            # per-block time scalars ride the tail of the luts tuple
            # (_time_args layout); trace consumes the head
            extra = list(luts[len(luts) - n_time_args :]) if n_time_args else []
            mask = compiler.trace(
                sel_where, enc, dev, list(luts[: len(luts) - n_time_args])
            )
            mask = jnp.logical_and(mask, row_mask)
            if n_bounds and DEFAULT_TIMESTAMP_KEY in enc.columns:
                ts = dev[DEFAULT_TIMESTAMP_KEY]
                bi = 2 * n_timebin
                if bounds_ms[0] is not None:
                    mask = jnp.logical_and(mask, ts >= extra[bi][0])
                    bi += 1
                if bounds_ms[1] is not None:
                    mask = jnp.logical_and(mask, ts < extra[bi][0])
                mask = jnp.logical_and(mask, dev[f"{DEFAULT_TIMESTAMP_KEY}__valid"])
            if key_sig and key_sig[0][0] == "pair":
                # host-compacted composite codes (multi-key high cardinality)
                ids = jnp.minimum(dev["__pairkey"], num_groups - 1)
            else:
                ids = None
                stride = 1
                ti = 0
                for (kind, column, bin_ms), cap in zip(key_sig, caps):
                    if kind == "dict":
                        codes = jnp.minimum(dev[column], cap - 1)
                    else:
                        shift, k_off = extra[ti][0], extra[ti + 1][0]
                        ti += 2
                        codes = jnp.clip(
                            (dev[column] + shift) // jnp.int32(bin_ms) + k_off,
                            0,
                            cap - 1,
                        )
                    part = codes * jnp.int32(stride)
                    ids = part if ids is None else ids + part
                    stride *= cap
                ids = (ids if ids is not None else jnp.zeros(local_rows, jnp.int32)).astype(jnp.int32)
            ids = ids.astype(jnp.int32)

            sum_v, min_v, max_v, valid_v, n_sumk, n_mink, n_maxk = _kernel_stacks(
                dev, layout, local_rows
            )
            count, pac, sums, mins, maxs = kernels.fused_groupby_block(
                ids,
                mask,
                sum_v,
                min_v,
                max_v,
                valid_v,
                num_groups,
                n_sumk,
                n_mink,
                n_maxk,
            )
            m2_loc, m2_n, m2_s = _block_m2(
                dev, layout, ids, mask, pac, sums, num_groups
            )
            if mesh is not None:
                m2_loc, _, _ = _psum_m2(m2_loc, m2_n, m2_s, layout.sq_cols)
                count = jax.lax.psum(count, "data")
                pac = jax.lax.psum(pac, "data")
                sums = jax.lax.psum(sums, "data")
                mins = jax.lax.pmin(mins, "data")
                maxs = jax.lax.pmax(maxs, "data")
            m2 = (
                jnp.stack(m2_loc)
                if layout.sq_cols
                else jnp.zeros((0, num_groups), jnp.float32)
            )
            # ONE stacked output -> ONE device->host readback per block
            # (each d2h call pays 100-500ms latency on a tunneled chip)
            return jnp.concatenate(
                [count[None, :], pac, sums, m2, mins, maxs], axis=0
            )

        if mesh is not None:
            try:
                from jax import shard_map
            except ImportError:  # jax < 0.5 keeps it in experimental
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            dev_spec = {k: P("data") for k in dev_keys}
            in_specs = (dev_spec, tuple(P() for _ in lut_shapes), P("data"))
            body = shard_map(fold, mesh=mesh, in_specs=in_specs, out_specs=P())
        else:
            body = fold

        # no donate_argnums here either — same tunneled-PJRT round-trip
        # pessimization as the executor.dense note in _get_program
        prog = jax.jit(body)  # jit-cache: executor.local
        if mesh is not None:
            global MESH_PROGRAMS_BUILT
            MESH_PROGRAMS_BUILT += 1
        _note_program_build("executor.local", key, self.route_stats)
        _PROGRAM_CACHE[key] = prog
        return prog

    @staticmethod
    def _decode_key_col(info: tuple, code: np.ndarray) -> pa.Array:
        """One key's codes -> typed arrow values (dictionary-typed for dict
        keys — readback partials carry codes, values decode only when the
        final rows do; time bins decode arithmetically)."""
        if info[0] == "dict":
            values = info[1]  # last entry is the null slot (None)
            if not values:
                return pa.nulls(len(code))
            arr = pa.array(values)
            take = np.minimum(code, len(values) - 1).astype(np.int32)
            return pa.DictionaryArray.from_arrays(pa.array(take), arr)
        origin_bin, bin_ms = info[1], info[2]
        abs_ms = (origin_bin + code) * bin_ms
        return pa.array(abs_ms.astype("datetime64[ms]"), pa.timestamp("ms"))

    def _partial_from_arrays(
        self,
        arr: np.ndarray,
        lay: AccLayout,
        keyinfo: list[tuple],
        specs: list[AggSpec],
        composite_vals: np.ndarray | None = None,
    ) -> pa.Table | None:
        """Nonzero groups of one dense partial -> partial-format table
        (__g{i} keys, __cnt, per-spec __pac/__sum/__min/__max), fully
        vectorized: divmod key decode + dictionary takes.

        Default layout: group id = sum(code_i * stride_i), first key minor.
        With `composite_vals` (pair-compacted mode): group g's keys decode
        from composite_vals[g] = ((c0*cap1 + c1)*cap2 + c2)..., first key
        MAJOR — the np.unique compaction order."""
        count = arr[0]
        idxs = np.nonzero(count > 0)[0]
        if len(idxs) == 0:
            return None
        cols: dict[str, pa.Array] = {}
        if composite_vals is None:
            rem = idxs.copy()
            for i, info in enumerate(keyinfo):
                cap = info[-1]
                code = rem % cap
                rem = rem // cap
                cols[f"__g{i}"] = self._decode_key_col(info, code)
        else:
            rem = composite_vals[idxs].copy()
            decoded: list[np.ndarray] = []
            for info in reversed(keyinfo[1:]):
                cap = info[-1]
                decoded.append(rem % cap)
                rem = rem // cap
            decoded.append(rem)
            for i, (info, code) in enumerate(zip(keyinfo, reversed(decoded))):
                cols[f"__g{i}"] = self._decode_key_col(info, code)
        cols["__cnt"] = pa.array(count[idxs])
        for si, spec in enumerate(specs):
            if spec.func == "count_star":
                continue
            pacv = arr[lay.pac_row(si)][idxs]
            cols[f"__pac{si}"] = pa.array(pacv)
            seen = pacv > 0
            if spec.func in ("sum", "avg"):
                cols[f"__sum{si}"] = pa.array(arr[lay.sum_row(si)][idxs], mask=~seen)
            elif spec.func in ("stddev", "var"):
                s = arr[lay.sqx_row(si)][idxs]
                n = np.maximum(pacv, 1.0)
                cols[f"__sum{si}"] = pa.array(s, mask=~seen)
                # raw sumsq reconstructed in f64 (see _flush_state note)
                cols[f"__sumsq{si}"] = pa.array(
                    arr[lay.sqm2_row(si)][idxs] + s * s / n, mask=~seen
                )
            elif spec.func == "min":
                cols[f"__min{si}"] = pa.array(arr[lay.min_row(si)][idxs], mask=~seen)
            elif spec.func == "max":
                cols[f"__max{si}"] = pa.array(arr[lay.max_row(si)][idxs], mask=~seen)
        return pa.table(cols)

    def _dense_to_partial(
        self,
        acc,
        num_groups: int,
        key_specs: list[KeySpec],
        specs: list[AggSpec],
        lay: AccLayout,
    ) -> pa.Table | None:
        """Dense global accumulator -> partial table (used when switching to
        block-local mode mid-query: the dense epoch's results merge through
        the same vectorized group_by as the block partials)."""
        arr = _timed_readback(acc, self.route_stats)
        keyinfo: list[tuple] = []
        for ks in key_specs:
            if ks.kind == "dict":
                keyinfo.append(("dict", list(ks.gdict.values) + [None], ks.capacity))
            else:
                keyinfo.append(("timebin", ks.origin_rel or 0, ks.bin_ms, ks.capacity))
        return self._partial_from_arrays(arr, lay, keyinfo, specs)

    def _read_hist(self, h, num_groups: int) -> np.ndarray:
        """Percentile-histogram readback: flat [G * DEVICE_NB] device f32
        -> (G, DEVICE_NB) host array.

        d2h is the slow direction on a tunneled chip (~9 MB/s measured vs
        750 MB/s in), so large single-device histograms first read back an
        NB-sized column-occupancy vector and gather only the ACTIVE bins —
        log data clusters in a few dozen octaves, so this typically cuts
        the readback 10-50x. Mesh runs read back directly (the buffer is
        local to the host that owns it)."""
        import jax.numpy as jnp

        total = num_groups * DEVICE_NB
        if self.mesh is not None or total <= (1 << 20):
            return np.asarray(
                _timed_readback(h, self.route_stats)
            ).reshape(num_groups, DEVICE_NB)
        mat = h.reshape(num_groups, DEVICE_NB)
        # NB-sized (~8 KB) occupancy probe gating a readback 10-50x larger
        # sync-boundary: when sparse — the probe pays for itself
        colsum = np.asarray(jnp.sum(mat, axis=0))
        active = np.nonzero(colsum > 0)[0]
        if len(active) * 2 >= DEVICE_NB:
            return np.asarray(
                _timed_readback(h, self.route_stats)
            ).reshape(num_groups, DEVICE_NB)
        out = np.zeros((num_groups, DEVICE_NB))
        if len(active):
            gathered = _timed_readback(mat[:, jnp.asarray(active)], self.route_stats)
            out[:, active] = gathered.reshape(num_groups, len(active))
        return out

    @staticmethod
    def _agg_groups_to_partial(
        agg: HashAggregator,
        specs: list[AggSpec],
        nkeys: int,
    ) -> pa.Table | None:
        """CPU-fallback partials (HashAggregator groups) -> partial table so
        mixed device/CPU runs merge exactly. Sized by the fallback blocks'
        group count only."""
        if not agg.groups:
            return None
        cs_idx = next((i for i, s in enumerate(specs) if s.func == "count_star"), None)
        cols: dict[str, list] = {f"__g{i}": [] for i in range(nkeys)}
        cols["__cnt"] = []
        for si, spec in enumerate(specs):
            if spec.func == "count_star":
                continue
            cols[f"__pac{si}"] = []
            if spec.func in ("sum", "avg"):
                cols[f"__sum{si}"] = []
            elif spec.func in ("stddev", "var"):
                cols[f"__sum{si}"] = []
                cols[f"__sumsq{si}"] = []
            elif spec.func == "min":
                cols[f"__min{si}"] = []
            elif spec.func == "max":
                cols[f"__max{si}"] = []
        for key, st in agg.groups.items():
            for i in range(nkeys):
                cols[f"__g{i}"].append(key[i])
            cols["__cnt"].append(
                float(st.count[cs_idx]) if cs_idx is not None else 1.0
            )
            for si, spec in enumerate(specs):
                if spec.func == "count_star":
                    continue
                cols[f"__pac{si}"].append(float(st.count[si]))
                if spec.func in ("sum", "avg"):
                    cols[f"__sum{si}"].append(st.sums[si] if st.count[si] else None)
                elif spec.func in ("stddev", "var"):
                    cols[f"__sum{si}"].append(st.sums[si] if st.count[si] else None)
                    cols[f"__sumsq{si}"].append(st.sumsqs[si] if st.count[si] else None)
                elif spec.func == "min":
                    cols[f"__min{si}"].append(st.mins[si])
                elif spec.func == "max":
                    cols[f"__max{si}"].append(st.maxs[si])
        return pa.table(cols)

    def _merge_partials(
        self, partials: list[pa.Table], specs: list[AggSpec], nkeys: int
    ) -> pa.Table:
        """Host merge phase of the two-phase aggregation (shared with the
        CPU engine: query/partials.py merge_partials)."""
        from parseable_tpu.query import partials as PT

        return PT.merge_partials(partials, specs, nkeys)

    # ------------------------------------------------------------- programs

    def _get_program(
        self,
        enc: EncodedBatch,
        layout: PlanLayout,
        num_groups: int,
        lut_shapes: tuple,
        remap_shapes: tuple,
        n_blocks: int = 1,
        dev_keys: tuple = (),
        dremap_shapes: tuple = (),
    ) -> Callable:
        """One jitted dispatch: WHERE mask + dict remap + group ids + fused
        aggregate + fold into the device accumulator.

        With a mesh active, the whole fold runs under `shard_map`: each
        device computes the fused partial aggregate for its row shard and
        the partials combine with psum/pmin/pmax over the `data` axis — the
        reduction the reference does in querier-side merge loops
        (cluster/mod.rs:1785-1964) happens on ICI inside one XLA program.

        Cached process-wide; the key covers everything baked into the trace.
        """
        mesh = self.mesh
        n_data_shards = mesh.shape.get("data", mesh.size) if mesh is not None else 1
        if mesh is not None and enc.block_rows % n_data_shards:
            mesh = None
            n_data_shards = 1
        # 2D layout: the accumulator itself shards over the `groups` axis
        # when the group space divides; otherwise that axis idles (inputs
        # replicated over it, fold identical per shard)
        n_group_shards = _mesh_group_shards(mesh)
        shard_groups = (
            n_group_shards
            if n_group_shards > 1 and num_groups % n_group_shards == 0 and num_groups >= n_group_shards
            else 1
        )
        # distinct presence bitmaps shard over `groups` too: the flat
        # groups-major layout (group * Vcap + code) makes each shard's
        # window contiguous, so P("groups") on the flat dim is exact
        kinds = tuple(sorted((n, c.kind) for n, c in enc.columns.items()))
        bounds_ms = self._bounds_ms()
        key = (
            _expr_fingerprint(self.plan.select.where),
            (bounds_ms[0] is not None, bounds_ms[1] is not None),
            tuple(S.expr_name(ks.expr) for ks in layout.key_specs),
            tuple(layout.stacked_cols),
            tuple(layout.sum_cols),
            tuple(layout.min_cols),
            tuple(layout.max_cols),
            enc.block_rows,
            kinds,
            layout.caps,
            # origins deliberately NOT in the key: bin offsets ship as
            # runtime scalars, so origin epoch changes reuse the program
            lut_shapes,
            remap_shapes,
            num_groups,
            n_blocks,
            None if mesh is None else id(mesh),
            dev_keys,
            tuple(layout.distinct_cols),
            layout.distinct_caps,
            layout.distinct_sketch,
            dremap_shapes,
            shard_groups,
            tuple(layout.sq_cols),
            tuple(layout.pct_cols),
            tuple(layout.cnt_cols),
        )
        prog = _PROGRAM_CACHE.get(key)
        if prog is not None:
            self.route_stats["programs_reused"] += 1
            return prog

        import jax
        import jax.numpy as jnp

        sel_where = self.plan.select.where
        compiler = PredicateCompiler()
        kernel_groups = num_groups // shard_groups  # per-device group window
        key_specs = [
            KeySpec(ks.kind, ks.column, ks.expr, ks.bin_ms, ks.gdict, cap, orig)
            for ks, cap, orig in zip(layout.key_specs, layout.caps, layout.origins)
        ]
        n_timebin = sum(1 for ks in key_specs if ks.kind == "timebin")
        n_bounds = sum(1 for b in bounds_ms if b is not None)
        n_time_args = 2 * n_timebin + n_bounds

        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        def fold_one(acc, dacc: tuple, pacc: tuple, dev: dict, luts: tuple, remaps: tuple, dremaps: tuple, row_mask):
            # row count as seen by this trace: the full block single-chip,
            # or this device's shard under shard_map
            local_rows = row_mask.shape[0]
            # per-block time scalars ride the tail of the luts tuple
            # (_time_args layout); trace consumes the head
            extra = list(luts[len(luts) - n_time_args :]) if n_time_args else []
            mask = compiler.trace(
                sel_where, enc, dev, list(luts[: len(luts) - n_time_args])
            )
            mask = jnp.logical_and(mask, row_mask)
            if n_bounds and DEFAULT_TIMESTAMP_KEY in enc.columns:
                ts = dev[DEFAULT_TIMESTAMP_KEY]
                bi = 2 * n_timebin
                if bounds_ms[0] is not None:
                    mask = jnp.logical_and(mask, ts >= extra[bi][0])
                    bi += 1
                if bounds_ms[1] is not None:
                    mask = jnp.logical_and(mask, ts < extra[bi][0])
                mask = jnp.logical_and(mask, dev[f"{DEFAULT_TIMESTAMP_KEY}__valid"])
            if not key_specs:
                ids = jnp.zeros(local_rows, dtype=jnp.int32)
            else:
                ids = None
                stride = 1
                ri = 0
                ti = 0
                for ks in key_specs:
                    cap = ks.capacity
                    if ks.kind == "dict":
                        codes = jnp.minimum(remaps[ri][_as_index(dev[ks.column])], cap - 1)
                        ri += 1
                    else:
                        shift, k_off = extra[ti][0], extra[ti + 1][0]
                        ti += 2
                        codes = jnp.clip(
                            (dev[ks.column] + shift) // jnp.int32(ks.bin_ms) + k_off,
                            0,
                            cap - 1,
                        )
                    part = codes * jnp.int32(stride)
                    ids = part if ids is None else ids + part
                    stride *= cap
                ids = ids.astype(jnp.int32)

            # group-sharded (2D) layout: this device owns one contiguous
            # window of the group space; rows outside it mask off instead
            # of routing (parallel/mesh.py distributed_groupby_2d design)
            if shard_groups > 1:
                gshard = jax.lax.axis_index("groups")
                local = ids - gshard * jnp.int32(kernel_groups)
                in_window = jnp.logical_and(local >= 0, local < kernel_groups)
                mask = jnp.logical_and(mask, in_window)
                ids = jnp.clip(local, 0, kernel_groups - 1)

            sum_v, min_v, max_v, valid_v, n_sumk, n_mink, n_maxk = _kernel_stacks(
                dev, layout, local_rows
            )
            count, pac, sums, mins, maxs = kernels.fused_groupby_block(
                ids,
                mask,
                sum_v,
                min_v,
                max_v,
                valid_v,
                kernel_groups,
                n_sumk,
                n_mink,
                n_maxk,
            )
            # stddev/var: centered per-group second moments for this block
            # (local to the device's row shard under a mesh)
            m2_loc, m2_n, m2_s = _block_m2(
                dev, layout, ids, mask, pac, sums, kernel_groups
            )
            adds = jnp.concatenate([count[None, :], pac, sums], axis=0)
            # distinct presence: OR (max) each (group, value-code) bit;
            # approx_distinct maxes HLL RANKS into the register slot the
            # value's hash selects (same flat shape, same pmax merge)
            dacc_new = []
            sketch_flags = layout.distinct_sketch or (False,) * len(layout.distinct_cols)
            for di, (dcol, dcap) in enumerate(zip(layout.distinct_cols, layout.distinct_caps)):
                dm = jnp.logical_and(mask, dev[f"{dcol}__valid"])
                if sketch_flags[di]:
                    lut = dremaps[di]
                    raw = _as_index(dev[dcol])
                    codes = jnp.minimum(lut[0][raw], dcap - 1)
                    val = jnp.where(dm, lut[1][raw].astype(jnp.float32), 0.0)
                else:
                    codes = jnp.minimum(dremaps[di][_as_index(dev[dcol])], dcap - 1)
                    val = dm.astype(jnp.float32)
                flat = ids * jnp.int32(dcap) + codes
                upd = jax.ops.segment_max(
                    val, flat, num_segments=kernel_groups * dcap
                )
                if mesh is not None:
                    upd = jax.lax.pmax(upd, "data")
                dacc_new.append(jnp.maximum(dacc[di], upd))
            # percentile histograms: per-row log2 bin -> one additive
            # segment_sum into the flat [G * DEVICE_NB] sketch layout
            # (query/sketch.py); partials psum over the data axis and ADD
            # into the running histogram — same mergeability as the sums
            pacc_new = []
            for pi, pcol in enumerate(layout.pct_cols):
                v = dev[pcol].astype(jnp.float32)
                pm = jnp.logical_and(
                    jnp.logical_and(mask, dev[f"{pcol}__valid"]), ~jnp.isnan(v)
                )
                mag = jnp.clip(
                    jnp.log2(jnp.abs(v)),
                    jnp.float32(LOG_LO),
                    jnp.float32(LOG_HI - 1e-6),
                )
                bin_ = jnp.clip(
                    ((mag - jnp.float32(LOG_LO)) * jnp.float32(PCT_SCALE)).astype(jnp.int32),
                    0,
                    PCT_BINS - 1,
                )
                slot = jnp.where(
                    v == 0.0,
                    jnp.int32(2 * PCT_BINS),
                    jnp.where(v > 0, jnp.int32(PCT_BINS) + bin_, bin_),
                )
                flat = ids * jnp.int32(DEVICE_NB) + slot
                upd = jax.ops.segment_sum(
                    pm.astype(jnp.float32), flat, num_segments=kernel_groups * DEVICE_NB
                )
                if mesh is not None:
                    upd = jax.lax.psum(upd, "data")
                pacc_new.append(pacc[pi] + upd)
            if mesh is not None:
                # the distributed reduce tree: partials ride ICI (centered
                # moments via Chan's two-psum recenter, _psum_m2)
                m2_loc, m2_n, m2_s = _psum_m2(m2_loc, m2_n, m2_s, layout.sq_cols)
                adds = jax.lax.psum(adds, "data")
                mins = jax.lax.pmin(mins, "data")
                maxs = jax.lax.pmax(maxs, "data")
            a0 = adds.shape[0]  # 1 + n_allk + n_sum + n_sq (additive rows)
            n_sq = len(layout.sq_cols)
            n_sum_only = len(layout.sum_cols)
            parts = [acc[:a0] + adds]
            if n_sq:
                n_allk_ = valid_v.shape[0]
                m2_new = [
                    _chan_merge_m2(
                        acc[1 + n_sum_only + qi],  # pac (pre-block)
                        acc[1 + n_allk_ + n_sum_only + qi],  # sum (pre-block)
                        acc[a0 + qi],  # M2 (pre-block)
                        m2_n[qi], m2_s[qi], m2_loc[qi],
                    )
                    for qi in range(n_sq)
                ]
                parts.append(jnp.stack(m2_new))
            parts.append(jnp.minimum(acc[a0 + n_sq : a0 + n_sq + n_mink], mins))
            parts.append(jnp.maximum(acc[a0 + n_sq + n_mink :], maxs))
            new_acc = jnp.concatenate(parts, axis=0)
            return new_acc, tuple(dacc_new), tuple(pacc_new)

        def prog_fn(
            acc,
            dacc: tuple,
            pacc: tuple,
            devs: tuple,
            luts_all: tuple,
            remaps_all: tuple,
            dremaps_all: tuple,
            row_masks: tuple,
        ):
            # unrolled folds: N blocks per dispatch amortize round-trip
            # latency; XLA sees one big program and schedules it as a unit
            for i in range(n_blocks):
                acc, dacc, pacc = fold_one(
                    acc, dacc, pacc, devs[i], luts_all[i], remaps_all[i], dremaps_all[i], row_masks[i]
                )
            return acc, dacc, pacc

        if mesh is not None:
            try:
                from jax import shard_map
            except ImportError:  # jax < 0.5 keeps it in experimental
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            n_remaps = sum(1 for s in remap_shapes if s is not None)
            n_dremaps = len(dremap_shapes)
            dev_spec = {k: P("data") for k in dev_keys}
            # accumulator: replicated on 1D meshes; its G axis shards over
            # `groups` on the 2D layout (each device owns G/shard buckets)
            acc_spec = P(None, "groups") if shard_groups > 1 else P()
            dacc_spec = P("groups") if shard_groups > 1 else P()
            in_specs = (
                acc_spec,
                tuple(dacc_spec for _ in layout.distinct_caps),  # presence bitmaps
                tuple(dacc_spec for _ in layout.pct_cols),  # pct histograms
                tuple(dev_spec for _ in range(n_blocks)),
                tuple(tuple(P() for _ in lut_shapes) for _ in range(n_blocks)),
                tuple(tuple(P() for _ in range(n_remaps)) for _ in range(n_blocks)),
                tuple(tuple(P() for _ in range(n_dremaps)) for _ in range(n_blocks)),
                tuple(P("data") for _ in range(n_blocks)),
            )
            out_specs = (
                acc_spec,
                tuple(dacc_spec for _ in layout.distinct_caps),
                tuple(dacc_spec for _ in layout.pct_cols),
            )
            prog_body = shard_map(prog_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        else:
            prog_body = prog_fn

        # NOTE: no donate_argnums — buffer donation forces a synchronous
        # round trip on tunneled PJRT backends (measured 424ms vs 10ms per
        # call); the G-sized accumulator copy is far cheaper
        prog = jax.jit(prog_body)  # jit-cache: executor.dense
        if mesh is not None:
            global MESH_PROGRAMS_BUILT, GROUP_SHARDED_PROGRAMS_BUILT
            MESH_PROGRAMS_BUILT += 1
            if shard_groups > 1:
                GROUP_SHARDED_PROGRAMS_BUILT += 1
        _note_program_build("executor.dense", key, self.route_stats)
        _PROGRAM_CACHE[key] = prog
        return prog

    # ------------------------------------------------------------- internals

    def _bounds_ms(self) -> tuple[int | None, int | None]:
        """API time bounds as absolute epoch ms, FLOORED for sub-ms bounds
        — the CPU engine's _bounds_filter coerces through
        pa.scalar(..., type=timestamp('ms')) the same way, and engine
        parity is the contract."""
        tb = self.plan.time_bounds
        out = []
        for b in (tb.low, tb.high):
            if b is None:
                out.append(None)
                continue
            bb = b if b.tzinfo else b.replace(tzinfo=UTC)
            out.append(_dt_to_us(bb) // 1000)
        return tuple(out)

    @staticmethod
    def _time_args(
        enc: EncodedBatch,
        key_specs: list[KeySpec],
        origins: tuple | list,
        bounds_ms: tuple[int | None, int | None],
    ) -> list[np.ndarray]:
        """Per-block time scalars appended after the predicate LUTs, in a
        fixed layout both the host builder and the traced fold share:
        [per-timebin-key (shift, K)...,  bounds lo?,  bounds hi?].

        shift = origin % bin (so (rel + shift) // bin is the global bin
        index minus origin//bin) and K = origin//bin - scan_lo_bin (the
        block's bin offset inside the scan's group window, bounded by the
        group capacity). Bounds clamp like predicate literals."""
        out: list[np.ndarray] = []
        for ks, origin_bin in zip(key_specs, origins):
            if ks.kind != "timebin":
                continue
            shift = enc.time_origin_ms % ks.bin_ms
            k_off = enc.time_origin_ms // ks.bin_ms - int(origin_bin)
            if not (-(2**31) < k_off < 2**31):
                raise UnsupportedOnDevice("block outside the scan's bin window")
            out.append(np.asarray([shift], dtype=np.int32))
            out.append(np.asarray([k_off], dtype=np.int32))
        for b in bounds_ms:
            if b is not None:
                rel = b - enc.time_origin_ms
                rel = max(-(2**31) + 2, min(2**31 - 2, rel))
                out.append(np.asarray([rel], dtype=np.int32))
        return out

    def _required_layout(self, ks: KeySpec, enc: EncodedBatch) -> tuple[int, int]:
        """(origin, capacity) this key needs for the incoming batch. A change
        in either forces a dense-state flush before processing the batch."""
        if ks.kind == "dict":
            card = max(1, len(ks.gdict) + 1)  # +1 null slot
            cap = max(ks.capacity, 2)
            while cap < card:
                cap *= 2
            return 0, cap
        col = enc.columns.get(ks.column)
        if col is None:
            raise UnsupportedOnDevice(f"time column {ks.column} missing")
        if col.vmin is None or col.vmax is None:
            return ks.origin_rel or 0, max(ks.capacity, 2)
        lo_bin = (enc.time_origin_ms + col.vmin) // ks.bin_ms
        hi_bin = (enc.time_origin_ms + col.vmax) // ks.bin_ms
        if ks.origin_rel is None and self.plan.scan_time_hint is not None:
            # pre-size from the scan's manifest time range: one capacity
            # epoch, one flush, one readback for the whole query
            h_lo, h_hi = self.plan.scan_time_hint
            hint_lo_bin = int(h_lo.timestamp() * 1000) // ks.bin_ms
            hint_hi_bin = int(h_hi.timestamp() * 1000) // ks.bin_ms
            if 0 < hint_hi_bin - hint_lo_bin <= (1 << 22):
                lo_bin = min(lo_bin, hint_lo_bin)
                hi_bin = max(hi_bin, hint_hi_bin)
        origin_bin = lo_bin if ks.origin_rel is None else min(ks.origin_rel, lo_bin)
        span = hi_bin - origin_bin + 1
        cap = max(ks.capacity, 2)
        while cap < span:
            cap *= 2
        if cap > (1 << 22):
            raise UnsupportedOnDevice(
                f"time-bin span {span} exceeds device group capacity; widen the bin"
            )
        return origin_bin, cap

    def _flush_state(
        self,
        arr: np.ndarray,
        key_specs: list[KeySpec],
        agg: HashAggregator,
        specs: list[AggSpec],
        lay: AccLayout,
        dists: list[tuple] | None = None,  # (spec_idx, KeySpec, [G, Vcap] presence)
        pcts: list[tuple[int, np.ndarray]] | None = None,  # (spec_idx, [G, NB])
    ) -> None:
        """Dense accumulators -> sparse host aggregator, decoding group ids.

        `arr` is the packed accumulator readback (AccLayout rows, f64).
        Percentile histograms become QuantileSketch objects so device
        blocks and CPU-fallback blocks merge exactly; stddev/var rows fold
        into GroupState sum/sumsq."""
        from parseable_tpu.query.sketch import QuantileSketch

        idxs = np.nonzero(arr[0] > 0)[0]
        for flat in idxs:
            key_parts = []
            rem = int(flat)
            for ks in key_specs:
                code = rem % ks.capacity
                rem //= ks.capacity
                if ks.kind == "dict":
                    gd = ks.gdict
                    key_parts.append(gd.values[code] if code < len(gd) else None)
                else:
                    abs_ms = ((ks.origin_rel or 0) + code) * ks.bin_ms
                    key_parts.append(
                        datetime.fromtimestamp(abs_ms / 1000.0, UTC).replace(tzinfo=None)
                    )
            counts = []
            sums_l = []
            sumsqs_l = []
            mins_l = []
            maxs_l = []
            for si, spec in enumerate(specs):
                if spec.func == "count_star":
                    counts.append(int(arr[0][flat]))
                elif spec.func in ("count_distinct", "approx_distinct", "percentile"):
                    # finalized from the merged value sets / registers /
                    # sketches
                    counts.append(0)
                else:
                    counts.append(int(arr[lay.pac_row(si)][flat]))
                if spec.func in ("sum", "avg"):
                    sums_l.append(float(arr[lay.sum_row(si)][flat]))
                    sumsqs_l.append(0.0)
                elif spec.func in ("stddev", "var"):
                    # reconstruct raw sumsq = M2 + sum^2/n in f64 so device
                    # partials merge with CPU GroupState raw moments; the
                    # sum^2/n terms cancel exactly at finalize, preserving
                    # the M2-level accuracy
                    s = float(arr[lay.sqx_row(si)][flat])
                    n = float(arr[lay.pac_row(si)][flat])
                    sums_l.append(s)
                    sumsqs_l.append(
                        float(arr[lay.sqm2_row(si)][flat]) + (s * s / n if n else 0.0)
                    )
                else:
                    sums_l.append(0.0)
                    sumsqs_l.append(0.0)
                if spec.func == "min":
                    # unseen = per-agg count 0 (the sentinel is f32 3.4e38,
                    # not inf, so gate on the count instead of the value)
                    seen = arr[lay.pac_row(si)][flat] > 0
                    mins_l.append(float(arr[lay.min_row(si)][flat]) if seen else None)
                else:
                    mins_l.append(None)
                if spec.func == "max":
                    seen = arr[lay.pac_row(si)][flat] > 0
                    maxs_l.append(float(arr[lay.max_row(si)][flat]) if seen else None)
                else:
                    maxs_l.append(None)
            distincts = None
            hlls = None
            if dists:
                distincts = {}
                for si, dk, presence in dists:
                    if specs[si].func == "approx_distinct":
                        if hlls is None:
                            hlls = {}
                        hlls[si] = presence[flat].astype(np.uint8)
                    else:
                        codes = np.nonzero(presence[flat][: len(dk.gdict)] > 0)[0]
                        distincts[si] = {dk.gdict.values[c] for c in codes}
            sketches = None
            if pcts:
                sketches = {}
                for si, hists in pcts:
                    row = hists[flat]
                    if row.sum() > 0:
                        sketches[si] = QuantileSketch.from_device_hist(
                            row,
                            float(arr[lay.pct_min_row(si)][flat]),
                            float(arr[lay.pct_max_row(si)][flat]),
                        )
                if not sketches:
                    sketches = None
            agg.merge_raw(
                tuple(key_parts), counts, sums_l, mins_l, maxs_l, distincts,
                sumsqs=sumsqs_l, sketches=sketches, hlls=hlls,
            )


# --------------------------------------------------------------- device util


def _bitcast_from_u8(seg, dtype: np.dtype, count: int):
    """Reinterpret a device u8 slice as `dtype` (no host round trip)."""
    import jax.numpy as jnp
    from jax import lax

    dt = np.dtype(dtype)
    if dt == np.uint8:
        return seg
    if dt == np.bool_:
        return seg != 0
    if dt.itemsize == 1:  # int8
        return lax.bitcast_convert_type(seg, jnp.dtype(dt))
    return lax.bitcast_convert_type(
        seg.reshape(count, dt.itemsize), jnp.dtype(dt)
    )


def _transfer(enc: EncodedBatch, mesh=None) -> tuple[dict, int]:
    """Ship encoded columns to device (row-sharded over the mesh `data`
    axis when one is active).

    Null-free columns share ONE device `ones` mask instead of shipping a
    validity array each — transfer bytes are the scan budget.

    Single-device path: ALL of a block's buffers are packed into one
    contiguous u8 payload and shipped with ONE device_put, then carved
    back into typed columns on-device (slice + bitcast, async, no round
    trips). Per-put link latency is 40-90 ms on a tunneled chip, so one
    put per block instead of one per column is the difference between a
    transfer-bound and a latency-bound cold scan.
    """
    import jax.numpy as jnp

    if mesh is not None and enc.block_rows % mesh.shape.get("data", mesh.size):
        mesh = None  # block not shardable; keep it single-device
    dev: dict[str, Any] = {}
    nbytes = 0
    ones = _device_ones(enc.block_rows, mesh)
    if mesh is not None:
        # mesh path keeps per-column puts: each column is row-sharded and
        # device counts are small on a pod slice (per-put latency is an
        # ICI/PCIe hop, not a tunnel round trip)
        import jax

        row_s, _ = _mesh_shardings(mesh)

        def put_row(a):  # link-priced: per-column nbytes summed into the
            return jax.device_put(a, row_s)  # scan tick below the loop

        for name, col in enc.columns.items():
            dev[name] = put_row(col.values)
            nbytes += col.values.nbytes
            if col.all_valid:
                dev[f"{name}__valid"] = ones
            else:
                dev[f"{name}__valid"] = put_row(col.valid)
                nbytes += col.valid.nbytes
        dev["__ones"] = ones
        if enc.num_rows != enc.block_rows:
            dev["__rowmask"] = put_row(enc.row_mask)
            nbytes += enc.row_mask.nbytes
        DEVICE_BYTES_TO_DEVICE.labels("scan").inc(nbytes)
        DEVICE_TRANSFER_BYTES.inc(nbytes)
        return dev, nbytes

    parts: list[tuple[str, np.dtype, int, int]] = []  # key, dtype, count, offset
    bufs: list[np.ndarray] = []
    off = 0

    def pack(key: str, arr: np.ndarray) -> None:
        nonlocal off
        a = np.ascontiguousarray(arr)
        parts.append((key, a.dtype, len(a), off))
        bufs.append(a.view(np.uint8).reshape(-1))
        off += a.nbytes

    for name, col in enc.columns.items():
        pack(name, col.values)
        if not col.all_valid:
            pack(f"{name}__valid", col.valid)
    if enc.num_rows != enc.block_rows:
        # padding mask must live with the block (host copy gets stripped
        # when the block enters the hot set)
        pack("__rowmask", enc.row_mask)
    payload = np.concatenate(bufs) if bufs else np.empty(0, np.uint8)
    _TRANSFER_COUNT[0] += 1
    sample = payload.nbytes >= (1 << 20) and (
        _TRANSFER_COUNT[0] == 1 or _TRANSFER_COUNT[0] % 8 == 0
    )
    t0 = _time.perf_counter() if sample else 0.0
    dev_payload = jnp.asarray(payload)
    if sample:
        # block on 1-in-8 puts to keep the link profile honest without
        # serializing the pipeline (puts are otherwise async)
        try:
            # sync-boundary: sampled link-profile probe
            dev_payload.block_until_ready()
            from parseable_tpu.ops.link import get_link

            get_link().record_h2d(payload.nbytes, _time.perf_counter() - t0)
        except Exception:
            pass
    nbytes = payload.nbytes
    for key, dtype, count, o in parts:
        dev[key] = _bitcast_from_u8(
            dev_payload[o : o + count * np.dtype(dtype).itemsize], dtype, count
        )
    for name, col in enc.columns.items():
        if col.all_valid:
            dev[f"{name}__valid"] = ones
    dev["__ones"] = ones
    DEVICE_BYTES_TO_DEVICE.labels("scan").inc(nbytes)
    DEVICE_TRANSFER_BYTES.inc(nbytes)
    return dev, nbytes


def _strip_host_values(enc: EncodedBatch) -> None:
    """Free the host-side ndarray copies before caching (dictionaries,
    vmin/vmax and flags stay — they're what queries need)."""
    empty = np.empty(0, np.int32)
    for col in enc.columns.values():
        col.values = empty
        col.valid = empty
    enc.row_mask = np.empty(0, bool)


def _concat_tables(tables: list[pa.Table]) -> pa.Table:
    if len(tables) == 1:
        return tables[0]
    return pa.concat_tables(tables, promote_options="permissive")


def _strip_where(sel: S.Select) -> S.Select:
    import copy

    out = copy.copy(sel)
    out.where = None
    return out
