"""TPU query executor: predicate + group-by aggregation on device.

This is the "TPU execution backend" the whole build centers on (SURVEY §7
step 5). Per scanned table:

1. columns encode host-side (ops/device.py): numerics -> f32, strings ->
   dictionary codes remapped into *global* per-column dictionaries,
   timestamps -> relative int32;
2. the WHERE tree compiles to a device boolean mask (string predicates become
   dictionary LUT gathers — the regex runs once per unique value, not per
   row);
3. group keys combine into one dense int32 id (dict codes x time bins) with
   power-of-two capacities so XLA sees a handful of static shapes;
4. ONE jitted program per (layout, block-shape) runs mask + group ids +
   `fused_groupby_block` in a single dispatch per batch. Dispatches and
   device->host copies are fully asynchronous; the host syncs once per
   flush, then accumulates G-sized partials in float64.

The single-dispatch + async design is what makes the path fast in practice:
device round-trips cost O(100ms) on tunneled setups while the fused kernel
itself sustains >1 G rows/s — so the number of synchronizing calls per
query, not FLOPs, is the budget.

Capacity growth (a new dictionary value or time bin overflowing the current
stride space) flushes the dense accumulator into the sparse host aggregator
and re-plans with doubled capacity — amortized O(log G) flushes. Predicate
LUTs are *runtime inputs* padded to pow2 length, so dictionary growth within
a capacity bucket does not retrace.

Anything the device path can't express (nested types, aggregates over
expressions or timestamps, count_distinct, date_bin with custom origin, ...)
falls back to the CPU executor — whole-query when detected at plan time,
per-table otherwise — merging into the same aggregator, so results are
always complete.

Precision: per-block reductions run in f32 (blocks <= 2^22 rows keep counts
exact); cross-block accumulation is f64 on host.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from datetime import UTC, datetime
from typing import Any, Callable, Iterator

import numpy as np
import pyarrow as pa

from parseable_tpu.config import Options
from parseable_tpu.ops import kernels
from parseable_tpu.ops.device import (
    EncodedBatch,
    EncodedColumn,
    encode_table,
    rel_time_value,
)
from parseable_tpu.query import sql as S
from parseable_tpu.query.executor import (
    AggSpec,
    HashAggregator,
    QueryExecutor,
)
from parseable_tpu.query.planner import LogicalPlan
from parseable_tpu.utils.metrics import DEVICE_BYTES_TO_DEVICE, DEVICE_EXECUTE_TIME
from parseable_tpu.utils.timeutil import parse_duration, parse_rfc3339

logger = logging.getLogger(__name__)


class UnsupportedOnDevice(Exception):
    pass


def _pow2(n: int, minimum: int = 8) -> int:
    p = minimum
    while p < n:
        p <<= 1
    return p


# ------------------------------------------------------------- global dicts


class GlobalDict:
    """Union of per-batch dictionaries for one column, with code remapping."""

    def __init__(self) -> None:
        self.values: list[Any] = []
        self.index: dict[Any, int] = {}

    def remap(self, batch_dict: list[Any], codes: np.ndarray) -> np.ndarray:
        """Translate batch-local codes (with trailing null slot) to global
        codes; nulls map to a large sentinel (validity masks cover them, and
        out-of-range gathers clamp to the LUT's null slot)."""
        lookup = np.empty(len(batch_dict), dtype=np.int32)
        identity = True
        for i, v in enumerate(batch_dict):
            if v is None:
                lookup[i] = -1
                identity = False
                continue
            gi = self.index.get(v)
            if gi is None:
                gi = len(self.values)
                self.values.append(v)
                self.index[v] = gi
            lookup[i] = gi
            identity = identity and gi == i
        if identity and len(batch_dict) == len(self.values):
            # batch dict == global dict in order: codes already ARE global
            # ids, and the null slot (== len(values)) stays past every real
            # code, clamping safely in LUT gathers / group-code minimums
            return codes
        out = lookup[np.clip(codes, 0, len(batch_dict) - 1)]
        return np.where(out < 0, np.int32(2**30), out).astype(np.int32)

    def __len__(self) -> int:
        return len(self.values)


# --------------------------------------------------------------- group keys


@dataclass
class KeySpec:
    kind: str  # "dict" | "timebin"
    column: str
    expr: S.Expr
    bin_ms: int = 0  # timebin only
    gdict: GlobalDict | None = None  # dict only
    capacity: int = 1  # current stride capacity (pow2)
    origin_rel: int | None = None  # timebin only: origin *bin index*


def _like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def _interval_ms(e: S.Expr) -> int | None:
    if isinstance(e, S.IntervalLit):
        return int(parse_duration(e.text).total_seconds() * 1000)
    if isinstance(e, S.Literal) and isinstance(e.value, str):
        try:
            return int(parse_duration(e.value).total_seconds() * 1000)
        except ValueError:
            return None
    return None


_TRUNC_MS = {
    "second": 1000,
    "minute": 60_000,
    "hour": 3_600_000,
    "day": 86_400_000,
}


def classify_group_expr(e: S.Expr) -> KeySpec:
    """Map a GROUP BY expression onto a device key kind, or raise."""
    if isinstance(e, S.Column):
        return KeySpec("dict", e.name, e, gdict=GlobalDict())
    if isinstance(e, S.FunctionCall) and e.name == "date_bin" and len(e.args) >= 2:
        if len(e.args) > 2:
            # custom bin origin: device bins are epoch-aligned only
            raise UnsupportedOnDevice("date_bin with explicit origin")
        ms = _interval_ms(e.args[0])
        col = e.args[1]
        if ms and isinstance(col, S.Column):
            return KeySpec("timebin", col.name, e, bin_ms=ms)
    if isinstance(e, S.FunctionCall) and e.name == "date_trunc" and len(e.args) == 2:
        unit = e.args[0].value if isinstance(e.args[0], S.Literal) else None
        col = e.args[1]
        ms = _TRUNC_MS.get(str(unit).lower()) if unit else None
        if ms and isinstance(col, S.Column):
            return KeySpec("timebin", col.name, e, bin_ms=ms)
    if isinstance(e, S.Cast):
        return classify_group_expr(e.expr)
    raise UnsupportedOnDevice(f"group expression not device-mappable: {S.expr_name(e)}")


# ------------------------------------------------------------ mask compiler


class PredicateCompiler:
    """Compile a WHERE tree into device ops, in two phases per batch:

    - `collect_luts(e, enc)` (host): evaluate string/dict predicates over the
      global dictionaries into boolean LUTs, padded to pow2 length. Cached by
      (predicate, dictionary size) so the regex work amortizes across
      batches.
    - `trace(e, enc, dev, luts)` (traced or eager): emit jnp ops, consuming
      the LUT arrays positionally. Runs identically under jax.jit (LUTs as
      runtime args) and eagerly.
    """

    def __init__(self, gdicts: dict[str, GlobalDict]):
        self.gdicts = gdicts
        self._lut_cache: dict[tuple, np.ndarray] = {}

    # ---------------------------------------------------------- phase A

    def collect_luts(self, e: S.Expr | None, enc: EncodedBatch) -> list[np.ndarray]:
        out: list[np.ndarray] = []
        if e is not None:
            self._walk_collect(e, enc, out)
        return out

    def _walk_collect(self, e: S.Expr, enc: EncodedBatch, out: list[np.ndarray]) -> None:
        if isinstance(e, S.BinaryOp):
            if e.op in ("and", "or"):
                self._walk_collect(e.left, enc, out)
                self._walk_collect(e.right, enc, out)
                return
            if e.op in ("=", "!=", "<", "<=", ">", ">="):
                col, op, lit = self._cmp_parts(e, enc)
                if col.kind == "dict":
                    out.append(self._dict_lut(col, op, lit))
                return
            if e.op in ("like", "ilike", "not_like", "not_ilike"):
                col = self._column_of(e.left, enc)
                raw = str(self._literal_of(e.right))
                out.append(
                    self._regex_lut(
                        col,
                        _like_to_regex(raw),
                        re.IGNORECASE if "ilike" in e.op else 0,
                        e.op.startswith("not_"),
                    )
                )
                return
        if isinstance(e, S.UnaryOp) and e.op == "not":
            self._walk_collect(e.operand, enc, out)
            return
        if isinstance(e, S.Between):
            self._walk_collect(S.BinaryOp(">=", e.expr, e.low), enc, out)
            self._walk_collect(S.BinaryOp("<=", e.expr, e.high), enc, out)
            return
        if isinstance(e, S.InList):
            col = self._column_of(e.expr, enc)
            if col.kind == "dict":
                out.append(self._in_lut(e, col))
            return
        if isinstance(e, S.FunctionCall) and e.name in ("regexp_match", "regexp_like"):
            col = self._column_of(e.args[0], enc)
            out.append(self._regex_lut(col, str(self._literal_of(e.args[1])), 0, False))
            return
        if isinstance(e, (S.IsNull, S.Literal)):
            return
        raise UnsupportedOnDevice(f"predicate not device-mappable: {type(e).__name__}")

    # ---------------------------------------------------------- phase B

    def trace(self, e: S.Expr | None, enc: EncodedBatch, dev: dict, luts: list):
        import jax.numpy as jnp

        if e is None:
            return jnp.ones(enc.block_rows, dtype=bool)
        it = iter(luts)
        return self._visit(e, enc, dev, it)

    def _visit(self, e: S.Expr, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        if isinstance(e, S.BinaryOp):
            if e.op == "and":
                return jnp.logical_and(
                    self._visit(e.left, enc, dev, luts), self._visit(e.right, enc, dev, luts)
                )
            if e.op == "or":
                return jnp.logical_or(
                    self._visit(e.left, enc, dev, luts), self._visit(e.right, enc, dev, luts)
                )
            if e.op in ("=", "!=", "<", "<=", ">", ">="):
                return self._cmp(e, enc, dev, luts)
            if e.op in ("like", "ilike", "not_like", "not_ilike"):
                col = self._column_of(e.left, enc)
                if col.kind != "dict":
                    raise UnsupportedOnDevice("string predicate on non-string column")
                lut = next(luts)
                return jnp.logical_and(lut[dev[col.name]], dev[f"{col.name}__valid"])
        if isinstance(e, S.UnaryOp) and e.op == "not":
            return jnp.logical_not(self._visit(e.operand, enc, dev, luts))
        if isinstance(e, S.Between):
            m = jnp.logical_and(
                self._cmp(S.BinaryOp(">=", e.expr, e.low), enc, dev, luts),
                self._cmp(S.BinaryOp("<=", e.expr, e.high), enc, dev, luts),
            )
            return jnp.logical_not(m) if e.negated else m
        if isinstance(e, S.InList):
            return self._in_list(e, enc, dev, luts)
        if isinstance(e, S.IsNull):
            col = self._column_of(e.expr, enc)
            valid = dev[f"{col.name}__valid"]
            return valid if e.negated else jnp.logical_not(valid)
        if isinstance(e, S.FunctionCall) and e.name in ("regexp_match", "regexp_like"):
            col = self._column_of(e.args[0], enc)
            if col.kind != "dict":
                raise UnsupportedOnDevice("regex on non-string column")
            lut = next(luts)
            return jnp.logical_and(lut[dev[col.name]], dev[f"{col.name}__valid"])
        if isinstance(e, S.Literal) and isinstance(e.value, bool):
            return jnp.full(enc.block_rows, e.value)
        raise UnsupportedOnDevice(f"predicate not device-mappable: {type(e).__name__}")

    # ---------------------------------------------------------- shared bits

    def _column_of(self, e: S.Expr, enc: EncodedBatch) -> EncodedColumn:
        if isinstance(e, S.Cast):
            return self._column_of(e.expr, enc)
        if not isinstance(e, S.Column):
            raise UnsupportedOnDevice("expected a column operand")
        col = enc.columns.get(e.name)
        if col is None:
            raise UnsupportedOnDevice(f"column {e.name} not encoded")
        return col

    def _literal_of(self, e: S.Expr) -> Any:
        if isinstance(e, S.Literal):
            return e.value
        if isinstance(e, S.Cast):
            return self._literal_of(e.expr)
        if isinstance(e, S.FunctionCall) and e.name == "to_timestamp" and e.args:
            return self._literal_of(e.args[0])
        raise UnsupportedOnDevice("expected a literal operand")

    def _cmp_parts(self, e: S.BinaryOp, enc: EncodedBatch):
        left_is_col = isinstance(e.left, (S.Column, S.Cast)) and not isinstance(e.left, S.Literal)
        if left_is_col:
            return self._column_of(e.left, enc), e.op, self._literal_of(e.right)
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return self._column_of(e.right, enc), flip.get(e.op, e.op), self._literal_of(e.left)

    def _cmp(self, e: S.BinaryOp, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        col, op, lit = self._cmp_parts(e, enc)
        valid = dev[f"{col.name}__valid"]
        values = dev[col.name]
        if col.kind == "dict":
            lut = next(luts)
            mask = lut[values]
        elif col.kind == "time":
            if isinstance(lit, str):
                lit_dt = parse_rfc3339(lit)
            elif isinstance(lit, datetime):
                lit_dt = lit
            else:
                raise UnsupportedOnDevice("timestamp compared to non-time literal")
            rel = rel_time_value(lit_dt, enc.time_origin_ms, enc.time_unit_ms)
            mask = _num_cmp(values, op, rel)
        elif col.kind in ("num", "bool"):
            if not isinstance(lit, (int, float, bool)):
                raise UnsupportedOnDevice("numeric compared to non-numeric literal")
            mask = _num_cmp(values, op, float(lit))
        else:
            raise UnsupportedOnDevice(f"cannot compare column kind {col.kind}")
        return jnp.logical_and(mask, valid)

    def _in_list(self, e: S.InList, enc: EncodedBatch, dev, luts):
        import jax.numpy as jnp

        col = self._column_of(e.expr, enc)
        valid = dev[f"{col.name}__valid"]
        if col.kind == "dict":
            lut = next(luts)
            return jnp.logical_and(lut[dev[col.name]], valid)
        if col.kind in ("num", "bool"):
            lits = [self._literal_of(i) for i in e.items]
            mask = jnp.zeros(enc.block_rows, dtype=bool)
            for v in lits:
                mask = jnp.logical_or(mask, dev[col.name] == float(v))
            if e.negated:
                mask = jnp.logical_not(mask)
            return jnp.logical_and(mask, valid)
        raise UnsupportedOnDevice("IN on unsupported column kind")

    # ---------------------------------------------------------- LUT builders

    def _gdict_values(self, col: EncodedColumn) -> list:
        gdict = self.gdicts.get(col.column if hasattr(col, "column") else col.name)
        return gdict.values if gdict is not None and len(gdict) else col.dictionary[:-1]

    def _padded(self, lut: np.ndarray) -> np.ndarray:
        n = _pow2(len(lut))
        if n == len(lut):
            return lut
        out = np.zeros(n, dtype=bool)
        out[: len(lut)] = lut
        return out

    def _dict_lut(self, col: EncodedColumn, op: str, lit: Any) -> np.ndarray:
        values = self._gdict_values(col)
        key = (col.name, op, repr(lit), len(values))
        hit = self._lut_cache.get(key)
        if hit is not None:
            return hit
        import operator as _op

        fns = {"=": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}
        f = fns[op]
        lut = np.zeros(len(values) + 1, dtype=bool)  # +1 null slot -> False
        for i, v in enumerate(values):
            if v is None:
                continue
            try:
                lut[i] = bool(f(v, lit))
            except TypeError:
                lut[i] = False
        lut = self._padded(lut)
        self._lut_cache[key] = lut
        return lut

    def _regex_lut(self, col: EncodedColumn, pattern: str, flags: int, negate: bool) -> np.ndarray:
        if col.kind != "dict":
            raise UnsupportedOnDevice("string predicate on non-string column")
        values = self._gdict_values(col)
        key = (col.name, pattern, flags, negate, len(values))
        hit = self._lut_cache.get(key)
        if hit is not None:
            return hit
        rx = re.compile(pattern, flags)
        lut = np.zeros(len(values) + 1, dtype=bool)
        for i, v in enumerate(values):
            if isinstance(v, str):
                m = rx.search(v) is not None
                lut[i] = (not m) if negate else m
        lut = self._padded(lut)
        self._lut_cache[key] = lut
        return lut

    def _in_lut(self, e: S.InList, col: EncodedColumn) -> np.ndarray:
        values = self._gdict_values(col)
        lits = set()
        for i in e.items:
            lits.add(self._literal_of(i))
        key = (col.name, "in", repr(sorted(map(repr, lits))), e.negated, len(values))
        hit = self._lut_cache.get(key)
        if hit is not None:
            return hit
        lut = np.zeros(len(values) + 1, dtype=bool)
        for i, v in enumerate(values):
            inside = v in lits
            lut[i] = (not inside) if e.negated else inside
        lut = self._padded(lut)
        self._lut_cache[key] = lut
        return lut


def _num_cmp(values, op: str, threshold):
    import jax.numpy as jnp

    t = jnp.asarray(threshold, dtype=values.dtype)
    return {
        "=": values == t,
        "!=": values != t,
        "<": values < t,
        "<=": values <= t,
        ">": values > t,
        ">=": values >= t,
    }[op]


# ------------------------------------------------------------ dense agg state


@dataclass
class DenseState:
    """Host-side f64 accumulators over the dense group space."""

    capacities: tuple[int, ...]
    num_groups: int
    count: np.ndarray
    per_agg_count: np.ndarray
    sums: np.ndarray
    mins: np.ndarray
    maxs: np.ndarray

    @classmethod
    def create(cls, capacities: tuple[int, ...], n_all: int, n_sum: int, n_min: int, n_max: int):
        g = 1
        for c in capacities:
            g *= c
        return cls(
            capacities=capacities,
            num_groups=g,
            count=np.zeros(g, np.float64),
            per_agg_count=np.zeros((n_all, g), np.float64),
            sums=np.zeros((n_sum, g), np.float64),
            mins=np.full((n_min, g), np.inf, np.float64),
            maxs=np.full((n_max, g), -np.inf, np.float64),
        )


@dataclass
class PlanLayout:
    """Everything that shapes the device program for one capacity epoch."""

    key_specs: list[KeySpec]
    caps: tuple[int, ...]
    origins: tuple[int, ...]
    sum_cols: list[str]
    min_cols: list[str]
    max_cols: list[str]
    stacked_cols: list[str]
    time_origin_ms: int
    time_unit_ms: int


# Jitted programs cached process-wide: two identical queries (or two
# executors in one query lifetime) reuse the compiled XLA executable.
_PROGRAM_CACHE: dict[tuple, Callable] = {}


def _expr_fingerprint(e: S.Expr | None) -> str:
    return repr(e)  # dataclass repr is structural and stable


class TpuQueryExecutor(QueryExecutor):
    """Device-accelerated aggregation; transparent CPU fallback."""

    def __init__(self, plan: LogicalPlan, options: Options | None = None):
        super().__init__(plan)
        self.options = options or Options()

    # ------------------------------------------------------------------ main

    def execute(self, tables: Iterator[pa.Table]) -> pa.Table:
        if self.plan.is_aggregate:
            try:
                return self._execute_aggregate_tpu(tables)
            except UnsupportedOnDevice as e:
                logger.info("TPU path unsupported (%s); falling back to CPU", e)
                return super()._execute_aggregate(tables)
        return self._execute_select_tpu(tables)

    # ------------------------------------------------- select (mask on device)

    def _execute_select_tpu(self, tables: Iterator[pa.Table]) -> pa.Table:
        """Plain SELECT: compute the WHERE mask on device, filter host-side.

        Wrapped per-table so unsupported predicates degrade to CPU eval."""
        sel = self.plan.select

        def filtered() -> Iterator[pa.Table]:
            from parseable_tpu.query.executor import _arr, evaluate

            gdicts: dict[str, GlobalDict] = {}
            compiler = PredicateCompiler(gdicts)
            for table in tables:
                if sel.where is None:
                    yield table
                    continue
                try:
                    enc = encode_table(
                        table,
                        None,
                        self.plan.time_bounds.low,
                        self.plan.time_bounds.high,
                    )
                    if enc is None:
                        raise UnsupportedOnDevice("unencodable column")
                    dev = _to_device(enc, gdicts)
                    import jax.numpy as jnp

                    luts = [jnp.asarray(l) for l in compiler.collect_luts(sel.where, enc)]
                    mask = compiler.trace(sel.where, enc, dev, luts)
                    mask_np = np.asarray(mask)[: enc.num_rows]
                    yield table.filter(pa.array(mask_np))
                except UnsupportedOnDevice:
                    # evaluate against the captured (un-stripped) WHERE
                    mask = _arr(evaluate(sel.where, table), table)
                    yield table.filter(mask)

        # reuse CPU projection/order/limit over pre-filtered tables
        inner = QueryExecutor(self.plan)
        inner.plan.select = _strip_where(sel)
        try:
            return inner._execute_select(filtered())
        finally:
            inner.plan.select = sel

    # -------------------------------------------------------------- aggregate

    def _execute_aggregate_tpu(self, tables: Iterator[pa.Table]) -> pa.Table:
        import time as _t

        import jax.numpy as jnp

        sel = self.plan.select
        agg, rewritten, group_names = self.build_aggregator()
        specs = agg.specs

        key_specs = [classify_group_expr(g) for g in sel.group_by]
        sum_idx: list[int] = []
        min_idx: list[int] = []
        max_idx: list[int] = []
        countcol_idx: list[int] = []
        for i, spec in enumerate(specs):
            if spec.func == "count_star":
                continue
            if spec.func == "count_distinct":
                raise UnsupportedOnDevice("count_distinct runs on the CPU engine")
            if not isinstance(spec.arg, S.Column):
                raise UnsupportedOnDevice(f"aggregate over expression: {S.expr_name(spec.arg)}")
            if spec.func in ("sum", "avg"):
                sum_idx.append(i)
            elif spec.func == "min":
                min_idx.append(i)
            elif spec.func == "max":
                max_idx.append(i)
            elif spec.func == "count":
                countcol_idx.append(i)
            else:
                raise UnsupportedOnDevice(f"aggregate {spec.func}")
        stacked_idx = sum_idx + min_idx + max_idx + countcol_idx
        n_sum, n_min, n_max = len(sum_idx), len(min_idx), len(max_idx)
        n_all = len(stacked_idx)

        gdicts: dict[str, GlobalDict] = {}
        for ks in key_specs:
            if ks.kind == "dict":
                gdicts[ks.column] = ks.gdict
        compiler = PredicateCompiler(gdicts)
        dict_cols = {ks.column for ks in key_specs if ks.kind == "dict"}

        acc = None  # device-resident packed accumulator (R, G) f32
        acc_groups = 0
        time_origin: int | None = None
        time_unit = 1

        def new_acc(num_groups: int):
            """Packed accumulator rows: count | per-agg counts | sums | mins | maxs."""
            parts = [
                np.zeros((1 + n_all + n_sum, num_groups), np.float32),
                np.full((n_min, num_groups), np.float32(3.4e38)),
                np.full((n_max, num_groups), np.float32(-3.4e38)),
            ]
            return jnp.asarray(np.concatenate(parts, axis=0))

        def flush(acc_dev, num_groups: int) -> None:
            """ONE device->host readback, then fold into the sparse agg."""
            arr = np.asarray(acc_dev, np.float64)
            state = DenseState(
                capacities=tuple(ks.capacity for ks in key_specs),
                num_groups=num_groups,
                count=arr[0],
                per_agg_count=arr[1 : 1 + n_all],
                sums=arr[1 + n_all : 1 + n_all + n_sum],
                mins=arr[1 + n_all + n_sum : 1 + n_all + n_sum + n_min],
                maxs=arr[1 + n_all + n_sum + n_min :],
            )
            self._flush_state(state, key_specs, agg, specs, time_origin or 0, time_unit)

        # Coalesce scan tables into larger device blocks: dispatch latency is
        # the budget, so fewer/bigger blocks win (Options.device_block_rows).
        target_rows = max(1 << 16, self.options.device_block_rows)

        def coalesced(src: Iterator[pa.Table]) -> Iterator[pa.Table]:
            buf: list[pa.Table] = []
            rows = 0
            for t in src:
                buf.append(t)
                rows += t.num_rows
                if rows >= target_rows:
                    yield _concat_tables(buf)
                    buf, rows = [], 0
            if buf:
                yield _concat_tables(buf)

        t_start = _t.monotonic()
        for table in coalesced(tables):
            try:
                enc = encode_table(
                    table,
                    self.plan.needed_columns,
                    self.plan.time_bounds.low,
                    self.plan.time_bounds.high,
                    dict_columns=dict_cols,
                )
                if enc is None:
                    raise UnsupportedOnDevice("unencodable column in batch")
                for i in stacked_idx:
                    kind = enc.columns[specs[i].arg.name].kind if specs[i].arg.name in enc.columns else None
                    if kind is None:
                        raise UnsupportedOnDevice(f"aggregate column {specs[i].arg.name} missing")
                    if kind == "dict" and i not in countcol_idx:
                        raise UnsupportedOnDevice("numeric aggregate over string column")
                    if kind == "time" and i not in countcol_idx:
                        # f32 cannot carry epoch times without rounding
                        raise UnsupportedOnDevice("min/max/sum over timestamp column")
                if time_origin is None:
                    time_origin, time_unit = enc.time_origin_ms, enc.time_unit_ms
                dev = _to_device(enc, gdicts)
                luts = compiler.collect_luts(sel.where, enc)

                layouts = [self._required_layout(ks, enc, gdicts) for ks in key_specs]
                caps = tuple(c for _, c in layouts)
                origins = tuple(o for o, _ in layouts)
                current = tuple((ks.origin_rel or 0, ks.capacity) for ks in key_specs)
                if acc is None or tuple(zip(origins, caps)) != current:
                    if acc is not None:
                        flush(acc, acc_groups)
                    for ks, (o, c) in zip(key_specs, layouts):
                        ks.capacity = c
                        ks.origin_rel = o if ks.kind == "timebin" else None
                    acc_groups = 1
                    for c in caps:
                        acc_groups *= c
                    acc_groups = max(acc_groups, 1)
                    acc = new_acc(acc_groups)

                layout = PlanLayout(
                    key_specs=key_specs,
                    caps=caps,
                    origins=origins,
                    sum_cols=[specs[i].arg.name for i in sum_idx],
                    min_cols=[specs[i].arg.name for i in min_idx],
                    max_cols=[specs[i].arg.name for i in max_idx],
                    stacked_cols=[specs[i].arg.name for i in stacked_idx],
                    time_origin_ms=enc.time_origin_ms,
                    time_unit_ms=enc.time_unit_ms,
                )
                program = self._get_program(enc, layout, acc_groups, tuple(l.shape for l in luts))
                row_mask = (
                    dev["__ones"]
                    if enc.num_rows == enc.block_rows
                    else jnp.asarray(enc.row_mask)
                )
                # single async dispatch folding this block into the accumulator
                acc = program(acc, dev, tuple(jnp.asarray(l) for l in luts), row_mask)
            except UnsupportedOnDevice as e:
                logger.debug("batch on CPU (%s)", e)
                agg.update(table, self._where_mask(table))
            except Exception:
                logger.exception("device aggregation failed for a batch; CPU fallback")
                agg.update(table, self._where_mask(table))

        if acc is not None:
            flush(acc, acc_groups)
        DEVICE_EXECUTE_TIME.labels("groupby").observe(_t.monotonic() - t_start)
        return self.finalize_aggregate(agg, rewritten, group_names)

    # ------------------------------------------------------------- programs

    def _get_program(
        self, enc: EncodedBatch, layout: PlanLayout, num_groups: int, lut_shapes: tuple
    ) -> Callable:
        """One jitted dispatch: WHERE mask + group ids + fused aggregate +
        fold into the donated device accumulator.

        Cached process-wide; the key covers everything baked into the trace:
        the predicate tree, block shape, column kinds, capacities/origins,
        LUT shapes, time encoding.
        """
        kinds = tuple(sorted((n, c.kind) for n, c in enc.columns.items()))
        key = (
            _expr_fingerprint(self.plan.select.where),
            tuple(S.expr_name(ks.expr) for ks in layout.key_specs),
            tuple(layout.stacked_cols),
            tuple(layout.sum_cols),
            tuple(layout.min_cols),
            tuple(layout.max_cols),
            enc.block_rows,
            kinds,
            layout.caps,
            layout.origins,
            lut_shapes,
            layout.time_origin_ms,
            layout.time_unit_ms,
            num_groups,
        )
        prog = _PROGRAM_CACHE.get(key)
        if prog is not None:
            return prog

        import jax
        import jax.numpy as jnp

        sel_where = self.plan.select.where
        compiler_gdicts = {ks.column: ks.gdict for ks in layout.key_specs if ks.kind == "dict"}
        compiler = PredicateCompiler(compiler_gdicts)
        n_sum, n_min, n_max = len(layout.sum_cols), len(layout.min_cols), len(layout.max_cols)
        n_all = len(layout.stacked_cols)
        key_specs = [
            KeySpec(ks.kind, ks.column, ks.expr, ks.bin_ms, ks.gdict, cap, orig)
            for ks, cap, orig in zip(layout.key_specs, layout.caps, layout.origins)
        ]
        time_origin_ms, time_unit_ms = layout.time_origin_ms, layout.time_unit_ms
        block_rows = enc.block_rows

        def prog_fn(acc, dev: dict, luts: tuple, row_mask):
            mask = compiler.trace(sel_where, enc, dev, list(luts))
            mask = jnp.logical_and(mask, row_mask)
            if not key_specs:
                ids = jnp.zeros(block_rows, dtype=jnp.int32)
            else:
                ids = None
                stride = 1
                for ks in key_specs:
                    cap = ks.capacity
                    if ks.kind == "dict":
                        codes = jnp.minimum(dev[ks.column], cap - 1)
                    else:
                        bin_units = max(1, ks.bin_ms // time_unit_ms)
                        origin_bin = ks.origin_rel or 0
                        base_units = origin_bin * bin_units - time_origin_ms // time_unit_ms
                        codes = jnp.clip(
                            (dev[ks.column] - jnp.int32(base_units)) // jnp.int32(bin_units),
                            0,
                            cap - 1,
                        )
                    part = codes * jnp.int32(stride)
                    ids = part if ids is None else ids + part
                    stride *= cap
                ids = ids.astype(jnp.int32)

            def stack(names):
                if not names:
                    return jnp.zeros((0, block_rows), jnp.float32)
                return jnp.stack([dev[n].astype(jnp.float32) for n in names])

            def stack_valid(names):
                if not names:
                    return jnp.zeros((0, block_rows), bool)
                return jnp.stack([dev[f"{n}__valid"] for n in names])

            count, pac, sums, mins, maxs = kernels.fused_groupby_block(
                ids,
                mask,
                stack(layout.sum_cols),
                stack(layout.min_cols),
                stack(layout.max_cols),
                stack_valid(layout.stacked_cols),
                num_groups,
                n_sum,
                n_min,
                n_max,
            )
            adds = jnp.concatenate([count[None, :], pac, sums], axis=0)
            a0 = 1 + n_all + n_sum
            new_acc = jnp.concatenate(
                [
                    acc[:a0] + adds,
                    jnp.minimum(acc[a0 : a0 + n_min], mins),
                    jnp.maximum(acc[a0 + n_min :], maxs),
                ],
                axis=0,
            )
            return new_acc

        # NOTE: no donate_argnums — buffer donation forces a synchronous
        # round trip on tunneled PJRT backends (measured 424ms vs 10ms per
        # call); the G-sized accumulator copy is far cheaper
        prog = jax.jit(prog_fn)
        _PROGRAM_CACHE[key] = prog
        return prog

    # ------------------------------------------------------------- internals

    def _required_layout(self, ks: KeySpec, enc: EncodedBatch, gdicts) -> tuple[int, int]:
        """(origin, capacity) this key needs for the incoming batch. A change
        in either forces a dense-state flush before processing the batch."""
        if ks.kind == "dict":
            card = max(1, len(gdicts[ks.column]) + 1)  # +1 null slot
            cap = max(ks.capacity, 2)
            while cap < card:
                cap *= 2
            return 0, cap
        col = enc.columns.get(ks.column)
        if col is None:
            raise UnsupportedOnDevice(f"time column {ks.column} missing")
        if ks.bin_ms % enc.time_unit_ms or enc.time_origin_ms % enc.time_unit_ms:
            raise UnsupportedOnDevice("bin finer than time encoding unit")
        if col.vmin is None or col.vmax is None:
            return ks.origin_rel or 0, max(ks.capacity, 2)
        lo_bin = (col.vmin * enc.time_unit_ms + enc.time_origin_ms) // ks.bin_ms
        hi_bin = (col.vmax * enc.time_unit_ms + enc.time_origin_ms) // ks.bin_ms
        origin_bin = lo_bin if ks.origin_rel is None else min(ks.origin_rel, lo_bin)
        span = hi_bin - origin_bin + 1
        cap = max(ks.capacity, 2)
        while cap < span:
            cap *= 2
        if cap > (1 << 22):
            raise UnsupportedOnDevice(
                f"time-bin span {span} exceeds device group capacity; widen the bin"
            )
        return origin_bin, cap

    def _flush_state(
        self,
        state: DenseState,
        key_specs: list[KeySpec],
        agg: HashAggregator,
        specs: list[AggSpec],
        time_origin: int,
        time_unit: int,
    ) -> None:
        """Dense accumulators -> sparse host aggregator, decoding group ids."""
        idxs = np.nonzero(state.count > 0)[0]
        n_sum_order = [i for i, s in enumerate(specs) if s.func in ("sum", "avg")]
        n_min_order = [i for i, s in enumerate(specs) if s.func == "min"]
        n_max_order = [i for i, s in enumerate(specs) if s.func == "max"]
        n_countcol_order = [i for i, s in enumerate(specs) if s.func == "count"]
        stacked_order = n_sum_order + n_min_order + n_max_order + n_countcol_order

        for flat in idxs:
            key_parts = []
            rem = int(flat)
            for ks in key_specs:
                code = rem % ks.capacity
                rem //= ks.capacity
                if ks.kind == "dict":
                    gd = ks.gdict
                    key_parts.append(gd.values[code] if code < len(gd) else None)
                else:
                    abs_ms = ((ks.origin_rel or 0) + code) * ks.bin_ms
                    key_parts.append(
                        datetime.fromtimestamp(abs_ms / 1000.0, UTC).replace(tzinfo=None)
                    )
            counts = []
            sums_l = []
            mins_l = []
            maxs_l = []
            for si, spec in enumerate(specs):
                if spec.func == "count_star":
                    counts.append(int(state.count[flat]))
                else:
                    pos = stacked_order.index(si)
                    counts.append(int(state.per_agg_count[pos][flat]))
                if spec.func in ("sum", "avg") and si in n_sum_order:
                    sums_l.append(float(state.sums[n_sum_order.index(si)][flat]))
                else:
                    sums_l.append(0.0)
                if spec.func == "min" and si in n_min_order:
                    v = state.mins[n_min_order.index(si)][flat]
                    mins_l.append(None if v == np.inf else float(v))
                else:
                    mins_l.append(None)
                if spec.func == "max" and si in n_max_order:
                    v = state.maxs[n_max_order.index(si)][flat]
                    maxs_l.append(None if v == -np.inf else float(v))
                else:
                    maxs_l.append(None)
            agg.merge_raw(tuple(key_parts), counts, sums_l, mins_l, maxs_l)
        state.count[:] = 0
        state.per_agg_count[:] = 0
        state.sums[:] = 0
        state.mins[:] = np.inf
        state.maxs[:] = -np.inf


# --------------------------------------------------------------- device util


# device-resident all-true masks per block size; eagerly computing jnp.ones
# per batch costs a full dispatch round trip on tunneled backends
_ONES_CACHE: dict[int, Any] = {}


def _device_ones(block_rows: int):
    import jax.numpy as jnp

    ones = _ONES_CACHE.get(block_rows)
    if ones is None:
        ones = jnp.asarray(np.ones(block_rows, dtype=bool))
        _ONES_CACHE[block_rows] = ones
    return ones


def _to_device(enc: EncodedBatch, gdicts: dict[str, GlobalDict]):
    """Ship encoded columns to device, remapping dict codes to global ids.

    Null-free columns share ONE device `ones` mask instead of shipping a
    validity array each — on tunneled backends transfer bytes are the query
    budget.
    """
    import jax.numpy as jnp

    dev: dict[str, Any] = {}
    nbytes = 0
    ones = _device_ones(enc.block_rows)
    for name, col in enc.columns.items():
        vals = col.values
        if col.kind == "dict":
            # every string column gets a global dictionary so predicate LUTs
            # and group codes stay stable across batches
            gd = gdicts.setdefault(name, GlobalDict())
            vals = gd.remap(col.dictionary, col.values)
        dev[name] = jnp.asarray(vals)
        nbytes += vals.nbytes
        if col.all_valid:
            dev[f"{name}__valid"] = ones
        else:
            dev[f"{name}__valid"] = jnp.asarray(col.valid)
            nbytes += col.valid.nbytes
    dev["__ones"] = ones
    DEVICE_BYTES_TO_DEVICE.labels("scan").inc(nbytes)
    return dev


def _concat_tables(tables: list[pa.Table]) -> pa.Table:
    if len(tables) == 1:
        return tables[0]
    return pa.concat_tables(tables, promote_options="permissive")


def _strip_where(sel: S.Select) -> S.Select:
    import copy

    out = copy.copy(sel)
    out.where = None
    return out
