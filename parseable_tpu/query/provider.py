"""Scan provider: union of staging + hot tier + object-store parquet.

Parity target (reference: src/query/stream_schema_provider.rs:533-666 scan).
The scan resolves, in order:

1. **staging** — recent in-memory/disk arrows on this node, included when the
   query range touches the staging window (last ~LOCAL_SYNC_INTERVAL secs);
2. **hot tier** — parquet files already cached on local NVMe;
3. **object store** — manifest-pruned parquet (time overlap + column min/max
   stats), downloaded through the storage client.

Returns pyarrow Tables column-pruned to what the plan needs. All sources are
adapted to the merged stream schema so mixed-schema files union cleanly.

Object-store files flow through a shared parallel pipeline (the reference
gets the equivalent from DataFusion's ParquetExec): a bounded worker pool
(P_SCAN_WORKERS) fetches+decodes manifest files concurrently — Arrow's
parquet decode releases the GIL and object-store GETs are network-bound —
and yields tables to the consumer as they complete, holding at most
P_SCAN_INFLIGHT_BYTES of decoded data ahead of it. Closing the consumer
(LIMIT satisfied, timeout, error) cancels queued work and drains the pool:
no leaked threads, no storage calls issued after close.

On top of the pool, **projected column-chunk range reads**: for remote files
on a backend with a real ranged GET, the footer is read via a tail
`get_range` and only the byte ranges of the column chunks the plan projects
are fetched (adjacent ranges coalesced), instead of the whole object. The
whole-object GET remains for hot-tier files, `SELECT *`, backends whose
`get_range` is the whole-object default, and projections that cover most of
the file anyway.
"""

from __future__ import annotations

import contextvars
import io
import logging
import queue as _queue
import struct
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from datetime import UTC, datetime, timedelta
from pathlib import Path
from typing import Callable, Iterator

import pyarrow as pa
import pyarrow.parquet as pq

from parseable_tpu import DEFAULT_TIMESTAMP_KEY, LOCAL_SYNC_INTERVAL
from parseable_tpu.catalog import ManifestFile, Snapshot
from parseable_tpu.core import Parseable
from parseable_tpu.query.planner import LogicalPlan, prune_file
from parseable_tpu.utils.metrics import (
    QUERY_SCAN_SCHED_WAIT,
    SCAN_ERRORS,
    SCAN_POOL_QUEUE_DEPTH,
    SCAN_PROJECTION_BYTES_SAVED,
    TOTAL_QUERY_BYTES_SCANNED_DATE,
)

logger = logging.getLogger(__name__)

_PARQUET_MAGIC = b"PAR1"


@dataclass
class ScanStats:
    files_total: int = 0
    files_pruned: int = 0
    bytes_scanned: int = 0
    rows_scanned: int = 0
    staging_batches: int = 0
    # files dropped from the result set by read/decode failures — nonzero
    # means the response is PARTIAL (surfaced in stats + a Prometheus counter)
    scan_errors: int = 0
    # bytes the projected range reads did not download vs whole-object GETs
    bytes_saved_by_projection: int = 0
    range_read_files: int = 0
    # cumulative time this query's scan tasks waited for a shared-pool
    # worker (enqueue -> dispatch): THE cross-query contention signal
    sched_wait_seconds: float = 0.0
    # distributed data plane: raw staging bytes pulled from peers over
    # Arrow IPC (central pull / pushdown fallback) + failed peer fetches
    fanin_bytes: int = 0
    fanin_errors: int = 0
    # transport-ladder breakdown of the fan-in (http_bytes / flight_bytes /
    # flight_peers / flight_fallbacks), merged from cluster.py's stats dict
    fanin_transport: dict = field(default_factory=dict)
    # manifest files skipped because a live peer's pushdown scan owns them
    # (they are NOT pruned — another node is scanning them)
    files_delegated: int = 0


# --------------------------------------------------------------------------
# shared scan scheduler: per-query lanes, weighted round-robin dispatch


class ScanLane:
    """One query's slice of the shared scan pool.

    Holds the query's undispatched tasks, its in-flight byte budget, and
    the completion queue its consumer drains. All dispatch-side state is
    guarded by the owning scheduler's lock (dispatch decisions must see a
    consistent cross-lane picture); the results queue is its own sync."""

    def __init__(self, sched: "ScanScheduler", inflight_bytes: int, weight: int,
                 on_wait: Callable[[float], None] | None = None):
        self._sched = sched
        self.cap = max(1, inflight_bytes)
        self.weight = max(1, weight)
        self.credits = self.weight  # guarded-by: sched._cond
        self.tasks: "deque" = deque()  # guarded-by: sched._cond
        self.used = 0  # guarded-by: sched._cond - decoded bytes in flight
        self.running = 0  # guarded-by: sched._cond - tasks mid-execution
        self.closed = False  # guarded-by: sched._cond
        self.cancelled = threading.Event()
        self.results: _queue.Queue = _queue.Queue()
        self.on_wait = on_wait  # per-query sched-wait accounting (stats)

    def submit(self, fn: Callable[[], None], est: int) -> None:
        self._sched._submit(self, fn, min(max(1, est), self.cap))

    def release_bytes(self, est: int) -> None:
        """Consumer took a decoded table: free its budget, wake dispatch."""
        self._sched._release_bytes(self, min(max(1, est), self.cap))

    def close(self) -> None:
        """Drop undispatched tasks and wait for this lane's running tasks
        to finish — after close() returns, no storage call runs or will
        ever run on this lane's behalf."""
        self.cancelled.set()
        self._sched._close_lane(self)


class ScanScheduler:
    """Shared fetch+decode worker pool with per-query fairness.

    Replaces the per-query ThreadPoolExecutor + global FIFO contention: one
    process-wide set of P_SCAN_WORKERS threads serves every concurrent
    query through per-query *lanes*. Dispatch policy:

    - "fair" (default): weighted round-robin across lanes with queued work.
      Each lane spends `weight` credits per round, so a 10k-file scan and a
      3-file dashboard query alternate dispatches instead of the big scan
      occupying every worker until its backlog drains.
    - "fifo": strict global arrival order — the pre-scheduler behavior,
      kept for A/B measurement (bench.py compares the two).

    A lane's task is only dispatched when its own inflight-byte budget has
    room, so a slow consumer parks its *lane*, never a worker thread.
    Queue-wait (enqueue -> dispatch) lands in the
    query_scan_sched_wait_seconds histogram and per-query ScanStats.
    """

    def __init__(self, workers: int, policy: str = "fair"):
        self.workers = max(1, workers)
        self.policy = policy if policy in ("fair", "fifo") else "fair"
        self._cond = threading.Condition()
        self._lanes: list[ScanLane] = []  # guarded-by: self._cond
        self._rr = 0  # guarded-by: self._cond - round-robin cursor
        self._seq = 0  # guarded-by: self._cond - global arrival order
        self._pending = 0  # guarded-by: self._cond - undispatched tasks
        self._stopped = False  # guarded-by: self._cond
        # NOT "scan-" prefixed: these are shared infrastructure threads that
        # outlive any one scan (per-scan thread-leak checks key on "scan*")
        self._threads = [
            threading.Thread(target=self._worker, name=f"qsched-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # ---------------------------------------------------------------- lanes

    def lane(self, *, inflight_bytes: int, weight: int = 1,
             on_wait: Callable[[float], None] | None = None) -> ScanLane:
        ln = ScanLane(self, inflight_bytes, weight, on_wait)
        with self._cond:
            if self._stopped:
                raise RuntimeError("scan scheduler is stopped")
            self._lanes.append(ln)
        return ln

    def _submit(self, lane: ScanLane, fn: Callable[[], None], est: int) -> None:
        with self._cond:
            if lane.closed or self._stopped:
                # complete immediately: the task fn observes the cancelled
                # flag and posts its skip record, so consumers never hang
                lane.cancelled.set()
                fn()
                return
            lane.tasks.append((fn, est, self._seq, _time.monotonic()))
            self._seq += 1
            self._pending += 1
            SCAN_POOL_QUEUE_DEPTH.set(self._pending)
            self._cond.notify()

    def _release_bytes(self, lane: ScanLane, est: int) -> None:
        with self._cond:
            lane.used = max(0, lane.used - est)
            self._cond.notify_all()

    def _close_lane(self, lane: ScanLane) -> None:
        with self._cond:
            if lane.closed:
                return
            lane.closed = True
            self._pending -= len(lane.tasks)
            lane.tasks.clear()
            SCAN_POOL_QUEUE_DEPTH.set(self._pending)
            # synchronous drain: tasks already mid-fetch finish and their
            # results are dropped; nothing queued ever touches storage
            while lane.running:
                self._cond.wait()
            if lane in self._lanes:
                self._lanes.remove(lane)

    # ------------------------------------------------------------- dispatch

    def _fits(self, lane: ScanLane) -> bool:
        est = lane.tasks[0][1]
        # an item larger than the whole cap admits alone (the cap bounds
        # concurrent holdings, never deadlocks)
        return lane.used == 0 or lane.used + est <= lane.cap

    def _worker(self) -> None:
        while True:
            with self._cond:
                # wait until some lane has a dispatchable head task (queued
                # work whose inflight budget has room)
                while True:
                    if self._stopped:
                        return
                    eligible = [
                        ln for ln in self._lanes if ln.tasks and self._fits(ln)
                    ]
                    if eligible:
                        break
                    self._cond.wait()
                if self.policy == "fifo":
                    lane = min(eligible, key=lambda ln: ln.tasks[0][2])
                else:
                    lane = None
                    n = len(self._lanes)
                    for _pass in range(2):
                        for off in range(n):
                            cand = self._lanes[(self._rr + off) % n]
                            if cand.tasks and cand.credits > 0 and self._fits(cand):
                                lane = cand
                                self._rr = (self._rr + off + 1) % max(1, n)
                                cand.credits -= 1
                                break
                        if lane is not None:
                            break
                        # every eligible lane spent its credits: new round
                        for ln in self._lanes:
                            ln.credits = ln.weight
                    if lane is None:  # pragma: no cover - eligible non-empty
                        lane = eligible[0]
                fn, est, _seq, enq = lane.tasks.popleft()
                lane.used += est
                lane.running += 1
                self._pending -= 1
                SCAN_POOL_QUEUE_DEPTH.set(self._pending)
            wait = max(0.0, _time.monotonic() - enq)
            QUERY_SCAN_SCHED_WAIT.observe(wait)
            if lane.on_wait is not None:
                try:
                    lane.on_wait(wait)
                except Exception:  # pragma: no cover - stats cb must not kill
                    logger.exception("scan sched wait callback failed")
            try:
                fn()
            finally:
                with self._cond:
                    lane.running -= 1
                    self._cond.notify_all()

    def shutdown(self) -> None:
        """Stop the workers and error-complete whatever was still queued so
        no consumer hangs. Deterministic: joins every thread."""
        with self._cond:
            if self._stopped:
                return
            self._stopped = True
            self._cond.notify_all()
            leftovers = [(ln, list(ln.tasks)) for ln in self._lanes]
            for ln in self._lanes:
                ln.cancelled.set()
                ln.tasks.clear()
            self._pending = 0
            SCAN_POOL_QUEUE_DEPTH.set(0)
        for t in self._threads:
            t.join()
        for ln, tasks in leftovers:
            for fn, _est, _seq, _enq in tasks:
                fn()  # cancelled flag set: posts the skip record


_SCHED: ScanScheduler | None = None
_SCHED_LOCK = threading.Lock()


def get_scan_scheduler(options=None) -> ScanScheduler:
    """Process-wide scheduler, sized by P_SCAN_WORKERS / P_SCAN_SCHED.
    Re-roots (shutdown + rebuild) when the configuration changes — tests
    and the A/B bench flip policy between phases with no scans in flight."""
    global _SCHED
    import os as _os

    workers = max(1, getattr(options, "scan_workers", 0) or min(8, _os.cpu_count() or 1))
    policy = getattr(options, "scan_sched", "fair") or "fair"
    with _SCHED_LOCK:
        if _SCHED is not None and (_SCHED.workers != workers or _SCHED.policy != policy):
            old, _SCHED = _SCHED, None
            old.shutdown()
        if _SCHED is None:
            _SCHED = ScanScheduler(workers, policy)
        return _SCHED


def shutdown_scan_scheduler() -> None:
    global _SCHED
    with _SCHED_LOCK:
        if _SCHED is not None:
            _SCHED.shutdown()
            _SCHED = None


def lane_iter(
    lane: ScanLane,
    items: list,
    fetch: Callable,
    size_of: Callable[[object], int],
):
    """Run `fetch(item)` for every item through the lane's scheduler,
    yielding `(item, result)` pairs **as they complete** (completion order,
    not submission order — the engines merge blocks orderlessly, and
    head-of-line blocking would idle the device behind one slow GET).

    Contract (the scan pool's cancellation guarantees, unchanged from the
    per-query pool it replaced):
    - closing the generator cancels not-yet-dispatched tasks, so no storage
      call is issued after close; tasks already mid-fetch finish and their
      results are dropped; the drain is synchronous;
    - in-flight decoded bytes are bounded by the lane's budget (estimated
      by `size_of`); the trace context at submission is carried into every
      worker so per-file spans parent correctly.

    `fetch` errors propagate to the consumer (expected per-file read errors
    are already converted to `None` results by the caller's fetch fn).
    """
    for item in items:
        est = max(1, size_of(item))
        # each task enters its own copy of the submitter's context so spans
        # recorded during fetch/decode join the query's trace
        ctx = contextvars.copy_context()

        def task(item=item, est=est, ctx=ctx):
            # every code path MUST put exactly one record or the consumer hangs
            if lane.cancelled.is_set():
                lane.results.put((item, None, None, est))
                return
            try:
                out = ctx.run(fetch, item)
            except BaseException as e:  # noqa: BLE001 - re-raised in the consumer
                lane.results.put((item, None, e, est))
                return
            lane.results.put((item, out, None, est))

        lane.submit(task, est)

    received = 0
    try:
        while received < len(items):
            item, out, err, est = lane.results.get()
            received += 1
            lane.release_bytes(est)
            if err is not None:
                raise err
            if out is not None:
                yield item, out
    finally:
        lane.close()


def scan_pool_iter(
    items: list,
    fetch: Callable,
    *,
    workers: int,
    inflight_bytes: int,
    size_of: Callable[[object], int],
):
    """Single-query pool over a throwaway scheduler (compat shim for
    callers that want an isolated pool; production scans share the global
    scheduler via get_scan_scheduler + lane_iter). Threads are joined when
    the generator finishes or is closed."""
    sched = ScanScheduler(max(1, workers), "fair")
    lane = sched.lane(inflight_bytes=inflight_bytes)
    try:
        yield from lane_iter(lane, items, fetch, size_of)
    finally:
        sched.shutdown()


# --------------------------------------------------------------------------
# projected column-chunk range reads


class _RangeReadUncovered(Exception):
    """A read landed outside the fetched ranges (page-index probe, metadata
    the chunk map didn't predict) — the caller falls back to a full GET."""


class _SparseFile:
    """Seekable read-only file over fetched byte segments of a remote object.

    pyarrow's ParquetFile drives it like any file: seek to the footer, then
    seek/read each projected column chunk. Reads must land inside a fetched
    segment; anything else raises `_RangeReadUncovered`."""

    def __init__(self, size: int, segments: list[tuple[int, bytes]]):
        self._size = size
        self._segs = sorted(segments)
        self._pos = 0
        self.closed = False

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def close(self) -> None:
        self.closed = True

    def flush(self) -> None:
        pass

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self._size + offset
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._size - self._pos
        if n == 0:
            return b""
        for start, data in self._segs:
            if start <= self._pos and self._pos + n <= start + len(data):
                off = self._pos - start
                self._pos += n
                return data[off : off + n]
        raise _RangeReadUncovered(f"read [{self._pos}, +{n}) outside fetched ranges")


def coalesce_ranges(
    ranges: list[tuple[int, int]], gap: int
) -> list[tuple[int, int]]:
    """Merge inclusive [start, end] ranges whose gap is <= `gap` bytes —
    a handful of slightly-fat GETs beats many tiny round trips."""
    if not ranges:
        return []
    out: list[list[int]] = []
    for s, e in sorted(ranges):
        if out and s <= out[-1][1] + 1 + gap:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


class StreamScan:
    """Materialize a stream's sources for one query."""

    def __init__(
        self,
        parseable: Parseable,
        plan: LogicalPlan,
        hot_tier_dir: Path | None = None,
        use_hot_stubs: bool = False,
        file_filter: Callable[[str], bool] | None = None,
        local_staging: bool = True,
        staging_parquet: bool = True,
        fetch_remote_staging: bool = True,
    ):
        self.p = parseable
        self.plan = plan
        self.hot_tier_dir = hot_tier_dir
        # device-resident blocks skip the parquet read entirely: the scan
        # yields a stub the TPU executor resolves from the hot set
        self.use_hot_stubs = use_hot_stubs
        # distributed pushdown scoping (query/fanout.py): predicate over a
        # manifest file's BASENAME partitioning the scan by owner tag — a
        # peer keeps only its own files, the querier skips files a live
        # peer will scan, the fallback pass keeps only a failed peer's.
        # Files it rejects count as files_delegated, not pruned.
        self.file_filter = file_filter
        # staging sources: this node's in-memory/arrow window, this node's
        # staged-but-uncommitted parquet, and (queriers) the peers' windows
        # over the cluster data plane — individually switchable because the
        # peer partial scan and the fallback scan each cover a subset
        self.local_staging = local_staging
        self.staging_parquet = staging_parquet
        self.fetch_remote_staging = fetch_remote_staging
        self._sources: dict[bytes, ManifestFile] = {}
        self._manifest_files: list[ManifestFile] | None = None
        # ordered source ids the scan stubbed (hot-set or enccache
        # resident): the TPU executor's prefetcher walks this list to ship
        # block i+1 while block i aggregates. Complete before the first
        # stub is yielded (the partition loop runs eagerly).
        self.prefetchable: list[bytes] = []
        # pool workers update the same ScanStats concurrently with the
        # consumer thread's own bookkeeping
        self.stats = ScanStats()  # guarded-by: self._stats_lock
        self._stats_lock = threading.Lock()

    # ---------------------------------------------------------------- helpers

    def merged_snapshot(self) -> Snapshot:
        """Union of all nodes' snapshots for this stream
        (reference: stream_schema_provider.rs:566-585)."""
        merged = Snapshot()
        for fmt in self.p.metastore.get_all_stream_jsons(self.plan.stream):
            merged.manifest_list.extend(fmt.snapshot.manifest_list)
        return merged

    def _within_staging_window(self) -> bool:
        """Does the query range touch data still in staging?
        (reference: stream_schema_provider.rs:849-871)."""
        high = self.plan.time_bounds.high
        if high is None:
            return True
        window_start = datetime.now(UTC) - timedelta(seconds=2 * LOCAL_SYNC_INTERVAL)
        return high >= window_start

    def _columns_for_read(self, available: list[str]) -> list[str] | None:
        needed = self.plan.needed_columns
        if needed is None:
            return None
        cols = [c for c in available if c in needed]
        # carry the timestamp column for time filtering — unless the plan
        # dropped it (no bounds, no expression touches it)
        tb = self.plan.time_bounds
        wants_ts = (
            DEFAULT_TIMESTAMP_KEY in needed or tb.low is not None or tb.high is not None
        )
        if wants_ts and DEFAULT_TIMESTAMP_KEY in available and DEFAULT_TIMESTAMP_KEY not in cols:
            cols.append(DEFAULT_TIMESTAMP_KEY)
        return cols

    # ---------------------------------------------------------------- sources

    def legacy_listing_files(self) -> list[ManifestFile]:
        """Prefix-listing fallback for pre-manifest data (reference:
        query/listing_table_builder.rs:41-147): when a stream has NO
        snapshot manifests at all, parquet uploaded by older deployments is
        discovered by listing `{stream}/date=.../` prefixes bounded by the
        query's time range."""
        tb = self.plan.time_bounds
        if tb.low is not None and tb.high is not None:
            from parseable_tpu.utils.timeutil import TimeRange

            prefixes = [
                f"{self.plan.stream}/{p}"
                for p in TimeRange(tb.low, tb.high).generate_prefixes()
            ]
            # too many minute prefixes -> one stream-wide listing wins
            if len(prefixes) > 256:
                prefixes = [f"{self.plan.stream}/date="]
        else:
            prefixes = [f"{self.plan.stream}/date="]
        out: list[ManifestFile] = []
        seen: set[str] = set()
        errors = 0
        for prefix in prefixes:
            try:
                metas = list(self.p.storage.list_prefix(prefix))
            except Exception:
                logger.warning("legacy listing failed for %s", prefix, exc_info=True)
                errors += 1
                continue
            for m in metas:
                if not m.key.endswith(".parquet") or m.key in seen:
                    continue
                seen.add(m.key)
                with self._stats_lock:
                    self.stats.files_total += 1
                if self.file_filter is not None and not self.file_filter(
                    m.key.rsplit("/", 1)[-1]
                ):
                    with self._stats_lock:
                        self.stats.files_delegated += 1
                    continue
                out.append(ManifestFile(file_path=m.key, num_rows=0, file_size=m.size))
        if errors == len(prefixes) and errors:
            # storage down must error, not masquerade as an empty stream
            raise RuntimeError("legacy listing failed for every prefix (storage unavailable?)")
        return out

    def manifest_files(self) -> list[ManifestFile]:
        """Manifest entries after time + stats pruning; falls back to
        prefix listing when the stream predates manifests. Memoized for
        the scan's lifetime — the session consults it up to three times
        per query (time hint, count fast path, the scan itself)."""
        if self._manifest_files is not None:
            return self._manifest_files
        self._manifest_files = self._manifest_files_uncached()
        return self._manifest_files

    def _manifest_files_uncached(self) -> list[ManifestFile]:
        snapshot = self.merged_snapshot()
        if not snapshot.manifest_list:
            return self.legacy_listing_files()
        items = snapshot.manifests_for_range(self.plan.time_bounds.low, self.plan.time_bounds.high)
        files: list[ManifestFile] = []
        seen: set[str] = set()
        for item in items:
            prefix = item.manifest_path[: -len("/manifest.json")]
            manifest = self.p.metastore.get_manifest(prefix)
            if manifest is None:
                continue
            for f in manifest.files:
                if f.file_path in seen:
                    continue
                seen.add(f.file_path)
                with self._stats_lock:
                    self.stats.files_total += 1
                if self.file_filter is not None and not self.file_filter(
                    f.file_path.rsplit("/", 1)[-1]
                ):
                    with self._stats_lock:
                        self.stats.files_delegated += 1
                    continue
                if not self._file_overlaps_time(f):
                    with self._stats_lock:
                        self.stats.files_pruned += 1
                    continue
                if not prune_file(f, self.plan.constraints):
                    with self._stats_lock:
                        self.stats.files_pruned += 1
                    continue
                files.append(f)
        return files

    def _file_overlaps_time(self, f: ManifestFile) -> bool:
        tb = self.plan.time_bounds
        if tb.low is None and tb.high is None:
            return True
        for col in f.columns:
            if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                lo = datetime.fromtimestamp(col.stats.min / 1000, UTC)
                hi = datetime.fromtimestamp(col.stats.max / 1000, UTC)
                if tb.low is not None and hi < tb.low:
                    return False
                if tb.high is not None and lo >= tb.high:
                    return False
        return True

    # ---------------------------------------------------- parquet read paths

    def _record_error(self) -> None:
        with self._stats_lock:
            self.stats.scan_errors += 1
        SCAN_ERRORS.labels(self.plan.stream).inc()

    def _read_parquet(
        self, f: ManifestFile, use_threads: bool = True
    ) -> pa.Table | None:
        """Read a manifest entry: hot tier first, then projected range
        reads, else a whole-object GET. Errors drop the file from the
        results but are COUNTED (stats.scan_errors + Prometheus) so a
        partial response is detectable, not silent.

        `use_threads=False` when called from the scan pool: file-level
        parallelism replaces Arrow's intra-file thread pool — stacking
        both oversubscribes the host and measurably slows the cold path."""
        from parseable_tpu.utils import telemetry

        local: Path | None = None
        if self.hot_tier_dir is not None:
            cand = self.hot_tier_dir / f.file_path
            if cand.is_file():
                local = cand
        try:
            if local is None:
                try:
                    table = self._read_projected_remote(f, use_threads)
                    if table is not None:
                        return table
                except Exception:
                    # any range-read surprise (uncovered read, footer probe
                    # mismatch, flaky ranged GET) falls back to the full GET
                    logger.debug(
                        "range read fell back for %s", f.file_path, exc_info=True
                    )
                with telemetry.TRACER.span(
                    "scan.fetch", file=f.file_path, stream=self.plan.stream
                ) as sp:
                    data = self.p.storage.get_object(f.file_path)
                    sp["bytes"] = len(data)
                with self._stats_lock:
                    self.stats.bytes_scanned += len(data)
                src = io.BytesIO(data)
            else:
                with self._stats_lock:
                    self.stats.bytes_scanned += local.stat().st_size
                src = local
            with telemetry.TRACER.span(
                "scan.decode", file=f.file_path, stream=self.plan.stream
            ):
                with pq.ParquetFile(src) as pf:
                    cols = self._columns_for_read(pf.schema_arrow.names)
                    table = pf.read(columns=cols, use_threads=use_threads)
            with self._stats_lock:
                self.stats.rows_scanned += table.num_rows
            return table
        except Exception:
            logger.exception("failed reading parquet %s", f.file_path)
            self._record_error()
            return None

    def _read_projected_remote(
        self, f: ManifestFile, use_threads: bool = True
    ) -> pa.Table | None:
        """Projected column-chunk range read; None means 'use the full GET'
        (no projection, no real ranged backend, projection covers most of
        the file, tiny file). Raises on surprises — caller falls back."""
        from parseable_tpu.utils import telemetry

        opts = getattr(self.p, "options", None)
        if opts is None or not getattr(opts, "scan_range_reads", False):
            return None
        if self.plan.needed_columns is None:
            return None
        storage = self.p.storage
        if not storage.supports_range_reads():
            return None
        size = f.file_size
        # pyarrow's ParquetFile.open probes the file with one 64 KiB tail
        # read regardless of the real footer size, so the fetched tail must
        # cover at least that much or the sparse file can't serve the probe
        footer_hint = max(64 * 1024, getattr(opts, "scan_footer_bytes", 64 * 1024))
        if not size or size <= 2 * footer_hint:
            return None  # tiny object: one GET is strictly cheaper

        fetched = 0
        table = None
        try:
            with telemetry.TRACER.span(
                "scan.fetch", file=f.file_path, ranged=True, stream=self.plan.stream
            ) as fetch_sp:
                tail = storage.get_range(
                    f.file_path, size - min(size, footer_hint), size - 1
                )
                fetched += len(tail)
                if len(tail) < 8 or tail[-4:] != _PARQUET_MAGIC:
                    raise ValueError(f"not a parquet object: {f.file_path}")
                footer_total = struct.unpack("<I", tail[-8:-4])[0] + 8
                if footer_total > size:
                    raise ValueError(f"corrupt parquet footer length in {f.file_path}")
                if footer_total > len(tail):
                    more = storage.get_range(
                        f.file_path, size - footer_total, size - len(tail) - 1
                    )
                    fetched += len(more)
                    tail = more + tail
                md = pq.read_metadata(io.BytesIO(tail[-footer_total:]))
                cols = self._columns_for_read(md.schema.to_arrow_schema().names)
                if cols is None:
                    return None
                colset = set(cols)
                ranges: list[tuple[int, int]] = []
                projected = 0
                for rg in range(md.num_row_groups):
                    group = md.row_group(rg)
                    for ci in range(group.num_columns):
                        chunk = group.column(ci)
                        if chunk.path_in_schema.split(".", 1)[0] not in colset:
                            continue
                        start = chunk.data_page_offset
                        dict_off = chunk.dictionary_page_offset
                        if dict_off is not None and 0 <= dict_off < start:
                            start = dict_off
                        length = chunk.total_compressed_size
                        if start < 0 or length <= 0 or start + length > size:
                            raise ValueError(
                                f"chunk range out of bounds in {f.file_path}"
                            )
                        ranges.append((start, start + length - 1))
                        projected += length
                if not ranges:
                    return None  # zero physical columns projected (count-only)
                max_cov = getattr(opts, "scan_range_max_coverage", 0.8)
                if projected + footer_total >= max_cov * size:
                    return None  # near-full coverage: one GET beats k round trips
                gap = max(0, getattr(opts, "scan_range_coalesce_bytes", 1024 * 1024))
                segments: list[tuple[int, bytes]] = []
                for s, e in coalesce_ranges(ranges, gap):
                    data = storage.get_range(f.file_path, s, e)
                    if len(data) != e - s + 1:
                        raise ValueError(f"short ranged GET on {f.file_path}")
                    fetched += len(data)
                    segments.append((s, data))
                segments.append((size - len(tail), tail))
                fetch_sp["bytes"] = fetched

            with telemetry.TRACER.span(
                "scan.decode",
                file=f.file_path,
                ranged=True,
                bytes=fetched,
                stream=self.plan.stream,
            ):
                with pq.ParquetFile(_SparseFile(size, segments)) as pf:
                    table = pf.read(columns=cols, use_threads=use_threads)
            return table
        finally:
            # every byte actually pulled counts — including the footer probe
            # when this path bails out to (or falls back on) the full GET
            with self._stats_lock:
                self.stats.bytes_scanned += fetched
                if table is not None:
                    saved = max(0, size - fetched)
                    self.stats.bytes_saved_by_projection += saved
                    self.stats.range_read_files += 1
                    self.stats.rows_scanned += table.num_rows
            if table is not None:
                SCAN_PROJECTION_BYTES_SAVED.labels(self.plan.stream).inc(
                    max(0, size - fetched)
                )

    def staging_tables(self) -> Iterator[pa.Table]:
        """Staging-window data: this node's unconverted arrows + unuploaded
        parquet, and — on a dedicated querier — every live ingestor's staging
        window fetched over the cluster data plane (reference:
        airplane.rs:155-184 recent-data fan-in)."""
        from parseable_tpu.config import Mode

        stream = self.p.streams.get(self.plan.stream)
        if stream is None:
            return
        if self.p.options.mode == Mode.QUERY and self.fetch_remote_staging:
            from parseable_tpu.server.cluster import fetch_staging_batches

            # bounded fan-in: the peer filters to the plan's time range and
            # projects to the needed columns before serializing — a narrow
            # dashboard query stops shipping every peer's full window
            fanin: dict = {}
            remote = fetch_staging_batches(
                self.p,
                self.plan.stream,
                time_bounds=self.plan.time_bounds,
                columns=self.plan.needed_columns,
                stats=fanin,
            )
            with self._stats_lock:
                self.stats.fanin_bytes += fanin.get("bytes", 0)
                self.stats.fanin_errors += fanin.get("errors", 0)
                for k in (
                    "http_bytes", "flight_bytes", "flight_peers", "flight_fallbacks"
                ):
                    if fanin.get(k):
                        self.stats.fanin_transport[k] = (
                            self.stats.fanin_transport.get(k, 0) + fanin[k]
                        )
            if remote:
                from parseable_tpu.utils.arrowutil import adapt_batch, merge_schemas

                with self._stats_lock:
                    self.stats.staging_batches += len(remote)
                schema = merge_schemas([b.schema for b in remote])
                table = pa.Table.from_batches([adapt_batch(schema, b) for b in remote])
                cols = self._columns_for_read(table.column_names)
                if cols is not None:
                    table = table.select(cols)
                yield table
        if not self.local_staging:
            return
        batches = stream.staging_batches()
        if batches:
            with self._stats_lock:
                self.stats.staging_batches += len(batches)
            table = pa.Table.from_batches(batches)
            cols = self._columns_for_read(table.column_names)
            if cols is not None:
                table = table.select(cols)
            yield table
        if not self.staging_parquet:
            return
        # a staged parquet that has already been uploaded and committed is
        # served by the manifest scan — reading the lingering local copy
        # (commit -> unlink is not atomic) would double-count its rows.
        # The memoized manifest list gives one consistent committed set for
        # both sides of the dedupe.
        staged = stream.parquet_files()
        committed = (
            {m.file_path.rsplit("/", 1)[-1] for m in self.manifest_files()}
            if staged
            else set()
        )
        for f in staged:
            if f.name in committed:
                continue
            try:
                with pq.ParquetFile(f) as pf:
                    cols = self._columns_for_read(pf.schema_arrow.names)
                    t = pf.read(columns=cols)
                with self._stats_lock:
                    self.stats.rows_scanned += t.num_rows
                yield t
            except FileNotFoundError:
                # committed + unlinked between listing and read; its rows
                # are (or are about to be) visible via the manifest
                logger.debug("staged parquet %s vanished (uploaded)", f)
            except Exception:
                logger.exception("failed reading staged parquet %s", f)
                self._record_error()

    # ------------------------------------------------------------------ scan

    def _stamp(self, table: pa.Table, source_id: bytes) -> pa.Table:
        meta = dict(table.schema.metadata or {})
        meta[b"ptpu_source_id"] = source_id
        return table.replace_schema_metadata(meta)

    def tables(self) -> Iterator[pa.Table]:
        """All sources.

        Staging tables are row-filtered here (they're query-local and never
        cached). Parquet tables yield *unfiltered* but stamped with a source
        id so their device encodings are query-independent and hot-set
        cacheable — the engines apply the row-level time filter themselves
        (host filter on CPU, device mask on TPU).

        Hot-set / enccache stubs resolve synchronously before any I/O;
        everything else goes through the parallel fetch+decode pool and
        yields in completion order. The bytes-scanned gauge lands in a
        `finally` so early exits (LIMIT, timeout, generator close) still
        account for what was actually fetched.
        """
        try:
            yield from self._tables_inner()
        finally:
            with self._stats_lock:
                scanned = self.stats.bytes_scanned
            TOTAL_QUERY_BYTES_SCANNED_DATE.labels(
                datetime.now(UTC).date().isoformat()
            ).inc(scanned)

    def _tables_inner(self) -> Iterator[pa.Table]:
        if self._within_staging_window():
            for t in self.staging_tables():
                t = self._apply_time_filter(t)
                if t.num_rows:
                    yield t
        hotset = key_fn = enccache = None
        dict_cols: set[str] = set()
        if self.use_hot_stubs:
            from parseable_tpu.ops.enccache import get_enccache
            from parseable_tpu.ops.hotset import get_hotset
            from parseable_tpu.query.executor_tpu import (
                dict_group_columns,
                hot_key,
                make_stub,
            )

            hotset = get_hotset()
            enccache = get_enccache(self.p.options)
            dict_cols = dict_group_columns(self.plan.select)
            key_fn = lambda sid: hot_key(sid, self.plan.needed_columns, dict_cols)
            make_stub_fn = make_stub
        to_fetch: list[tuple[ManifestFile, bytes]] = []
        stubs: list[tuple[bytes, int]] = []
        for f in self.manifest_files():
            # size + row count make the id content-sensitive: a rewritten
            # object at the same path must not serve a stale cached block
            source_id = f"{f.file_path}|{f.file_size}|{f.num_rows}".encode()
            self._sources[source_id] = f
            if hotset is not None:
                entry = hotset.get(key_fn(source_id))
                if entry is not None:
                    stubs.append((source_id, entry.meta.num_rows))
                    continue
                # encoded-block disk cache: the executor loads device-ready
                # columns; skip the parquet read entirely
                if enccache is not None and enccache.can_serve(
                    source_id, self.plan.needed_columns, dict_cols
                ):
                    stubs.append((source_id, f.num_rows))
                    continue
            to_fetch.append((f, source_id))
        # publish the ordered stub list BEFORE the first stub yield: the
        # executor's prefetcher ships block i+1 from the enccache while
        # block i aggregates (hot-now entries are included too — under
        # eviction pressure they may be gone by the time the engine gets
        # there, and the prefetcher skips anything still resident)
        self.prefetchable = [sid for sid, _rows in stubs]
        for source_id, rows in stubs:
            with self._stats_lock:
                self.stats.rows_scanned += rows
            yield make_stub_fn(source_id, rows)

        opts = getattr(self.p, "options", None)
        workers = min(len(to_fetch), max(1, getattr(opts, "scan_workers", 1)))
        if workers <= 1:
            for f, source_id in to_fetch:
                t = self._read_parquet(f)
                if t is None or t.num_rows == 0:
                    continue
                yield self._stamp(t, source_id)
            return
        inflight = max(1, getattr(opts, "scan_inflight_bytes", 256 * 1024 * 1024))

        def on_wait(seconds: float) -> None:
            with self._stats_lock:
                self.stats.sched_wait_seconds += seconds

        # shared cross-query scheduler: this query's files ride one lane,
        # dispatched fairly against every other in-flight query's lanes
        lane = get_scan_scheduler(opts).lane(
            inflight_bytes=inflight, on_wait=on_wait
        )
        pooled = lane_iter(
            lane,
            to_fetch,
            lambda pair: self._read_parquet(pair[0], use_threads=False),
            lambda pair: pair[0].file_size or 1,
        )
        try:
            for (f, source_id), t in pooled:
                if t.num_rows == 0:
                    continue
                yield self._stamp(t, source_id)
        finally:
            # explicit, synchronous lane drain when the consumer closes us
            # (a for-loop does not close its source generator on its own)
            pooled.close()

    def read_source(self, source_id: bytes) -> pa.Table:
        """Re-read a stubbed source (hot-set eviction race / CPU fallback)."""
        f = self._sources.get(source_id)
        if f is None:
            raise KeyError(f"unknown scan source {source_id!r}")
        t = self._read_parquet(f)
        if t is None:
            raise OSError(f"failed to re-read {f.file_path}")
        return self._stamp(t, source_id)

    def _apply_time_filter(self, table: pa.Table) -> pa.Table:
        tb = self.plan.time_bounds
        if (tb.low is None and tb.high is None) or DEFAULT_TIMESTAMP_KEY not in table.column_names:
            return table
        import pyarrow.compute as pc

        col = table.column(DEFAULT_TIMESTAMP_KEY)
        mask = None
        if tb.low is not None:
            mask = pc.greater_equal(col, pa.scalar(tb.low.replace(tzinfo=None), type=col.type))
        if tb.high is not None:
            m2 = pc.less(col, pa.scalar(tb.high.replace(tzinfo=None), type=col.type))
            mask = m2 if mask is None else pc.and_(mask, m2)
        return table.filter(mask)
