"""Scan provider: union of staging + hot tier + object-store parquet.

Parity target (reference: src/query/stream_schema_provider.rs:533-666 scan).
The scan resolves, in order:

1. **staging** — recent in-memory/disk arrows on this node, included when the
   query range touches the staging window (last ~LOCAL_SYNC_INTERVAL secs);
2. **hot tier** — parquet files already cached on local NVMe;
3. **object store** — manifest-pruned parquet (time overlap + column min/max
   stats), downloaded through the storage client.

Returns pyarrow Tables column-pruned to what the plan needs. All sources are
adapted to the merged stream schema so mixed-schema files union cleanly.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from datetime import UTC, datetime, timedelta
from pathlib import Path
from typing import Iterator

import pyarrow as pa
import pyarrow.parquet as pq

from parseable_tpu import DEFAULT_TIMESTAMP_KEY, LOCAL_SYNC_INTERVAL
from parseable_tpu.catalog import ManifestFile, Snapshot
from parseable_tpu.core import Parseable
from parseable_tpu.query.planner import LogicalPlan, prune_file
from parseable_tpu.utils.metrics import TOTAL_QUERY_BYTES_SCANNED_DATE

logger = logging.getLogger(__name__)


@dataclass
class ScanStats:
    files_total: int = 0
    files_pruned: int = 0
    bytes_scanned: int = 0
    rows_scanned: int = 0
    staging_batches: int = 0


def prefetch_iter(source, depth: int = 2):
    """Run `source` on a background thread, keeping `depth` items ready.

    Overlaps parquet read/decode with device compute (SURVEY hard-parts:
    "keep host->device transfer off the critical path"). Exceptions
    propagate to the consumer. When the consumer stops early (LIMIT,
    timeout, generator close), the worker notices the closed flag on its
    next bounded put and exits — no leaked thread or buffered tables.
    """
    import queue as _q

    q: _q.Queue = _q.Queue(maxsize=max(1, depth))
    _END = object()
    closed = threading.Event()

    def worker():
        try:
            for item in source:
                while not closed.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except _q.Full:
                        continue
                if closed.is_set():
                    return
        except BaseException as e:  # propagate into the consumer
            if not closed.is_set():
                q.put((_END, e))
            return
        if not closed.is_set():
            q.put((_END, None))

    t = threading.Thread(target=worker, name="scan-prefetch", daemon=True)
    t.start()

    def gen():
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and len(item) == 2 and item[0] is _END:
                    if item[1] is not None:
                        raise item[1]
                    return
                yield item
        finally:
            closed.set()
            while not q.empty():  # drop buffered tables promptly
                try:
                    q.get_nowait()
                except _q.Empty:
                    break

    return gen()


class StreamScan:
    """Materialize a stream's sources for one query."""

    def __init__(
        self,
        parseable: Parseable,
        plan: LogicalPlan,
        hot_tier_dir: Path | None = None,
        use_hot_stubs: bool = False,
    ):
        self.p = parseable
        self.plan = plan
        self.hot_tier_dir = hot_tier_dir
        # device-resident blocks skip the parquet read entirely: the scan
        # yields a stub the TPU executor resolves from the hot set
        self.use_hot_stubs = use_hot_stubs
        self._sources: dict[bytes, ManifestFile] = {}
        self._manifest_files: list[ManifestFile] | None = None
        self.stats = ScanStats()

    # ---------------------------------------------------------------- helpers

    def merged_snapshot(self) -> Snapshot:
        """Union of all nodes' snapshots for this stream
        (reference: stream_schema_provider.rs:566-585)."""
        merged = Snapshot()
        for fmt in self.p.metastore.get_all_stream_jsons(self.plan.stream):
            merged.manifest_list.extend(fmt.snapshot.manifest_list)
        return merged

    def _within_staging_window(self) -> bool:
        """Does the query range touch data still in staging?
        (reference: stream_schema_provider.rs:849-871)."""
        high = self.plan.time_bounds.high
        if high is None:
            return True
        window_start = datetime.now(UTC) - timedelta(seconds=2 * LOCAL_SYNC_INTERVAL)
        return high >= window_start

    def _columns_for_read(self, available: list[str]) -> list[str] | None:
        needed = self.plan.needed_columns
        if needed is None:
            return None
        cols = [c for c in available if c in needed]
        # carry the timestamp column for time filtering — unless the plan
        # dropped it (no bounds, no expression touches it)
        tb = self.plan.time_bounds
        wants_ts = (
            DEFAULT_TIMESTAMP_KEY in needed or tb.low is not None or tb.high is not None
        )
        if wants_ts and DEFAULT_TIMESTAMP_KEY in available and DEFAULT_TIMESTAMP_KEY not in cols:
            cols.append(DEFAULT_TIMESTAMP_KEY)
        return cols

    # ---------------------------------------------------------------- sources

    def legacy_listing_files(self) -> list[ManifestFile]:
        """Prefix-listing fallback for pre-manifest data (reference:
        query/listing_table_builder.rs:41-147): when a stream has NO
        snapshot manifests at all, parquet uploaded by older deployments is
        discovered by listing `{stream}/date=.../` prefixes bounded by the
        query's time range."""
        tb = self.plan.time_bounds
        if tb.low is not None and tb.high is not None:
            from parseable_tpu.utils.timeutil import TimeRange

            prefixes = [
                f"{self.plan.stream}/{p}"
                for p in TimeRange(tb.low, tb.high).generate_prefixes()
            ]
            # too many minute prefixes -> one stream-wide listing wins
            if len(prefixes) > 256:
                prefixes = [f"{self.plan.stream}/date="]
        else:
            prefixes = [f"{self.plan.stream}/date="]
        out: list[ManifestFile] = []
        seen: set[str] = set()
        errors = 0
        for prefix in prefixes:
            try:
                metas = list(self.p.storage.list_prefix(prefix))
            except Exception:
                logger.warning("legacy listing failed for %s", prefix, exc_info=True)
                errors += 1
                continue
            for m in metas:
                if not m.key.endswith(".parquet") or m.key in seen:
                    continue
                seen.add(m.key)
                self.stats.files_total += 1
                out.append(ManifestFile(file_path=m.key, num_rows=0, file_size=m.size))
        if errors == len(prefixes) and errors:
            # storage down must error, not masquerade as an empty stream
            raise RuntimeError("legacy listing failed for every prefix (storage unavailable?)")
        return out

    def manifest_files(self) -> list[ManifestFile]:
        """Manifest entries after time + stats pruning; falls back to
        prefix listing when the stream predates manifests. Memoized for
        the scan's lifetime — the session consults it up to three times
        per query (time hint, count fast path, the scan itself)."""
        if self._manifest_files is not None:
            return self._manifest_files
        self._manifest_files = self._manifest_files_uncached()
        return self._manifest_files

    def _manifest_files_uncached(self) -> list[ManifestFile]:
        snapshot = self.merged_snapshot()
        if not snapshot.manifest_list:
            return self.legacy_listing_files()
        items = snapshot.manifests_for_range(self.plan.time_bounds.low, self.plan.time_bounds.high)
        files: list[ManifestFile] = []
        seen: set[str] = set()
        for item in items:
            prefix = item.manifest_path[: -len("/manifest.json")]
            manifest = self.p.metastore.get_manifest(prefix)
            if manifest is None:
                continue
            for f in manifest.files:
                if f.file_path in seen:
                    continue
                seen.add(f.file_path)
                self.stats.files_total += 1
                if not self._file_overlaps_time(f):
                    self.stats.files_pruned += 1
                    continue
                if not prune_file(f, self.plan.constraints):
                    self.stats.files_pruned += 1
                    continue
                files.append(f)
        return files

    def _file_overlaps_time(self, f: ManifestFile) -> bool:
        tb = self.plan.time_bounds
        if tb.low is None and tb.high is None:
            return True
        for col in f.columns:
            if col.name == DEFAULT_TIMESTAMP_KEY and col.stats is not None:
                lo = datetime.fromtimestamp(col.stats.min / 1000, UTC)
                hi = datetime.fromtimestamp(col.stats.max / 1000, UTC)
                if tb.low is not None and hi < tb.low:
                    return False
                if tb.high is not None and lo >= tb.high:
                    return False
        return True

    def _read_parquet(self, f: ManifestFile) -> pa.Table | None:
        """Read a manifest entry: hot tier first, else object store."""
        local: Path | None = None
        if self.hot_tier_dir is not None:
            cand = self.hot_tier_dir / f.file_path
            if cand.is_file():
                local = cand
        try:
            if local is None:
                import io

                data = self.p.storage.get_object(f.file_path)
                self.stats.bytes_scanned += len(data)
                src = io.BytesIO(data)
            else:
                self.stats.bytes_scanned += local.stat().st_size
                src = local
            pf = pq.ParquetFile(src)
            cols = self._columns_for_read(pf.schema_arrow.names)
            table = pf.read(columns=cols)
            self.stats.rows_scanned += table.num_rows
            return table
        except Exception:
            logger.exception("failed reading parquet %s", f.file_path)
            return None

    def staging_tables(self) -> Iterator[pa.Table]:
        """Staging-window data: this node's unconverted arrows + unuploaded
        parquet, and — on a dedicated querier — every live ingestor's staging
        window fetched over the cluster data plane (reference:
        airplane.rs:155-184 recent-data fan-in)."""
        from parseable_tpu.config import Mode

        stream = self.p.streams.get(self.plan.stream)
        if stream is None:
            return
        if self.p.options.mode == Mode.QUERY:
            from parseable_tpu.server.cluster import fetch_staging_batches

            remote = fetch_staging_batches(self.p, self.plan.stream)
            if remote:
                from parseable_tpu.utils.arrowutil import adapt_batch, merge_schemas

                self.stats.staging_batches += len(remote)
                schema = merge_schemas([b.schema for b in remote])
                table = pa.Table.from_batches([adapt_batch(schema, b) for b in remote])
                cols = self._columns_for_read(table.column_names)
                if cols is not None:
                    table = table.select(cols)
                yield table
        batches = stream.staging_batches()
        if batches:
            self.stats.staging_batches += len(batches)
            table = pa.Table.from_batches(batches)
            cols = self._columns_for_read(table.column_names)
            if cols is not None:
                table = table.select(cols)
            yield table
        for f in stream.parquet_files():
            try:
                pf = pq.ParquetFile(f)
                cols = self._columns_for_read(pf.schema_arrow.names)
                t = pf.read(columns=cols)
                self.stats.rows_scanned += t.num_rows
                yield t
            except Exception:
                logger.exception("failed reading staged parquet %s", f)

    # ------------------------------------------------------------------ scan

    def tables(self) -> Iterator[pa.Table]:
        """All sources.

        Staging tables are row-filtered here (they're query-local and never
        cached). Parquet tables yield *unfiltered* but stamped with a source
        id so their device encodings are query-independent and hot-set
        cacheable — the engines apply the row-level time filter themselves
        (host filter on CPU, device mask on TPU).
        """
        if self._within_staging_window():
            for t in self.staging_tables():
                t = self._apply_time_filter(t)
                if t.num_rows:
                    yield t
        hotset = key_fn = enccache = None
        dict_cols: set[str] = set()
        if self.use_hot_stubs:
            from parseable_tpu.ops.enccache import get_enccache
            from parseable_tpu.ops.hotset import get_hotset
            from parseable_tpu.query.executor_tpu import (
                dict_group_columns,
                hot_key,
                make_stub,
            )

            hotset = get_hotset()
            enccache = get_enccache(self.p.options)
            dict_cols = dict_group_columns(self.plan.select)
            key_fn = lambda sid: hot_key(sid, self.plan.needed_columns, dict_cols)
            make_stub_fn = make_stub
        for f in self.manifest_files():
            # size + row count make the id content-sensitive: a rewritten
            # object at the same path must not serve a stale cached block
            source_id = f"{f.file_path}|{f.file_size}|{f.num_rows}".encode()
            self._sources[source_id] = f
            if hotset is not None:
                entry = hotset.get(key_fn(source_id))
                if entry is not None:
                    self.stats.rows_scanned += entry.meta.num_rows
                    yield make_stub_fn(source_id, entry.meta.num_rows)
                    continue
                # encoded-block disk cache: the executor loads device-ready
                # columns; skip the parquet read entirely
                if enccache is not None and enccache.can_serve(
                    source_id, self.plan.needed_columns, dict_cols
                ):
                    self.stats.rows_scanned += f.num_rows
                    yield make_stub_fn(source_id, f.num_rows)
                    continue
            t = self._read_parquet(f)
            if t is None or t.num_rows == 0:
                continue
            meta = dict(t.schema.metadata or {})
            meta[b"ptpu_source_id"] = source_id
            yield t.replace_schema_metadata(meta)
        TOTAL_QUERY_BYTES_SCANNED_DATE.labels(datetime.now(UTC).date().isoformat()).inc(
            self.stats.bytes_scanned
        )

    def read_source(self, source_id: bytes) -> pa.Table:
        """Re-read a stubbed source (hot-set eviction race / CPU fallback)."""
        f = self._sources.get(source_id)
        if f is None:
            raise KeyError(f"unknown scan source {source_id!r}")
        t = self._read_parquet(f)
        if t is None:
            raise OSError(f"failed to re-read {f.file_path}")
        meta = dict(t.schema.metadata or {})
        meta[b"ptpu_source_id"] = source_id
        return t.replace_schema_metadata(meta)

    def _apply_time_filter(self, table: pa.Table) -> pa.Table:
        tb = self.plan.time_bounds
        if (tb.low is None and tb.high is None) or DEFAULT_TIMESTAMP_KEY not in table.column_names:
            return table
        import pyarrow.compute as pc

        col = table.column(DEFAULT_TIMESTAMP_KEY)
        mask = None
        if tb.low is not None:
            mask = pc.greater_equal(col, pa.scalar(tb.low.replace(tzinfo=None), type=col.type))
        if tb.high is not None:
            m2 = pc.less(col, pa.scalar(tb.high.replace(tzinfo=None), type=col.type))
            mask = m2 if mask is None else pc.and_(mask, m2)
        return table.filter(mask)

