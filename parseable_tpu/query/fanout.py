"""Distributed query fan-out: partial-aggregate pushdown + scatter-gather.

Parity target (reference: handlers/http/cluster/mod.rs:1785-1964 querier
fan-out + airplane.rs do_get): instead of pulling every ingestor's raw
staging window and scanning all parquet centrally, the querier scatters the
statement + resolved time bounds to live ingestor peers; each peer executes
scan + PARTIAL aggregation over node-local data only — its own staging
window plus the manifest files it owns (the PR 3 basename owner tag) — and
returns ONE combined partial table (``__g*``/``__cnt``/``__pac``/``__sum``/
``__sumsq``/``__min``/``__max``) as Arrow IPC. The querier folds peer
partials into its own scan's per-block partials and finalizes through the
existing `merge_partials` -> `finalize_from_interim` funnel, so avg/stddev
stay exact (the wire carries (count, sum[, sumsq]) state, never finalized
values) and a GROUP BY over N nodes costs one merge, not N raw transfers.

Scatter-gather runtime:
- completion-order streaming gather: each peer's partial is consumed as it
  lands, never `f.result()` in submission order;
- bounded in-flight fan-out (P_FANOUT_MAX_INFLIGHT): extra peers dispatch
  as earlier requests resolve;
- per-peer timeout (P_FANOUT_TIMEOUT_MS) + ONE retry on retryable errors;
- hedging (P_FANOUT_HEDGE_MS): a duplicate request to a peer whose first
  attempt is still outstanding; first answer wins, the loser is discarded
  — a peer can never contribute twice (merge-side `done` gate);
- per-peer fallback: a peer that 404s the endpoint (older build), rejects
  the plan, times out, or answers with a mismatched owner tag is served by
  the CENTRAL path for exactly its slice — bounded staging pull + a local
  scan restricted to its owned manifest files — so failures degrade to the
  old data plane without dropping or duplicating groups.

Eligibility: single-stream GROUP BY aggregates whose specs are
partializable (partials.PARTIALIZABLE_FUNCS); everything else stays on the
central-pull path. The local merge runs the CPU executor regardless of the
session engine — the distributed funnel is host-side; peers are free to
use any engine for their node-local scan.
"""

from __future__ import annotations

import io
import json
import logging
import queue as _queue
import threading
import time as _time
import urllib.error
from typing import TYPE_CHECKING

import pyarrow as pa
import pyarrow.ipc as ipc

from parseable_tpu.utils import telemetry
from parseable_tpu.utils.metrics import (
    CLUSTER_FANOUT_BYTES,
    CLUSTER_FANOUT_LATENCY,
    CLUSTER_FANOUT_REQUESTS,
)

if TYPE_CHECKING:  # pragma: no cover - typing only
    from parseable_tpu.core import Parseable
    from parseable_tpu.query.planner import LogicalPlan
    from parseable_tpu.query.provider import StreamScan

logger = logging.getLogger(__name__)

PARTIAL_PATH = "/api/v1/internal/query/partial"

# response headers carrying the peer's scan accounting + identity proof
H_ROWS = "X-P-Rows-Scanned"
H_ERRORS = "X-P-Scan-Errors"
H_TAG = "X-P-Owner-Tag"


class UnsupportedPartial(Exception):
    """The statement can't execute as a node-local partial (not a GROUP BY,
    un-partializable aggregate, composite query) — the peer answers 400 and
    the querier keeps that peer on the central path."""


def serialize_table(table: pa.Table) -> bytes:
    sink = io.BytesIO()
    with ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def deserialize_table(data: bytes) -> pa.Table:
    with ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()


# --------------------------------------------------------------- peer side


def execute_local_partial(
    p: "Parseable", stream_name: str, sql: str, start: str | None, end: str | None
) -> tuple[bytes, dict] | None:
    """HTTP wire shape of `execute_local_partial_table`: the combined
    partial serialized as Arrow IPC. Returns (ipc_payload, meta) — payload
    b"" when the node-local slice is empty — or None when this node doesn't
    know the stream at all."""
    out = execute_local_partial_table(p, stream_name, sql, start, end)
    if out is None:
        return None
    table, meta = out
    if table is None:
        return b"", meta
    return serialize_table(table), meta


def execute_local_partial_table(
    p: "Parseable", stream_name: str, sql: str, start: str | None, end: str | None
) -> tuple[pa.Table | None, dict] | None:
    """Run the node-local half of a pushed-down aggregate: scan this node's
    staging window (arrows AND flushed-but-unuploaded parquet — the querier
    delegated this node's whole slice, so nothing else covers those rows)
    plus the manifest files this node owns, reduce to per-block partials, and combine
    them into one wire-ready partial table.

    Transport-neutral core shared by the HTTP handler (which serializes to
    IPC) and the Flight DoGet partial ticket (which streams the table
    zero-copy). Returns (combined_table_or_None, meta) — table None when
    the node-local slice is empty — or None when this node doesn't know the
    stream at all (nothing node-local can exist). Raises UnsupportedPartial
    for plans the partial protocol can't express."""
    from parseable_tpu.query import partials as PT
    from parseable_tpu.query import sql as S
    from parseable_tpu.query.executor import QueryExecutor
    from parseable_tpu.query.provider import StreamScan
    from parseable_tpu.query.session import QueryError, QuerySession
    from parseable_tpu.query.sql import SqlError

    t0 = _time.monotonic()
    try:
        select = S.parse_sql(sql)
    except SqlError as e:
        raise UnsupportedPartial(f"unparseable statement: {e}") from e
    if select.ctes or select.set_ops or select.joins or select.explain:
        raise UnsupportedPartial("composite statements are not partializable")
    if select.table != stream_name:
        raise UnsupportedPartial("statement stream does not match the route")

    sess = QuerySession(p, engine="cpu")
    try:
        lp = sess._plan_ast(select, start, end, None, t0)
    except QueryError:
        # unknown stream on this node: no staging, no owned files
        return None

    ex = QueryExecutor(lp)
    agg, _rewritten, _names = ex.build_aggregator()
    if not (
        lp.is_aggregate
        and lp.select.group_by
        and PT.specs_partializable(agg.specs)
    ):
        raise UnsupportedPartial("plan is not a partializable GROUP BY aggregate")

    tag = p.owner_tag
    meta = {"owner_tag": tag, "rows_scanned": 0, "scan_errors": 0}
    # staging_parquet=True: the querier delegated this node's WHOLE slice,
    # so flushed-but-not-yet-uploaded parquet must be served here — nobody
    # else can see it. The scan dedupes staged copies against the committed
    # manifest, so a file mid-upload is never counted twice.
    scan = StreamScan(
        p,
        lp,
        file_filter=lambda basename: basename.startswith(tag),
        fetch_remote_staging=False,
    )
    with telemetry.TRACER.span(
        "query.partial", stream=stream_name, owner=tag
    ) as sp:
        tables = scan.tables()
        rows_seen = [0]

        def counted():
            # staging blocks don't tick scan.stats.rows_scanned (only
            # parquet reads do), so count what actually flowed through
            for t in tables:
                rows_seen[0] += t.num_rows
                yield t

        try:
            parts = ex.partial_tables(counted())
        finally:
            tables.close()
        meta["rows_scanned"] = rows_seen[0]
        with scan._stats_lock:
            meta["scan_errors"] = scan.stats.scan_errors
        sp["rows"] = meta["rows_scanned"]
        if not parts:
            return None, meta
        combined = PT.combine_partials(parts, agg.specs, len(lp.select.group_by))
        sp["bytes"] = combined.nbytes
    return combined, meta


# ------------------------------------------------------------ querier side


class _PeerState:
    """Gather-side bookkeeping for one scattered peer. All fields are
    mutated only by the collector thread (collect()) except via the queue;
    attempt workers never touch state directly."""

    def __init__(self, node: dict):
        self.node = node
        self.domain = node["domain_name"]
        self.tag = node["owner_tag"]
        self.issued = 0
        self.resolved = 0
        self.retried = False
        self.hedged = False
        self.first_sent_at: float | None = None
        self.done = False  # a result was merged
        self.failed = False  # exhausted -> central fallback
        self.fail_reason: str | None = None
        self.elapsed_ms: float | None = None
        self.bytes = 0
        self.rows = 0  # peer-reported rows scanned (H_ROWS)
        self.transport: str | None = None  # "flight" | "http" once done


class DistributedRun:
    """One query's scatter-gather. start() launches the bounded fan-out on
    the cluster pool; collect() — invoked by the executor after the local
    scan has reduced — gathers peer partials in completion order, applies
    retry/hedge policy, runs the central fallback for failed peers, and
    returns the partial tables to merge."""

    def __init__(self, p: "Parseable", lp: "LogicalPlan", scan: "StreamScan",
                 peers: list[dict], body: dict):
        self.p = p
        self.lp = lp
        self.scan = scan
        self.opts = p.options
        self.body = json.dumps(body).encode()
        self.body_dict = body  # reused verbatim as the Flight partial ticket
        self.peers = [_PeerState(n) for n in peers]
        self._q: _queue.Queue = _queue.Queue()
        self._deferred: list[_PeerState] = []
        # worker-incremented under the GIL (same pragmatic idiom as the
        # fan-in stats dict); read only after collect() drains the queue
        self._flight_declines = 0
        self.stats: dict = {
            "mode": "pushdown",
            "peers": len(peers),
            "ok": 0,
            "fallback": 0,
            "hedged": 0,
            "retries": 0,
            "bytes": 0,
            "fallback_fanin_bytes": 0,
            "per_peer": {},
        }

    # ---------------------------------------------------------- dispatch

    def start(self) -> None:
        max_inflight = max(1, self.opts.fanout_max_inflight)
        for st in self.peers[:max_inflight]:
            self._submit(st, "initial")
        self._deferred = list(self.peers[max_inflight:])

    def _submit(self, st: _PeerState, kind: str) -> None:
        st.issued += 1
        if st.first_sent_at is None:
            st.first_sent_at = _time.monotonic()
        from parseable_tpu.server.cluster import get_cluster_pool

        # propagate: attempts run inside the query's trace
        get_cluster_pool().submit(telemetry.propagate(self._attempt), st, kind)

    def _attempt(self, st: _PeerState, kind: str) -> None:
        """Worker-side: one round trip down the transport ladder — Arrow
        Flight when the peer's registry entry advertises it, with ANY
        flight failure declining to the HTTP tier byte-identically; every
        outcome posts exactly one queue record (the collector owns all
        state)."""
        from parseable_tpu.server import cluster as C

        timeout = max(0.1, self.opts.fanout_timeout_ms / 1000.0)
        t0 = _time.monotonic()
        location = C.flight_location(self.p, st.node)
        if location is not None:
            try:
                with telemetry.TRACER.span(
                    "query.fanout", peer=st.domain, kind=kind, transport="flight"
                ) as sp:
                    table, headers, nbytes = self._flight_attempt(location, timeout)
                    sp["bytes"] = nbytes
                self._q.put(
                    (st, True, table, headers, _time.monotonic() - t0, kind)
                )
                return
            except Exception as e:  # noqa: BLE001 - decline to HTTP
                C.get_flight_pool().invalidate(location)
                self._flight_declines += 1
                CLUSTER_FANOUT_REQUESTS.labels(st.domain, "flight_decline").inc()
                logger.warning(
                    "flight pushdown to %s declined (%s), retrying over HTTP: %s",
                    st.domain, kind, e,
                )
        url = f"{st.domain}{PARTIAL_PATH}/{self.lp.stream}"
        try:
            with telemetry.TRACER.span(
                "query.fanout", peer=st.domain, kind=kind, transport="http"
            ) as sp:
                with C._http(self.p, "POST", url, self.body, timeout=timeout) as resp:
                    data = resp.read()
                    headers = {
                        "rows_scanned": int(resp.headers.get(H_ROWS, 0) or 0),
                        "scan_errors": int(resp.headers.get(H_ERRORS, 0) or 0),
                        "owner_tag": resp.headers.get(H_TAG, ""),
                        "status": resp.status,
                        "transport": "http",
                        "wire_bytes": len(data),
                    }
                sp["bytes"] = len(data)
            self._q.put((st, True, data, headers, _time.monotonic() - t0, kind))
        except urllib.error.HTTPError as e:
            # 404 = endpoint absent (older peer), 400 = plan rejected: both
            # terminal for this query; 5xx is retryable
            e.close()
            self._q.put(
                (st, False, e.code, None, _time.monotonic() - t0, kind)
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            self._q.put((st, False, e, None, _time.monotonic() - t0, kind))

    def _flight_attempt(self, location: str, timeout: float):
        """One DoGet with the partial ticket: the peer's combined partial
        streams back zero-copy, its accounting riding as ptpu.* schema
        metadata (server/flight.py) which is stripped before the merge so
        the table matches the HTTP tier's byte for byte. Raises on any
        failure — the caller declines to HTTP."""
        import pyarrow.flight as fl

        from parseable_tpu.server import cluster as C
        from parseable_tpu.server.flight import (
            META_EMPTY,
            META_ERRORS,
            META_OWNER_TAG,
            META_ROWS,
            strip_flight_meta,
        )

        ticket = dict(self.body_dict, kind="partial", stream=self.lp.stream)
        client = C.get_flight_pool().get(location)
        reader = client.do_get(
            fl.Ticket(json.dumps(ticket).encode()),
            C._flight_call_options(self.p, timeout),
        )
        table = reader.read_all()
        meta = table.schema.metadata or {}
        headers = {
            "rows_scanned": int(meta.get(META_ROWS, b"0") or 0),
            "scan_errors": int(meta.get(META_ERRORS, b"0") or 0),
            "owner_tag": (meta.get(META_OWNER_TAG) or b"").decode(),
            "status": 200,
            "transport": "flight",
        }
        if meta.get(META_EMPTY) == b"1" or table.num_columns == 0:
            headers["wire_bytes"] = 0
            return None, headers, 0
        nbytes = table.nbytes
        headers["wire_bytes"] = nbytes
        return strip_flight_meta(table), headers, nbytes

    # ------------------------------------------------------------ gather

    def collect(self) -> list[pa.Table]:
        """Completion-order gather + central fallback. Called on the
        executor thread once the local blocks have reduced; peers have been
        computing since start(), overlapping the local scan."""
        from parseable_tpu.query import partials as PT  # noqa: F401 (doc link)

        tables: list[pa.Table] = []
        timeout_s = max(0.1, self.opts.fanout_timeout_ms / 1000.0)
        hedge_s = self.opts.fanout_hedge_ms / 1000.0
        deadline = _time.monotonic() + 2 * timeout_s + max(hedge_s, 0.0) + 2.0
        if self.lp.deadline is not None:
            deadline = min(deadline, self.lp.deadline)

        while True:
            pending = [st for st in self.peers if not st.done and not st.failed]
            if not pending:
                break
            now = _time.monotonic()
            if now >= deadline:
                for st in pending:
                    self._fail(st, "timeout")
                break
            # hedging: duplicate the slowest outstanding peer(s) past the
            # hedge delay; first answer wins, the loser is discarded
            next_timer = deadline
            if hedge_s > 0:
                for st in pending:
                    if st.first_sent_at is None or st.hedged:
                        continue
                    due = st.first_sent_at + hedge_s
                    if now >= due:
                        st.hedged = True
                        self.stats["hedged"] += 1
                        CLUSTER_FANOUT_REQUESTS.labels(st.domain, "hedged").inc()
                        self._submit(st, "hedge")
                    else:
                        next_timer = min(next_timer, due)
            try:
                item = self._q.get(timeout=max(0.02, next_timer - now))
            except _queue.Empty:
                continue
            self._handle(item, tables)

        fallback = [st for st in self.peers if st.failed]
        if fallback:
            tables.extend(self._fallback_partials(fallback))
        transport: dict = {}
        for st in self.peers:
            if st.done and st.transport:
                transport[st.transport] = transport.get(st.transport, 0) + 1
            self.stats["per_peer"][st.domain] = {
                "result": "ok" if st.done else (st.fail_reason or "failed"),
                "ms": round(st.elapsed_ms, 3) if st.elapsed_ms is not None else None,
                "bytes": st.bytes,
                "rows": st.rows,
                "attempts": st.issued,
                "hedged": st.hedged,
                "transport": st.transport,
            }
        # queue is drained, workers are done: safe to read the decline tally
        if self._flight_declines:
            transport["flight_declines"] = self._flight_declines
        self.stats["transport"] = transport
        return tables

    def _handle(self, item, tables: list[pa.Table]) -> None:
        st, ok, payload, headers, elapsed, kind = item
        st.resolved += 1
        if st.done or st.failed:
            # hedge/retry loser, or a straggler past the overall deadline
            # whose slice the fallback already covered: discarding is what
            # guarantees no duplicate groups
            CLUSTER_FANOUT_REQUESTS.labels(st.domain, "discarded").inc()
            return
        if ok:
            if headers["owner_tag"] != st.tag:
                # the peer answered with a different identity than the
                # registry promised: merging would double-count everything
                # outside its real scope — treat as failure, fall back
                logger.warning(
                    "pushdown peer %s owner tag mismatch (%r != %r)",
                    st.domain, headers["owner_tag"], st.tag,
                )
                self._fail(st, "tag_mismatch")
                return
            # payload is already a Table off the Flight tier, IPC bytes off
            # HTTP, or empty/None for a peer with nothing node-local
            if isinstance(payload, pa.Table):
                table = payload
            elif payload:
                try:
                    table = deserialize_table(payload)
                except pa.ArrowInvalid:
                    logger.warning("bad partial payload from %s", st.domain)
                    self._fail(st, "bad_payload")
                    return
            else:
                table = None
            nbytes = int(headers.get("wire_bytes", 0) or 0)
            st.done = True
            st.transport = headers.get("transport")
            st.elapsed_ms = elapsed * 1000
            st.bytes = nbytes
            st.rows = headers["rows_scanned"]
            self.stats["ok"] += 1
            self.stats["bytes"] += nbytes
            CLUSTER_FANOUT_REQUESTS.labels(st.domain, "ok").inc()
            CLUSTER_FANOUT_BYTES.labels(st.domain).inc(nbytes)
            CLUSTER_FANOUT_LATENCY.labels(st.domain).observe(elapsed)
            with self.scan._stats_lock:
                self.scan.stats.rows_scanned += headers["rows_scanned"]
                self.scan.stats.scan_errors += headers["scan_errors"]
            if table is not None:
                tables.append(table)
            self._submit_deferred()
            return
        # error record: payload is an exception or an HTTP status code
        terminal = isinstance(payload, int) and payload in (400, 404, 403, 401)
        logger.warning(
            "pushdown attempt (%s) to %s failed: %s", kind, st.domain, payload
        )
        if terminal:
            self._fail(st, f"http_{payload}")
        elif not st.retried:
            st.retried = True
            self.stats["retries"] += 1
            CLUSTER_FANOUT_REQUESTS.labels(st.domain, "retried").inc()
            self._submit(st, "retry")
        elif st.resolved >= st.issued:
            # nothing left outstanding and the retry budget is spent
            self._fail(st, "error")
        self._submit_deferred()

    def _fail(self, st: _PeerState, reason: str) -> None:
        st.failed = True
        st.fail_reason = reason
        self.stats["fallback"] += 1
        CLUSTER_FANOUT_REQUESTS.labels(st.domain, "fallback").inc()
        result = "timeout" if reason == "timeout" else "error"
        CLUSTER_FANOUT_REQUESTS.labels(st.domain, result).inc()

    def _submit_deferred(self) -> None:
        if not self._deferred:
            return
        inflight = sum(
            st.issued - st.resolved
            for st in self.peers
            if not st.done and not st.failed
        )
        while self._deferred and inflight < max(1, self.opts.fanout_max_inflight):
            self._submit(self._deferred.pop(0), "initial")
            inflight += 1

    # ---------------------------------------------------------- fallback

    def _fallback_partials(self, failed: list[_PeerState]) -> list[pa.Table]:
        """Central-pull coverage for exactly the failed peers' slices: their
        staging windows over the bounded fan-in, and their owned manifest
        files scanned locally. Identical results to the pre-pushdown data
        plane for those peers (an unreachable peer's staging window is
        unavailable either way, and is logged + counted)."""
        from parseable_tpu.query.executor import QueryExecutor
        from parseable_tpu.query.provider import StreamScan
        from parseable_tpu.server.cluster import fetch_staging_batches
        from parseable_tpu.utils.arrowutil import adapt_batch, merge_schemas

        parts: list[pa.Table] = []
        ex = QueryExecutor(self.lp)
        fanin: dict = {}
        batches = fetch_staging_batches(
            self.p,
            self.lp.stream,
            time_bounds=self.lp.time_bounds,
            columns=self.lp.needed_columns,
            nodes=[st.node for st in failed],
            stats=fanin,
        )
        self.stats["fallback_fanin_bytes"] += fanin.get("bytes", 0)
        with self.scan._stats_lock:
            self.scan.stats.fanin_bytes += fanin.get("bytes", 0)
            self.scan.stats.fanin_errors += fanin.get("errors", 0)
            for k in ("http_bytes", "flight_bytes", "flight_peers", "flight_fallbacks"):
                if fanin.get(k):
                    self.scan.stats.fanin_transport[k] = (
                        self.scan.stats.fanin_transport.get(k, 0) + fanin[k]
                    )
        if batches:
            schema = merge_schemas([b.schema for b in batches])
            table = pa.Table.from_batches([adapt_batch(schema, b) for b in batches])
            parts.extend(ex.partial_tables(iter([table])))

        tags = tuple(st.tag for st in failed)
        fscan = StreamScan(
            self.p,
            self.lp,
            hot_tier_dir=self.scan.hot_tier_dir,
            file_filter=lambda basename: basename.startswith(tags),
            local_staging=False,
            fetch_remote_staging=False,
        )
        tables = fscan.tables()
        try:
            parts.extend(ex.partial_tables(tables))
        finally:
            tables.close()
        with fscan._stats_lock:
            extra = (
                fscan.stats.bytes_scanned,
                fscan.stats.rows_scanned,
                fscan.stats.scan_errors,
                fscan.stats.bytes_saved_by_projection,
            )
        with self.scan._stats_lock:
            self.scan.stats.bytes_scanned += extra[0]
            self.scan.stats.rows_scanned += extra[1]
            self.scan.stats.scan_errors += extra[2]
            self.scan.stats.bytes_saved_by_projection += extra[3]
        return parts


def prepare(
    p: "Parseable", lp: "LogicalPlan", scan: "StreamScan", sql_text: str
) -> DistributedRun | None:
    """Eligibility gate + scatter launch. Returns None when the query stays
    on the central path: not a partializable GROUP BY, no live peers with a
    registered owner tag (older nodes), or pushdown disabled. On success
    the scan is re-scoped — remote staging fan-in off (peers serve their
    own windows), manifest files owned by scattered peers delegated — and
    peer requests are already in flight when this returns."""
    from parseable_tpu.query import partials as PT
    from parseable_tpu.query.executor import QueryExecutor
    from parseable_tpu.server.cluster import live_ingestors

    sel = lp.select
    if not sel.group_by:
        return None
    agg, _rewritten, _names = QueryExecutor(lp).build_aggregator()
    if not PT.specs_partializable(agg.specs):
        return None
    peers = [n for n in live_ingestors(p) if n.get("owner_tag")]
    if not peers:
        return None

    body: dict = {
        "query": sql_text,
        "fingerprint": PT.plan_fingerprint(lp, "cpu"),
    }
    if lp.time_bounds.low is not None and lp.time_bounds.high is not None:
        body["startTime"] = lp.time_bounds.low.isoformat()
        body["endTime"] = lp.time_bounds.high.isoformat()

    # re-scope the local scan: peers serve their own staging windows and
    # owned files; the querier keeps unowned/historical manifests. The
    # memoized manifest list is reset because the result-cache fingerprint
    # intentionally covered the FULL set (the merged answer represents it).
    scan.use_hot_stubs = False
    scan.fetch_remote_staging = False
    tags = tuple(n["owner_tag"] for n in peers)
    scan.file_filter = lambda basename: not basename.startswith(tags)
    scan._manifest_files = None

    run = DistributedRun(p, lp, scan, peers, body)
    run.start()
    return run
