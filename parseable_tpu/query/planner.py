"""Logical planning: time-filter extraction, predicate analysis, plan shape.

Parity targets (reference: src/query/mod.rs:385-423 final_logical_plan time
injection; src/query/stream_schema_provider.rs:705-944 PartialTimeFilter
extraction + manifest pruning bounds).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from datetime import UTC, datetime, timedelta

from parseable_tpu import DEFAULT_TIMESTAMP_KEY
from parseable_tpu.catalog import ManifestFile
from parseable_tpu.query import sql as S
from parseable_tpu.utils.timeutil import parse_rfc3339


@dataclass
class TimeBounds:
    """[low, high) bounds on the event timestamp column."""

    low: datetime | None = None
    high: datetime | None = None

    def intersect(self, other: "TimeBounds") -> "TimeBounds":
        low = max(filter(None, [self.low, other.low]), default=None)
        high = min(filter(None, [self.high, other.high]), default=None)
        return TimeBounds(low, high)


def _as_datetime(v) -> datetime | None:
    if isinstance(v, datetime):
        return v if v.tzinfo else v.replace(tzinfo=UTC)
    if isinstance(v, str):
        try:
            return parse_rfc3339(v)
        except ValueError:
            return None
    if isinstance(v, (int, float)):
        return datetime.fromtimestamp(v / 1000.0, UTC)
    return None


def extract_time_bounds(where: S.Expr | None, time_col: str = DEFAULT_TIMESTAMP_KEY) -> TimeBounds:
    """Pull conjunctive p_timestamp bounds out of a WHERE expression.

    Only top-level ANDs contribute (an OR can't restrict the scan window),
    matching the reference's PartialTimeFilter semantics.
    """
    bounds = TimeBounds()
    if where is None:
        return bounds

    def visit(e: S.Expr) -> None:
        nonlocal bounds
        if isinstance(e, S.BinaryOp) and e.op == "and":
            visit(e.left)
            visit(e.right)
            return
        if isinstance(e, S.Between) and not e.negated:
            if _col_name(e.expr) == time_col:
                lo = _literal_dt(e.low)
                hi = _literal_dt(e.high)
                if lo:
                    bounds = bounds.intersect(TimeBounds(low=lo))
                if hi:
                    bounds = bounds.intersect(TimeBounds(high=hi + timedelta(milliseconds=1)))
            return
        if isinstance(e, S.BinaryOp) and e.op in ("<", "<=", ">", ">=", "="):
            left_col = _col_name(e.left)
            right_col = _col_name(e.right)
            if left_col == time_col and right_col is None:
                dt = _literal_dt(e.right)
                if dt is None:
                    return
                # bounds are [low, high) at millisecond resolution, so the
                # strict ops need a 1 ms nudge to stay exclusive/inclusive.
                if e.op == ">":
                    bounds = bounds.intersect(TimeBounds(low=dt + timedelta(milliseconds=1)))
                elif e.op == ">=":
                    bounds = bounds.intersect(TimeBounds(low=dt))
                elif e.op == "<":
                    bounds = bounds.intersect(TimeBounds(high=dt))
                elif e.op == "<=":
                    bounds = bounds.intersect(TimeBounds(high=dt + timedelta(milliseconds=1)))
                else:  # =
                    bounds = bounds.intersect(TimeBounds(low=dt, high=dt + timedelta(milliseconds=1)))
            elif right_col == time_col and left_col is None:
                dt = _literal_dt(e.left)
                if dt is None:
                    return
                if e.op == "<":  # dt < ts  ==  ts > dt
                    bounds = bounds.intersect(TimeBounds(low=dt + timedelta(milliseconds=1)))
                elif e.op == "<=":
                    bounds = bounds.intersect(TimeBounds(low=dt))
                elif e.op == ">":  # dt > ts  ==  ts < dt
                    bounds = bounds.intersect(TimeBounds(high=dt))
                elif e.op == ">=":
                    bounds = bounds.intersect(TimeBounds(high=dt + timedelta(milliseconds=1)))
                else:  # =
                    bounds = bounds.intersect(TimeBounds(low=dt, high=dt + timedelta(milliseconds=1)))

    visit(where)
    return bounds


def _col_name(e: S.Expr) -> str | None:
    if isinstance(e, S.Column):
        return e.name
    if isinstance(e, S.Cast):
        return _col_name(e.expr)
    return None


def _literal_dt(e: S.Expr) -> datetime | None:
    if isinstance(e, S.Literal):
        return _as_datetime(e.value)
    if isinstance(e, S.Cast):
        return _literal_dt(e.expr)
    if isinstance(e, S.FunctionCall) and e.name == "to_timestamp" and e.args:
        return _literal_dt(e.args[0])
    return None


@dataclass
class ColumnConstraint:
    """One conjunctive comparison usable for min/max stats pruning."""

    column: str
    op: str  # = != < <= > >=
    value: object


def extract_column_constraints(where: S.Expr | None) -> list[ColumnConstraint]:
    out: list[ColumnConstraint] = []
    if where is None:
        return out

    def visit(e: S.Expr) -> None:
        if isinstance(e, S.BinaryOp) and e.op == "and":
            visit(e.left)
            visit(e.right)
            return
        if isinstance(e, S.BinaryOp) and e.op in ("=", "!=", "<", "<=", ">", ">="):
            lc, rc = _col_name(e.left), _col_name(e.right)
            if lc and isinstance(e.right, S.Literal):
                out.append(ColumnConstraint(lc, e.op, e.right.value))
            elif rc and isinstance(e.left, S.Literal):
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                out.append(ColumnConstraint(rc, flip.get(e.op, e.op), e.left.value))
        if isinstance(e, S.Between) and not e.negated:
            c = _col_name(e.expr)
            if c and isinstance(e.low, S.Literal) and isinstance(e.high, S.Literal):
                out.append(ColumnConstraint(c, ">=", e.low.value))
                out.append(ColumnConstraint(c, "<=", e.high.value))

    visit(where)
    return out


def prune_file(entry: ManifestFile, constraints: list[ColumnConstraint]) -> bool:
    """True if the file may contain matching rows (stats overlap check)
    (reference: stream_schema_provider.rs:946-1065)."""
    stats = entry.column_stats()
    for c in constraints:
        st = stats.get(c.column)
        if st is None:
            continue
        v = c.value
        if isinstance(v, str) and st.kind == "Int":
            dt = _as_datetime(v)
            if dt is None:
                continue
            v = int(dt.timestamp() * 1000)
        if isinstance(v, bool) and st.kind != "Bool":
            continue
        try:
            if c.op == "=" and not (st.min <= v <= st.max):
                return False
            if c.op == "<" and not (st.min < v):
                return False
            if c.op == "<=" and not (st.min <= v):
                return False
            if c.op == ">" and not (st.max > v):
                return False
            if c.op == ">=" and not (st.max >= v):
                return False
        except TypeError:
            continue  # incomparable types: cannot prune
    return True


def _is_pure_time_range(where: S.Expr | None, time_col: str = DEFAULT_TIMESTAMP_KEY) -> bool:
    """True when WHERE is None or only ANDed range comparisons/BETWEEN on the
    timestamp column — i.e. fully captured by extract_time_bounds."""
    if where is None:
        return True
    if isinstance(where, S.BinaryOp) and where.op == "and":
        return _is_pure_time_range(where.left) and _is_pure_time_range(where.right)
    if isinstance(where, S.BinaryOp) and where.op in ("<", "<=", ">", ">=", "="):
        lc, rc = _col_name(where.left), _col_name(where.right)
        if lc == time_col and rc is None:
            return _literal_dt(where.right) is not None
        if rc == time_col and lc is None:
            return _literal_dt(where.left) is not None
        return False
    if isinstance(where, S.Between) and not where.negated:
        return (
            _col_name(where.expr) == time_col
            and _literal_dt(where.low) is not None
            and _literal_dt(where.high) is not None
        )
    return False


def referenced_columns(e: S.Expr | None) -> set[str]:
    cols: set[str] = set()
    if e is None:
        return cols

    def visit(x: S.Expr) -> None:
        if isinstance(x, S.Subquery):
            return  # inner select's columns belong to the inner stream
        if isinstance(x, S.Column):
            cols.add(x.name)
        elif isinstance(x, S.BinaryOp):
            visit(x.left)
            visit(x.right)
        elif isinstance(x, S.UnaryOp):
            visit(x.operand)
        elif isinstance(x, S.InList):
            visit(x.expr)
            for i in x.items:
                visit(i)
        elif isinstance(x, S.Between):
            visit(x.expr)
            visit(x.low)
            visit(x.high)
        elif isinstance(x, S.IsNull):
            visit(x.expr)
        elif isinstance(x, S.FunctionCall):
            for a in x.args:
                visit(a)
        elif isinstance(x, S.WindowCall):
            for a in x.args:
                visit(a)
            for p in x.partition_by:
                visit(p)
            for o in x.order_by:
                visit(o.expr)
        elif isinstance(x, S.Cast):
            visit(x.expr)
        elif isinstance(x, S.Case):
            for w, t in x.whens:
                visit(w)
                visit(t)
            if x.else_expr is not None:
                visit(x.else_expr)

    visit(e)
    return cols


@dataclass
class LogicalPlan:
    """Resolved single-stream plan."""

    select: S.Select
    stream: str
    time_bounds: TimeBounds
    constraints: list[ColumnConstraint]
    needed_columns: set[str] | None  # None = all (select *)
    aggregates: list[S.SelectItem] = dc_field(default_factory=list)
    is_aggregate: bool = False
    # stream schema, when known — typed empty results, projection validation
    schema_hint: object | None = None  # pa.Schema
    # scan's overall [min, max] event time (from manifests): lets the TPU
    # engine pre-size time-bin group capacities and flush exactly once
    scan_time_hint: tuple[datetime, datetime] | None = None
    # True when p_timestamp entered needed_columns only for time-bounds
    # filtering: a query with no bounds can then skip encoding/shipping the
    # column entirely (transfer bytes are the cold-scan budget)
    ts_artificial: bool = False
    # safety rails (set by the session from Options; reference:
    # query/mod.rs:92,152-165 timeout + :216-226 memory pool)
    deadline: float | None = None  # time.monotonic() cutoff
    memory_limit_bytes: int | None = None
    execution_batch_size: int | None = None  # streaming emission chunk rows

    @property
    def count_star_only(self) -> bool:
        """Fast path: bare `SELECT count(*)` whose WHERE is *entirely* a
        conjunctive p_timestamp range (everything extract_time_bounds
        captured) — served from manifest row counts without touching data
        (reference: query/mod.rs:425-462). OR / != / IS NULL time predicates
        disqualify it: their semantics aren't carried by the bounds.
        """
        if self.select.group_by or self.select.distinct:
            return False
        # constraints on the time column are fully captured by the bounds
        # (given the purity check below); any other column disqualifies
        if any(c.column != DEFAULT_TIMESTAMP_KEY for c in self.constraints):
            return False
        if not _is_pure_time_range(self.select.where):
            return False
        if len(self.select.items) != 1:
            return False
        e = self.select.items[0].expr
        return (
            isinstance(e, S.FunctionCall)
            and e.name == "count"
            and (not e.args or isinstance(e.args[0], S.Star))
        )


def _substitute_aliases(e: S.Expr, aliases: dict[str, S.Expr]) -> S.Expr:
    if isinstance(e, S.Column) and e.name in aliases:
        return aliases[e.name]
    if isinstance(e, S.BinaryOp):
        return S.BinaryOp(
            e.op, _substitute_aliases(e.left, aliases), _substitute_aliases(e.right, aliases)
        )
    if isinstance(e, S.UnaryOp):
        return S.UnaryOp(e.op, _substitute_aliases(e.operand, aliases))
    if isinstance(e, S.FunctionCall):
        return S.FunctionCall(
            e.name, [_substitute_aliases(a, aliases) for a in e.args], e.distinct
        )
    if isinstance(e, S.Cast):
        return S.Cast(_substitute_aliases(e.expr, aliases), e.type_name)
    return e


def plan(select: S.Select) -> LogicalPlan:
    if select.table is None:
        raise S.SqlError("query has no FROM table")

    # GROUP BY / ORDER BY / HAVING may reference select aliases
    # (e.g. `SELECT date_bin(...) AS b ... GROUP BY b`): inline them
    aliases = {
        item.alias: item.expr
        for item in select.items
        if item.alias is not None and not isinstance(item.expr, S.Star)
    }
    if aliases:
        # ORDER BY aliases resolve against the *output* table, so they stay;
        # GROUP BY / HAVING run input-side and need the real expressions
        select.group_by = [_substitute_aliases(g, aliases) for g in select.group_by]
        if select.having is not None:
            select.having = _substitute_aliases(select.having, aliases)

    bounds = extract_time_bounds(select.where)
    constraints = extract_column_constraints(select.where)

    needed: set[str] | None = set()
    ts_artificial = False
    for item in select.items:
        if isinstance(item.expr, S.Star):
            needed = None
            break
        needed |= referenced_columns(item.expr)
    if needed is not None:
        needed |= referenced_columns(select.where)
        for g in select.group_by:
            needed |= referenced_columns(g)
        needed |= referenced_columns(select.having)
        # ORDER BY resolves select ALIASES against the output table; an
        # alias name is not an input column (it would poison column-pruned
        # scans and encoded-cache lookups with a phantom column)
        alias_names = {i.alias for i in select.items if i.alias}
        for o in select.order_by:
            needed |= referenced_columns(o.expr) - alias_names
        # engines row-filter by time bounds themselves (scan tables arrive
        # unfiltered so device encodings stay query-independent)
        ts_artificial = DEFAULT_TIMESTAMP_KEY not in needed
        needed.add(DEFAULT_TIMESTAMP_KEY)

    is_agg = bool(select.group_by) or any(S.is_aggregate(i.expr) for i in select.items)
    return LogicalPlan(
        select=select,
        stream=select.table,
        time_bounds=bounds,
        constraints=constraints,
        needed_columns=needed,
        is_aggregate=is_agg,
        ts_artificial=ts_artificial,
    )
