"""CPU query executor over pyarrow.compute — the measured baseline engine.

Structure mirrors what the TPU backend needs: scans produce tables, each
table contributes a *partial aggregate*, partials merge associatively, and a
finalize step evaluates the select list. The TPU engine (ops/, executor_tpu)
plugs into the same frame with device kernels producing the partials — and a
mesh psum replacing the host merge loop in distributed mode.

Reference analogue: DataFusion physical operators under src/query/mod.rs.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from datetime import UTC, datetime, timedelta
from typing import Any, Iterator

import pyarrow as pa
import pyarrow.compute as pc

from parseable_tpu.query import sql as S
from parseable_tpu.query.planner import LogicalPlan
from parseable_tpu.utils.timeutil import parse_duration


class ExecError(ValueError):
    pass


class QueryTimeout(ExecError):
    """Cooperative SQL timeout (reference: QUERY_RUNTIME sql timeout,
    src/query/mod.rs:92,152-165). Raised between scan blocks once the
    plan's deadline passes."""


class MemoryLimitExceeded(ExecError):
    """Result materialization exceeded the query memory cap (reference:
    85% memory pool / P_QUERY_MEMORY_LIMIT, src/query/mod.rs:216-226)."""


# ------------------------------------------------------------- expression eval


def _interval_to_timedelta(text: str) -> timedelta:
    return parse_duration(text)


def evaluate(e: S.Expr, table: pa.Table) -> Any:
    """Evaluate a scalar (non-aggregate) expression -> Array or python scalar."""
    if isinstance(e, S.Literal):
        return e.value
    if isinstance(e, S.Column):
        # qualified refs resolve against join-output columns ("alias.col")
        if e.table is not None and f"{e.table}.{e.name}" in table.column_names:
            return table.column(f"{e.table}.{e.name}").combine_chunks()
        if e.name not in table.column_names:
            return pa.nulls(table.num_rows)
        return table.column(e.name).combine_chunks()
    if isinstance(e, S.Star):
        raise ExecError("'*' outside count()")
    if isinstance(e, S.IntervalLit):
        return _interval_to_timedelta(e.text)
    if isinstance(e, S.UnaryOp):
        v = evaluate(e.operand, table)
        if e.op == "-":
            return pc.negate(_arr(v, table)) if _is_arr(v) else -v
        if e.op == "not":
            return pc.invert(_arr(v, table))
        raise ExecError(f"unknown unary op {e.op}")
    if isinstance(e, S.BinaryOp):
        return _eval_binary(e, table)
    if isinstance(e, S.InList):
        arr = _arr(evaluate(e.expr, table), table)
        values = [i.value if isinstance(i, S.Literal) else evaluate(i, table) for i in e.items]
        mask = pc.is_in(arr, value_set=pa.array(values))
        return pc.invert(mask) if e.negated else mask
    if isinstance(e, S.Between):
        arr = _arr(evaluate(e.expr, table), table)
        lo = _coerce_scalar(evaluate(e.low, table), arr.type)
        hi = _coerce_scalar(evaluate(e.high, table), arr.type)
        mask = pc.and_(pc.greater_equal(arr, lo), pc.less_equal(arr, hi))
        return pc.invert(mask) if e.negated else mask
    if isinstance(e, S.IsNull):
        arr = _arr(evaluate(e.expr, table), table)
        return pc.is_valid(arr) if e.negated else pc.is_null(arr)
    if isinstance(e, S.Cast):
        return _eval_cast(e, table)
    if isinstance(e, S.Case):
        return _eval_case(e, table)
    if isinstance(e, S.FunctionCall):
        return _eval_function(e, table)
    raise ExecError(f"cannot evaluate {e!r}")


def _is_arr(v: Any) -> bool:
    return isinstance(v, (pa.Array, pa.ChunkedArray))


def _arr(v: Any, table: pa.Table) -> pa.Array:
    if isinstance(v, pa.ChunkedArray):
        return v.combine_chunks()
    if isinstance(v, pa.Array):
        return v
    return pa.array([v] * table.num_rows)


def _coerce_scalar(v: Any, t: pa.DataType) -> Any:
    if pa.types.is_timestamp(t):
        if isinstance(v, str):
            from parseable_tpu.utils.timeutil import parse_rfc3339

            return pa.scalar(parse_rfc3339(v).replace(tzinfo=None), type=t)
        if isinstance(v, datetime):
            return pa.scalar(v.replace(tzinfo=None) if v.tzinfo else v, type=t)
    return v


def _eval_binary(e: S.BinaryOp, table: pa.Table) -> Any:
    op = e.op
    if op in ("and", "or"):
        l = _arr(evaluate(e.left, table), table)
        r = _arr(evaluate(e.right, table), table)
        return pc.and_kleene(l, r) if op == "and" else pc.or_kleene(l, r)
    if op in ("like", "ilike", "not_like", "not_ilike"):
        arr = _arr(evaluate(e.left, table), table)
        pattern = evaluate(e.right, table)
        if not isinstance(pattern, str):
            raise ExecError("LIKE pattern must be a string literal")
        mask = pc.match_like(arr, pattern, ignore_case="ilike" in op)
        return pc.invert(mask) if op.startswith("not_") else mask
    if op == "||":
        l = _arr(evaluate(e.left, table), table)
        r = _arr(evaluate(e.right, table), table)
        return pc.binary_join_element_wise(pc.cast(l, pa.string()), pc.cast(r, pa.string()), "")

    lv = evaluate(e.left, table)
    rv = evaluate(e.right, table)
    # timestamp +/- interval
    if isinstance(rv, timedelta) and op in ("+", "-"):
        arr = _arr(lv, table)
        delta = pa.scalar(rv, type=pa.duration("ms"))
        return pc.add(arr, delta) if op == "+" else pc.subtract(arr, delta)
    larr = _is_arr(lv)
    rarr = _is_arr(rv)
    if not larr and not rarr:
        return _python_binop(op, lv, rv)
    a = _arr(lv, table) if larr else lv
    b = _arr(rv, table) if rarr else rv
    # coerce scalar side for timestamp comparisons
    if larr and not rarr:
        b = _coerce_scalar(b, a.type)
    if rarr and not larr:
        a = _coerce_scalar(a, b.type)
    fns = {
        "+": pc.add,
        "-": pc.subtract,
        "*": pc.multiply,
        "/": pc.divide,
        "%": lambda x, y: pc.subtract(x, pc.multiply(pc.floor(pc.divide(x, y)), y)),
        "=": pc.equal,
        "!=": pc.not_equal,
        "<": pc.less,
        "<=": pc.less_equal,
        ">": pc.greater,
        ">=": pc.greater_equal,
    }
    if op not in fns:
        raise ExecError(f"unknown operator {op}")
    return fns[op](a, b)


def _python_binop(op: str, a: Any, b: Any) -> Any:
    import operator

    fns = {
        "+": operator.add, "-": operator.sub, "*": operator.mul,
        "/": operator.truediv, "%": operator.mod, "=": operator.eq,
        "!=": operator.ne, "<": operator.lt, "<=": operator.le,
        ">": operator.gt, ">=": operator.ge,
    }
    return fns[op](a, b)


_CAST_TYPES = {
    "int": pa.int64(), "integer": pa.int64(), "bigint": pa.int64(),
    "float": pa.float64(), "double": pa.float64(), "real": pa.float64(),
    "text": pa.string(), "varchar": pa.string(), "string": pa.string(),
    "bool": pa.bool_(), "boolean": pa.bool_(),
    "timestamp": pa.timestamp("ms"), "date": pa.date32(),
}


def _eval_cast(e: S.Cast, table: pa.Table) -> Any:
    v = evaluate(e.expr, table)
    t = _CAST_TYPES.get(e.type_name)
    if t is None:
        raise ExecError(f"unknown cast type {e.type_name}")
    if _is_arr(v):
        return pc.cast(_arr(v, table), t, safe=False)
    return pa.scalar(v, type=t).as_py() if v is not None else None


def _eval_case(e: S.Case, table: pa.Table) -> Any:
    result = None
    if e.else_expr is not None:
        result = _arr(evaluate(e.else_expr, table), table)
    for cond, then in reversed(e.whens):
        mask = _arr(evaluate(cond, table), table)
        then_v = _arr(evaluate(then, table), table)
        if result is None:
            result = pc.if_else(mask, then_v, pa.nulls(table.num_rows, then_v.type))
        else:
            result = pc.if_else(mask, then_v, result)
    return result


def date_bin(interval: timedelta, arr: pa.Array, origin: datetime | None = None) -> pa.Array:
    """Floor timestamps to interval buckets (DataFusion date_bin parity)."""
    step_ms = int(interval.total_seconds() * 1000)
    if step_ms <= 0:
        raise ExecError("date_bin interval must be positive")
    origin_ms = int(origin.timestamp() * 1000) if origin else 0
    ints = pc.cast(arr, pa.int64())
    binned = pc.add(
        pc.multiply(
            pc.floor(pc.divide(pc.cast(pc.subtract(ints, origin_ms), pa.float64()), step_ms)),
            float(step_ms),
        ),
        float(origin_ms),
    )
    return pc.cast(pc.cast(binned, pa.int64()), arr.type)


def _eval_function(e: S.FunctionCall, table: pa.Table) -> Any:
    name = e.name
    if name == "date_bin":
        if len(e.args) < 2:
            raise ExecError("date_bin(interval, column[, origin])")
        interval = evaluate(e.args[0], table)
        if not isinstance(interval, timedelta):
            interval = _interval_to_timedelta(str(interval))
        arr = _arr(evaluate(e.args[1], table), table)
        origin = None
        if len(e.args) > 2:
            o = evaluate(e.args[2], table)
            if isinstance(o, str):
                from parseable_tpu.utils.timeutil import parse_rfc3339

                origin = parse_rfc3339(o)
        return date_bin(interval, arr, origin)
    if name == "date_trunc":
        if len(e.args) != 2:
            raise ExecError("date_trunc(unit, column)")
        unit = evaluate(e.args[0], table)
        arr = _arr(evaluate(e.args[1], table), table)
        return pc.floor_temporal(arr, unit=str(unit).lower())
    if name == "to_timestamp" or name == "to_timestamp_millis":
        v = evaluate(e.args[0], table)
        if _is_arr(v):
            return pc.cast(_arr(v, table), pa.timestamp("ms"), safe=False)
        from parseable_tpu.utils.timeutil import parse_rfc3339

        return parse_rfc3339(v).replace(tzinfo=None) if isinstance(v, str) else v
    if name in ("lower", "upper", "length", "abs", "floor", "ceil", "trim"):
        arr = _arr(evaluate(e.args[0], table), table)
        fn = {
            "lower": pc.utf8_lower, "upper": pc.utf8_upper,
            "length": pc.utf8_length, "abs": pc.abs, "floor": pc.floor,
            "ceil": pc.ceil, "trim": pc.utf8_trim_whitespace,
        }[name]
        return fn(arr)
    if name == "round":
        arr = _arr(evaluate(e.args[0], table), table)
        digits = evaluate(e.args[1], table) if len(e.args) > 1 else 0
        return pc.round(arr, ndigits=int(digits))
    if name == "coalesce":
        args = [_arr(evaluate(a, table), table) for a in e.args]
        out = args[0]
        for nxt in args[1:]:
            out = pc.if_else(pc.is_valid(out), out, nxt)
        return out
    if name == "now":
        return datetime.now(UTC).replace(tzinfo=None)
    if name in ("regexp_match", "regexp_like"):
        arr = _arr(evaluate(e.args[0], table), table)
        pattern = evaluate(e.args[1], table)
        return pc.match_substring_regex(arr, str(pattern))
    if name == "strpos":
        arr = _arr(evaluate(e.args[0], table), table)
        sub = evaluate(e.args[1], table)
        return pc.add(pc.find_substring(arr, str(sub)), 1)
    # --- DataFusion-parity scalar surface (dashboards/alerts use these;
    # the reference gets them from DataFusion's function library) ---------
    if name in ("substr", "substring"):
        arr = _arr(evaluate(e.args[0], table), table)
        start = int(evaluate(e.args[1], table)) - 1  # SQL is 1-based
        if len(e.args) > 2:
            length = int(evaluate(e.args[2], table))
            return pc.utf8_slice_codeunits(arr, max(start, 0), max(start, 0) + length)
        return pc.utf8_slice_codeunits(arr, max(start, 0))
    if name == "replace":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.replace_substring(
            arr, str(evaluate(e.args[1], table)), str(evaluate(e.args[2], table))
        )
    if name == "concat":
        parts = [
            pc.cast(_arr(evaluate(a, table), table), pa.string()) for a in e.args
        ]
        # SQL concat skips NULLs (unlike ||): substitute empty strings
        parts = [pc.fill_null(x, "") for x in parts]
        return pc.binary_join_element_wise(*parts, "")
    if name == "concat_ws":
        sep = str(evaluate(e.args[0], table))
        parts = [
            pc.fill_null(pc.cast(_arr(evaluate(a, table), table), pa.string()), "")
            for a in e.args[1:]
        ]
        return pc.binary_join_element_wise(*parts, sep)
    if name == "split_part":
        import numpy as np

        arr = _arr(evaluate(e.args[0], table), table)
        sep = str(evaluate(e.args[1], table))
        idx = int(evaluate(e.args[2], table))
        # SQL split_part returns '' past the last part (list_element would
        # raise); slice the wanted element per row via list offsets
        split = pc.list_slice(pc.split_pattern(arr, sep), start=idx - 1, stop=idx)
        if isinstance(split, pa.ChunkedArray):
            split = split.combine_chunks()
        offsets = np.asarray(split.offsets)
        lens = np.diff(offsets)
        flat = split.flatten()
        take = np.where(lens > 0, offsets[:-1], 0)
        vals = flat.take(pa.array(np.clip(take, 0, max(len(flat) - 1, 0))))
        nulls = pc.is_null(arr).to_numpy(zero_copy_only=False)
        out = pc.if_else(pa.array(lens > 0), vals, pa.scalar("", pa.string()))
        return pc.if_else(pa.array(~nulls), out, pa.scalar(None, pa.string()))
    if name in ("extract", "date_part"):
        unit = str(evaluate(e.args[0], table)).lower()
        arr = _arr(evaluate(e.args[1], table), table)
        fns = {
            "year": pc.year, "month": pc.month, "day": pc.day,
            "hour": pc.hour, "minute": pc.minute, "second": pc.second,
            "dow": pc.day_of_week, "doy": pc.day_of_year,
            "week": pc.iso_week, "quarter": pc.quarter,
            "millisecond": pc.millisecond,
        }
        if unit not in fns:
            raise ExecError(f"unknown {name} unit {unit!r}")
        return pc.cast(fns[unit](arr), pa.int64())
    if name in ("char_length", "character_length"):
        return pc.utf8_length(_arr(evaluate(e.args[0], table), table))
    if name == "ltrim":
        return pc.utf8_ltrim_whitespace(_arr(evaluate(e.args[0], table), table))
    if name == "rtrim":
        return pc.utf8_rtrim_whitespace(_arr(evaluate(e.args[0], table), table))
    if name == "left":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.utf8_slice_codeunits(arr, 0, int(evaluate(e.args[1], table)))
    if name == "right":
        arr = _arr(evaluate(e.args[0], table), table)
        k = int(evaluate(e.args[1], table))
        # the slice kernel wants scalar offsets; reverse+left+reverse gives
        # per-row tails in three vectorized kernels
        rev = pc.utf8_reverse(arr)
        return pc.utf8_reverse(pc.utf8_slice_codeunits(rev, 0, k))
    if name == "repeat":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.binary_repeat(arr, int(evaluate(e.args[1], table)))
    if name == "reverse":
        return pc.utf8_reverse(_arr(evaluate(e.args[0], table), table))
    if name in ("lpad", "rpad"):
        arr = _arr(evaluate(e.args[0], table), table)
        width = int(evaluate(e.args[1], table))
        padchar = str(evaluate(e.args[2], table)) if len(e.args) > 2 else " "
        fn = pc.utf8_lpad if name == "lpad" else pc.utf8_rpad
        return fn(arr, width, padding=padchar)
    if name == "starts_with":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.starts_with(arr, str(evaluate(e.args[1], table)))
    if name == "ends_with":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.ends_with(arr, str(evaluate(e.args[1], table)))
    if name == "contains":
        arr = _arr(evaluate(e.args[0], table), table)
        return pc.match_substring(arr, str(evaluate(e.args[1], table)))
    if name == "nullif":
        a = _arr(evaluate(e.args[0], table), table)
        b = evaluate(e.args[1], table)
        b_arr = _arr(b, table)
        eq = pc.fill_null(pc.equal(a, b_arr), False)
        return pc.if_else(eq, pa.nulls(table.num_rows, a.type), a)
    if name in ("greatest", "least"):
        parts = [_arr(evaluate(a, table), table) for a in e.args]
        fn = pc.max_element_wise if name == "greatest" else pc.min_element_wise
        return fn(*parts)
    if name in ("power", "pow"):
        a = _arr(evaluate(e.args[0], table), table)
        return pc.power(pc.cast(a, pa.float64()), float(evaluate(e.args[1], table)))
    if name in ("sqrt", "exp", "ln", "log10", "sign", "sin", "cos", "tan"):
        arr = pc.cast(_arr(evaluate(e.args[0], table), table), pa.float64())
        fn = {
            "sqrt": pc.sqrt, "exp": pc.exp, "ln": pc.ln, "log10": pc.log10,
            "sign": pc.sign, "sin": pc.sin, "cos": pc.cos, "tan": pc.tan,
        }[name]
        return fn(arr)
    if name == "log":
        # log(x) = ln, log(base, x) = logb
        if len(e.args) == 1:
            return pc.ln(pc.cast(_arr(evaluate(e.args[0], table), table), pa.float64()))
        base = float(evaluate(e.args[0], table))
        arr = pc.cast(_arr(evaluate(e.args[1], table), table), pa.float64())
        return pc.logb(arr, base)
    if name == "mod":
        a = _arr(evaluate(e.args[0], table), table)
        b = evaluate(e.args[1], table)
        return _eval_binary(S.BinaryOp("%", e.args[0], e.args[1]), table)
    if name == "trunc":
        return pc.trunc(pc.cast(_arr(evaluate(e.args[0], table), table), pa.float64()))
    if name == "pi":
        return math.pi
    if name == "md5":
        import hashlib as _hl

        arr = _arr(evaluate(e.args[0], table), table)
        return pa.array(
            [
                _hl.md5(v.encode()).hexdigest() if v is not None else None
                for v in arr.to_pylist()
            ]
        )
    raise ExecError(f"unknown function {name}")


# ---------------------------------------------------------------- aggregation


@dataclass
class AggSpec:
    func: str  # count | count_star | sum | min | max | avg | count_distinct
    arg: S.Expr | None
    out_name: str
    param: float | None = None  # percentile for approx_percentile_cont


def _collect_aggs(e: S.Expr, out: list[AggSpec], counter: list[int]) -> S.Expr:
    """Replace aggregate calls in `e` with Column refs to computed agg slots;
    append specs to `out`. Returns the rewritten expression."""
    if isinstance(e, S.FunctionCall) and e.name in S.AGGREGATE_FUNCS:
        func = e.name
        arg: S.Expr | None = None
        if func == "count" and (not e.args or isinstance(e.args[0], S.Star)):
            func = "count_star"
        elif e.args:
            arg = e.args[0]
        # approx_distinct keeps its own func: HLL register estimate
        # (ops/hll_sketch.py) in both engines — device-native and
        # mesh-mergeable where exact distinct would blow the bitmap budget
        param: float | None = None
        if func == "approx_percentile_cont":
            func = "percentile"
            if len(e.args) != 2 or not isinstance(e.args[1], S.Literal):
                raise ExecError(
                    "approx_percentile_cont takes (column, percentile-literal)"
                )
            pv = e.args[1].value
            if not isinstance(pv, (int, float)) or isinstance(pv, bool):
                raise ExecError("percentile must be a numeric literal")
            param = float(pv)
            if not 0.0 <= param <= 1.0:
                raise ExecError("percentile must be between 0 and 1")
        elif func == "approx_median":
            func = "percentile"
            if len(e.args) != 1:
                raise ExecError("approx_median takes exactly one argument")
            param = 0.5
        slot = f"__agg{counter[0]}"
        counter[0] += 1
        out.append(AggSpec(func, arg, slot, param=param))
        return S.Column(slot)
    if isinstance(e, S.BinaryOp):
        return S.BinaryOp(e.op, _collect_aggs(e.left, out, counter), _collect_aggs(e.right, out, counter))
    if isinstance(e, S.UnaryOp):
        return S.UnaryOp(e.op, _collect_aggs(e.operand, out, counter))
    if isinstance(e, S.Cast):
        return S.Cast(_collect_aggs(e.expr, out, counter), e.type_name)
    if isinstance(e, S.Case):
        return S.Case(
            [(_collect_aggs(w, out, counter), _collect_aggs(t, out, counter)) for w, t in e.whens],
            _collect_aggs(e.else_expr, out, counter) if e.else_expr else None,
        )
    if isinstance(e, S.WindowCall):
        # windows over aggregate output (`rank() OVER (ORDER BY sum(b))`):
        # the aggregate inputs rewrite to slots; the window itself
        # evaluates post-aggregation over the interim table
        return S.WindowCall(
            e.name,
            [_collect_aggs(a, out, counter) for a in e.args],
            [_collect_aggs(p, out, counter) for p in e.partition_by],
            [S.OrderItem(_collect_aggs(o.expr, out, counter), o.desc) for o in e.order_by],
            e.frame,
        )
    return e


@dataclass
class GroupState:
    count: list[int]
    sums: list[float]
    mins: list[Any]
    maxs: list[Any]
    distincts: list[set]
    sumsqs: list[float]
    sketches: list[Any]  # QuantileSketch | None per spec
    hlls: list[Any]  # approx_distinct uint8[HLL_M] registers | None per spec


class HashAggregator:
    """Streaming partial aggregation keyed by group tuples.

    `update(table)` folds one table in; `merge(other)` combines partials
    (used by the distributed tree); `finalize()` emits one row per group.
    """

    def __init__(self, group_exprs: list[S.Expr], specs: list[AggSpec]):
        self.group_exprs = group_exprs
        self.specs = specs
        self.groups: dict[tuple, GroupState] = {}

    def _new_state(self) -> GroupState:
        n = len(self.specs)
        return GroupState(
            count=[0] * n,
            sums=[0.0] * n,
            mins=[None] * n,
            maxs=[None] * n,
            distincts=[set() for _ in range(n)],
            sumsqs=[0.0] * n,
            sketches=[None] * n,
            hlls=[None] * n,
        )

    def update(self, table: pa.Table, mask: pa.Array | None = None) -> None:
        """Vectorized partial aggregation via pyarrow group_by (the hash
        aggregate runs in Arrow's C++ kernels; only the per-*group* merge is
        Python)."""
        if mask is not None:
            table = table.filter(mask)
        if table.num_rows == 0:
            return
        n = table.num_rows
        cols: dict[str, pa.Array] = {}
        key_names = []
        for i, g in enumerate(self.group_exprs):
            key_names.append(f"__k{i}")
            cols[f"__k{i}"] = _arr(evaluate(g, table), table)
        aggs: list[tuple[str, str]] = []
        for si, spec in enumerate(self.specs):
            if spec.func == "count_star":
                continue
            cols[f"__a{si}"] = _arr(evaluate(spec.arg, table), table)
            if spec.func in ("sum", "avg"):
                aggs.append((f"__a{si}", "sum"))
                aggs.append((f"__a{si}", "count"))
            elif spec.func in ("stddev", "var"):
                # float64 before squaring: int64 squares wrap silently
                fv = pc.cast(cols[f"__a{si}"], pa.float64(), safe=False)
                cols[f"__asq{si}"] = pc.multiply(fv, fv)
                aggs.append((f"__a{si}", "sum"))
                aggs.append((f"__a{si}", "count"))
                aggs.append((f"__asq{si}", "sum"))
            elif spec.func == "min":
                aggs.append((f"__a{si}", "min"))
            elif spec.func == "max":
                aggs.append((f"__a{si}", "max"))
            elif spec.func == "count":
                aggs.append((f"__a{si}", "count"))
        aggs.append(([], "count_all"))
        tmp = pa.table(cols) if cols else pa.table({"__dummy": pa.nulls(n, pa.int8())})
        grouped = tmp.group_by(key_names, use_threads=False).aggregate(aggs)

        gcols = {name: grouped.column(name).to_pylist() for name in grouped.column_names}
        keys_lists = [gcols[k] for k in key_names]
        rows_out = len(grouped)
        for r in range(rows_out):
            key = tuple(kl[r] for kl in keys_lists)
            st = self.groups.get(key)
            if st is None:
                st = self._new_state()
                self.groups[key] = st
            for si, spec in enumerate(self.specs):
                if spec.func == "count_star":
                    st.count[si] += gcols["count_all"][r]
                elif spec.func in ("sum", "avg"):
                    st.count[si] += gcols[f"__a{si}_count"][r]
                    s = gcols[f"__a{si}_sum"][r]
                    if s is not None:
                        st.sums[si] += s
                elif spec.func in ("stddev", "var"):
                    st.count[si] += gcols[f"__a{si}_count"][r]
                    s = gcols[f"__a{si}_sum"][r]
                    if s is not None:
                        st.sums[si] += s
                    sq = gcols[f"__asq{si}_sum"][r]
                    if sq is not None:
                        st.sumsqs[si] += sq
                elif spec.func == "min":
                    v = gcols[f"__a{si}_min"][r]
                    if v is not None:
                        st.count[si] += 1
                        st.mins[si] = v if st.mins[si] is None else min(st.mins[si], v)
                elif spec.func == "max":
                    v = gcols[f"__a{si}_max"][r]
                    if v is not None:
                        st.count[si] += 1
                        st.maxs[si] = v if st.maxs[si] is None else max(st.maxs[si], v)
                elif spec.func == "count":
                    st.count[si] += gcols[f"__a{si}_count"][r]

        # percentile sketches: one argsort over combined group codes gives
        # contiguous per-group value slices; per-GROUP python only
        pct_specs = [si for si, s in enumerate(self.specs) if s.func == "percentile"]
        if pct_specs:
            import numpy as np

            from parseable_tpu.query.partials import (
                _FastPathUnavailable,
                _combine_codes,
                _encode_key,
            )
            from parseable_tpu.query.sketch import QuantileSketch

            combined: np.ndarray | None = None
            if key_names:
                try:
                    codes_list, sizes = [], []
                    for k in key_names:
                        codes, d = _encode_key(tmp.column(k))
                        codes_list.append(codes)
                        sizes.append(len(d) + 1)
                    combined = _combine_codes(codes_list, sizes)
                except _FastPathUnavailable:
                    # un-encodable key type or code-space overflow: factorize
                    # row tuples in Python (rare; correctness over speed)
                    tuples = list(
                        zip(*[tmp.column(k).to_pylist() for k in key_names])
                    )
                    index: dict = {}
                    combined = np.fromiter(
                        (index.setdefault(tp, len(index)) for tp in tuples),
                        np.int64,
                        n,
                    )
            else:
                combined = np.zeros(n, np.int64)
            order = np.argsort(combined, kind="stable")
            sorted_codes = combined[order]
            starts = np.flatnonzero(
                np.r_[True, sorted_codes[1:] != sorted_codes[:-1]]
            )
            bounds = np.r_[starts, n]
            # one key tuple per GROUP (first row of each slice), never per row
            first_rows = (
                tmp.select(key_names)
                .take(pa.array(order[starts]))
                .to_pylist()
                if key_names
                else [{} for _ in starts]
            )
            for si in pct_specs:
                col = tmp.column(f"__a{si}")
                vals = np.asarray(
                    pc.cast(col, pa.float64(), safe=False).to_numpy(
                        zero_copy_only=False
                    )
                )
                sorted_vals = vals[order]
                for bi in range(len(starts)):
                    s, e = bounds[bi], bounds[bi + 1]
                    key = tuple(first_rows[bi][k] for k in key_names)
                    st = self.groups.get(key)
                    if st is None:
                        st = self._new_state()
                        self.groups[key] = st
                    if st.sketches[si] is None:
                        st.sketches[si] = QuantileSketch()
                    st.sketches[si].update(sorted_vals[s:e])
                    st.count[si] = st.sketches[si].count

        # distinct: unique (keys, value) combos per chunk -> host sets
        # (exact) or HLL registers (approx_distinct; hashing the uniques
        # is equivalent to hashing every row)
        for si, spec in enumerate(self.specs):
            if spec.func not in ("count_distinct", "approx_distinct"):
                continue
            sel = key_names + [f"__a{si}"]
            uniq = tmp.select(sel).group_by(sel, use_threads=False).aggregate([])
            ucols = {name: uniq.column(name).to_pylist() for name in uniq.column_names}
            approx = spec.func == "approx_distinct"
            if approx:
                from parseable_tpu.ops.hll_sketch import registers_add

            for r in range(len(uniq)):
                key = tuple(ucols[k][r] for k in key_names)
                v = ucols[f"__a{si}"][r]
                if v is None:
                    continue
                st = self.groups.get(key)
                if st is None:
                    st = self._new_state()
                    self.groups[key] = st
                if approx:
                    st.hlls[si] = registers_add(st.hlls[si], (v,))
                else:
                    st.distincts[si].add(v)

    @staticmethod
    def _copy_state(st: GroupState) -> GroupState:
        """Own copy of a donor's state: merge must never alias the source
        (a twice-merged or reused donor would otherwise be mutated)."""
        return GroupState(
            count=list(st.count),
            sums=list(st.sums),
            mins=list(st.mins),
            maxs=list(st.maxs),
            distincts=[set(s) for s in st.distincts],
            sumsqs=list(st.sumsqs),
            sketches=[sk.copy() if sk is not None else None for sk in st.sketches],
            hlls=[h.copy() if h is not None else None for h in st.hlls],
        )

    def merge(self, other: "HashAggregator") -> None:
        for key, st in other.groups.items():
            mine = self.groups.get(key)
            if mine is None:
                self.groups[key] = self._copy_state(st)
                continue
            for si, spec in enumerate(self.specs):
                mine.count[si] += st.count[si]
                mine.sums[si] += st.sums[si]
                mine.sumsqs[si] += st.sumsqs[si]
                for attr, fn in (("mins", min), ("maxs", max)):
                    a = getattr(mine, attr)[si]
                    b = getattr(st, attr)[si]
                    getattr(mine, attr)[si] = b if a is None else (a if b is None else fn(a, b))
                mine.distincts[si] |= st.distincts[si]
                if st.hlls[si] is not None:
                    from parseable_tpu.ops.hll_sketch import merge_registers

                    # merge_registers copies on the None path: registers_add
                    # mutates in place and the donor must stay untouched
                    mine.hlls[si] = merge_registers(mine.hlls[si], st.hlls[si])
                if st.sketches[si] is not None:
                    if mine.sketches[si] is None:
                        mine.sketches[si] = st.sketches[si].copy()
                    else:
                        mine.sketches[si].merge(st.sketches[si])
                    mine.count[si] = mine.sketches[si].count

    def merge_raw(
        self,
        key: tuple,
        counts: list[int],
        sums: list[float],
        mins: list,
        maxs: list,
        distincts: dict[int, set] | None = None,
        sumsqs: list[float] | None = None,
        sketches: dict[int, Any] | None = None,
        hlls: dict[int, Any] | None = None,
    ) -> None:
        """Merge one group's partials produced by a device kernel.

        `distincts` maps spec index -> set of observed values (decoded from
        the device presence bitmap); `sumsqs` carries stddev/var sum-of-
        squares partials; `sketches` maps spec index -> QuantileSketch built
        from the device histogram — so device blocks and CPU-fallback
        blocks merge exactly."""
        st = self.groups.get(key)
        if st is None:
            st = self._new_state()
            self.groups[key] = st
        for si in range(len(self.specs)):
            st.count[si] += counts[si]
            st.sums[si] += sums[si]
            if sumsqs is not None:
                st.sumsqs[si] += sumsqs[si]
            for attr, vals, fn in (("mins", mins, min), ("maxs", maxs, max)):
                a = getattr(st, attr)[si]
                b = vals[si]
                getattr(st, attr)[si] = b if a is None else (a if b is None else fn(a, b))
        if distincts:
            for si, vals_set in distincts.items():
                st.distincts[si] |= vals_set
        if hlls:
            import numpy as np

            # merge_raw takes OWNERSHIP of the register arrays (its only
            # callers hand over freshly materialized device readbacks), so
            # the None-sided path adopts without the defensive copy
            for si, regs in hlls.items():
                if st.hlls[si] is None:
                    st.hlls[si] = regs
                else:
                    np.maximum(st.hlls[si], regs, out=st.hlls[si])
        if sketches:
            for si, sk in sketches.items():
                if st.sketches[si] is None:
                    st.sketches[si] = sk
                else:
                    st.sketches[si].merge(sk)
                st.count[si] = st.sketches[si].count

    def finalize_value(self, st: GroupState, si: int) -> Any:
        spec = self.specs[si]
        if spec.func in ("count_star", "count"):
            return st.count[si]
        if spec.func == "sum":
            return st.sums[si] if st.count[si] else None
        if spec.func == "avg":
            return st.sums[si] / st.count[si] if st.count[si] else None
        if spec.func == "min":
            return st.mins[si]
        if spec.func == "max":
            return st.maxs[si]
        if spec.func == "count_distinct":
            return len(st.distincts[si])
        if spec.func == "approx_distinct":
            if st.hlls[si] is None:
                return 0
            from parseable_tpu.ops.hll_sketch import estimate

            return int(round(estimate(st.hlls[si])))
        if spec.func in ("stddev", "var"):
            # sample variance (n-1 denominator, DataFusion semantics)
            n = st.count[si]
            if n < 2:
                return None
            var = (st.sumsqs[si] - st.sums[si] ** 2 / n) / (n - 1)
            var = max(0.0, var)  # guard f.p. negatives
            return math.sqrt(var) if spec.func == "stddev" else var
        if spec.func == "percentile":
            sk = st.sketches[si]
            if sk is None:
                return None
            return sk.quantile(spec.param if spec.param is not None else 0.5)
        raise ExecError(f"unknown aggregate {spec.func}")


# ------------------------------------------------------------------- executor


class QueryExecutor:
    """Execute a LogicalPlan over an iterator of tables (CPU engine)."""

    # set by the session when the query is result-cache eligible: receives
    # the merged interim (finalized partials) the moment the scan has been
    # fully reduced, before HAVING/projection/ORDER BY run. Every engine
    # (CPU two-phase, classic hash aggregate, TPU dense fold) funnels its
    # interim through finalize_from_interim, so one hook covers them all.
    interim_sink = None
    # distributed pushdown hook (query/fanout.py): called after the local
    # scan's blocks have all reduced, returns the peers' partial tables to
    # fold into the same merge — collection happens here, not earlier, so
    # peer execution overlaps the local scan instead of preceding it
    partials_source = None

    def __init__(self, plan: LogicalPlan):
        self.plan = plan

    # -- shared pieces -------------------------------------------------------

    def _check_deadline(self) -> None:
        """Cooperative timeout, checked once per scan block."""
        import time as _time

        dl = getattr(self.plan, "deadline", None)
        if dl is not None and _time.monotonic() > dl:
            raise QueryTimeout("query exceeded its timeout and was cancelled")

    def _memory_budget(self) -> int | None:
        return getattr(self.plan, "memory_limit_bytes", None)

    def _where_mask(self, table: pa.Table) -> pa.Array | None:
        w = self.plan.select.where
        if w is None:
            return None
        mask = _arr(evaluate(w, table), table)
        if not pa.types.is_boolean(mask.type):
            raise ExecError("WHERE must be boolean")
        return mask

    def _bounds_filter(self, table: pa.Table) -> pa.Table:
        """Row-level time-bounds filter (scan tables arrive unfiltered so
        their device encodings stay query-independent)."""
        from parseable_tpu import DEFAULT_TIMESTAMP_KEY

        tb = self.plan.time_bounds
        if (tb.low is None and tb.high is None) or DEFAULT_TIMESTAMP_KEY not in table.column_names:
            return table
        col = table.column(DEFAULT_TIMESTAMP_KEY)
        mask = None
        if tb.low is not None:
            mask = pc.greater_equal(col, pa.scalar(tb.low.replace(tzinfo=None), type=col.type))
        if tb.high is not None:
            m2 = pc.less(col, pa.scalar(tb.high.replace(tzinfo=None), type=col.type))
            mask = m2 if mask is None else pc.and_(mask, m2)
        return table.filter(mask)

    def execute(self, tables: Iterator[pa.Table]) -> pa.Table:
        if self.plan.is_aggregate:
            return self._execute_aggregate(tables)
        return self._execute_select(tables)

    # -- plain select --------------------------------------------------------

    def _execute_select(self, tables: Iterator[pa.Table]) -> pa.Table:
        sel = self.plan.select
        if any(S.contains_window(i.expr) for i in sel.items) or any(
            S.contains_window(o.expr) for o in sel.order_by
        ):
            return self._execute_select_windows(tables)
        out_parts: list[pa.Table] = []
        rows_needed = None
        if sel.limit is not None and not sel.distinct:
            rows_needed = sel.limit + (sel.offset or 0)
        # top-K pushdown: with ORDER BY + LIMIT, periodically sort-compact
        # the working set down to the K needed rows instead of materializing
        # the whole scan (reference leans on DataFusion's sort-limit;
        # `SELECT * ... LIMIT 100` over 100 GB must not OOM)
        topk = rows_needed is not None and bool(sel.order_by)
        compact_at = max(2 * (rows_needed or 0), 100_000)
        budget = self._memory_budget()
        held_bytes = 0
        total = 0
        for table in tables:
            self._check_deadline()
            table = self._bounds_filter(table)
            mask = self._where_mask(table)
            if mask is not None:
                table = table.filter(mask)
            if table.num_rows == 0:
                continue
            part = self._project(table)
            out_parts.append(part)
            total += part.num_rows
            held_bytes += part.nbytes
            if rows_needed is not None and not sel.order_by and total >= rows_needed:
                break
            # compact on row count OR budget pressure — a tight memory cap
            # must trigger top-K compaction, not fail a bounded query
            if topk and (total >= compact_at or (budget is not None and held_bytes > budget)):
                compacted = self._sorted(_unify_parts(out_parts)).slice(0, rows_needed)
                out_parts = [compacted]
                total = compacted.num_rows
                held_bytes = compacted.nbytes
            if budget is not None and held_bytes > budget:
                raise MemoryLimitExceeded(
                    f"query holds {held_bytes} bytes of results "
                    f"(limit {budget}); add LIMIT/filters or raise P_QUERY_MEMORY_LIMIT"
                )
        if not out_parts:
            return self._project(_empty_like(self.plan))
        result = _unify_parts(out_parts)
        if sel.distinct:
            result = result.group_by(result.column_names).aggregate([])
        result = self._order_limit(result)
        return self._strip_order_carry(result)

    def _strip_order_carry(self, result: pa.Table) -> pa.Table:
        sel = self.plan.select
        if any(isinstance(i.expr, S.Star) for i in sel.items):
            return result
        declared = [i.alias or S.expr_name(i.expr) for i in sel.items]
        carried = [
            S.expr_name(o.expr)
            for o in sel.order_by
            if S.expr_name(o.expr) not in declared
        ]
        if not carried:
            return result
        keep = [c for c in result.column_names if c not in carried]
        return result.select(keep)

    def _execute_select_windows(self, tables: Iterator[pa.Table]) -> pa.Table:
        """Non-aggregate SELECT carrying window functions: materialize the
        filtered scan (windows need the whole input before any row's value
        is known), attach `__w{i}` columns, project with rewritten items.

        Reference parity: DataFusion WindowAggExec over the filtered scan
        (the reference gets this from src/query/mod.rs:212-276)."""
        from parseable_tpu.query import window as W

        sel = self.plan.select
        budget = self._memory_budget()
        held = 0
        parts: list[pa.Table] = []
        for table in tables:
            self._check_deadline()
            table = self._bounds_filter(table)
            mask = self._where_mask(table)
            if mask is not None:
                table = table.filter(mask)
            if table.num_rows == 0:
                continue
            parts.append(table)
            held += table.nbytes
            if budget is not None and held > budget:
                raise MemoryLimitExceeded(
                    f"window query holds {held} bytes of input (limit {budget}); "
                    "add filters or raise P_QUERY_MEMORY_LIMIT"
                )
        if not parts:
            full = _empty_like(self.plan)
        else:
            full = _unify_parts(parts)
        windows: list[S.WindowCall] = []
        for item in sel.items:
            windows.extend(W.window_calls(item.expr))
        for o in sel.order_by:
            windows.extend(W.window_calls(o.expr))
        aug, mapping = W.attach_window_columns(full, windows)
        items = [
            S.SelectItem(
                W.rewrite_windows(item.expr, mapping),
                item.alias or S.expr_name(item.expr),
            )
            for item in sel.items
        ]
        # ORDER BY may carry windows too (`ORDER BY row_number() OVER ...`):
        # rewrite them to the computed slots and sort under the rewritten
        # spec so _sorted never meets a raw WindowCall
        rewritten_order = [
            S.OrderItem(W.rewrite_windows(o.expr, mapping), o.desc) for o in sel.order_by
        ]
        names: list[str] = []
        arrays: list[pa.Array] = []
        for item in items:
            if isinstance(item.expr, S.Star):
                for name in aug.column_names:
                    if name.startswith("__w"):
                        continue  # window slots are not part of `*`
                    names.append(name)
                    arrays.append(aug.column(name).combine_chunks())
                continue
            names.append(item.alias)
            arrays.append(_arr(evaluate(item.expr, aug), aug))
        import copy as _copy

        shim = _copy.copy(sel)
        shim.order_by = rewritten_order
        prev_sel = self.plan.select
        self.plan.select = shim
        try:
            if not any(isinstance(i.expr, S.Star) for i in items):
                for nm in self._order_carry_names(names, aug):
                    for o in rewritten_order:
                        if S.expr_name(o.expr) == nm:
                            names.append(nm)
                            arrays.append(_arr(evaluate(o.expr, aug), aug))
                            break
            result = pa.table(_dedup(names, arrays))
            if sel.distinct:
                result = result.group_by(result.column_names).aggregate([])
            return self._strip_order_carry(self._order_limit(result))
        finally:
            self.plan.select = prev_sel

    def execute_select_stream(self, tables: Iterator[pa.Table]) -> Iterator[pa.Table]:
        """Stream filtered + projected blocks one at a time (reference:
        chunked streaming responses, handlers/http/query.rs:325-407).

        ORDER BY / DISTINCT / aggregates need the full result before the
        first row can be emitted, so those yield the materialized table.
        """
        sel = self.plan.select
        if (
            self.plan.is_aggregate
            or sel.order_by
            or sel.distinct
            or any(S.contains_window(i.expr) for i in sel.items)
        ):
            yield self.execute(tables)
            return
        # chunk emissions at the execution batch size (reference: DF batch
        # size, cli.rs:448-454) so response writes stay uniformly sized
        batch_rows = getattr(self.plan, "execution_batch_size", None) or 1 << 30
        to_skip = sel.offset or 0
        remaining = sel.limit  # None = unbounded
        for table in tables:
            self._check_deadline()
            table = self._bounds_filter(table)
            mask = self._where_mask(table)
            if mask is not None:
                table = table.filter(mask)
            if table.num_rows == 0:
                continue
            part = self._project(table)
            if to_skip:
                drop = min(to_skip, part.num_rows)
                part = part.slice(drop)
                to_skip -= drop
                if part.num_rows == 0:
                    continue
            if remaining is not None:
                part = part.slice(0, remaining)
                remaining -= part.num_rows
            for off in range(0, part.num_rows, batch_rows):
                chunk = part.slice(off, batch_rows)
                if chunk.num_rows:
                    yield chunk
            if remaining == 0:
                return

    def _order_carry_names(self, declared: list[str], table: pa.Table) -> list[str]:
        """ORDER BY columns the projection would drop: carried through the
        output under their own names so the final sort can see them, then
        stripped (`SELECT ms FROM t ORDER BY rn` must sort by rn, not by an
        all-null placeholder)."""
        from parseable_tpu.query.planner import referenced_columns

        sel = self.plan.select
        out: list[str] = []
        if sel.distinct:
            # DISTINCT + ORDER BY an unselected column is ill-defined
            return out
        for o in sel.order_by:
            nm = S.expr_name(o.expr)
            if nm in declared or nm in out:
                continue
            refs = referenced_columns(o.expr)
            if refs and all(r in table.column_names for r in refs):
                out.append(nm)
        return out

    def _project(self, table: pa.Table) -> pa.Table:
        sel = self.plan.select
        names: list[str] = []
        arrays: list[pa.Array] = []
        for item in sel.items:
            if isinstance(item.expr, S.Star):
                prefix = f"{item.expr.table}." if item.expr.table else None
                cols = table.column_names
                if prefix is not None:
                    qualified = [n for n in cols if n.startswith(prefix)]
                    # single-table scans have unqualified columns; `r.*`
                    # over them means everything
                    cols = qualified or cols
                for name in cols:
                    names.append(name)
                    arrays.append(table.column(name).combine_chunks())
                continue
            names.append(item.alias or S.expr_name(item.expr))
            arrays.append(_arr(evaluate(item.expr, table), table))
        if not any(isinstance(i.expr, S.Star) for i in sel.items):
            for nm in self._order_carry_names(names, table):
                for o in sel.order_by:
                    if S.expr_name(o.expr) == nm:
                        names.append(nm)
                        arrays.append(_arr(evaluate(o.expr, table), table))
                        break
        return pa.table(dict(zip(names, arrays)) if len(set(names)) == len(names) else _dedup(names, arrays))

    # -- aggregate -----------------------------------------------------------

    def build_aggregator(self) -> tuple[HashAggregator, list[S.SelectItem], list[str]]:
        """Construct the aggregator + rewritten post-agg select items."""
        sel = self.plan.select
        specs: list[AggSpec] = []
        counter = [0]
        rewritten: list[S.SelectItem] = []
        for item in sel.items:
            new_expr = _collect_aggs(item.expr, specs, counter)
            rewritten.append(S.SelectItem(new_expr, item.alias or S.expr_name(item.expr)))
        having = _collect_aggs(sel.having, specs, counter) if sel.having else None
        group_names = [S.expr_name(g) for g in sel.group_by]
        agg = HashAggregator(sel.group_by, specs)
        self._having = having
        return agg, rewritten, group_names

    def _execute_aggregate(self, tables: Iterator[pa.Table]) -> pa.Table:
        agg, rewritten, group_names = self.build_aggregator()
        sel = self.plan.select
        from parseable_tpu.query import partials as PT

        if sel.group_by and PT.specs_partializable(agg.specs):
            # two-phase: per-block pyarrow partials + ONE vectorized merge —
            # no per-group Python, so 1M-group queries don't cliff
            # (DataFusion partial/final split parity)
            import time as _time

            from parseable_tpu.ops.link import get_link

            link = get_link(getattr(self, "options", None))
            parts: list[pa.Table] = []
            for table in tables:
                self._check_deadline()
                t0 = _time.perf_counter()
                table = self._bounds_filter(table)
                rows_scanned = table.num_rows  # pre-filter: the adaptive
                mask = self._where_mask(table)  # cost model prices raw rows
                if mask is not None:
                    table = table.filter(mask)
                pt = PT.partial_from_block(table, sel.group_by, agg.specs)
                if pt is not None:
                    parts.append(pt)
                link.record_cpu_agg(rows_scanned, _time.perf_counter() - t0)
            if self.partials_source is not None:
                # distributed pushdown: peers' combined partials join the
                # local blocks in ONE merge (same funnel, exact avg/stddev)
                parts.extend(self.partials_source())
            if parts:
                interim = PT.merge_partials(parts, agg.specs, len(sel.group_by))
                return self.finalize_from_interim(interim, rewritten)
            return self.finalize_aggregate(agg, rewritten, group_names)
        for table in tables:
            self._check_deadline()
            table = self._bounds_filter(table)
            mask = self._where_mask(table)
            agg.update(table, mask)
        return self.finalize_aggregate(agg, rewritten, group_names)

    def partial_tables(self, tables: Iterator[pa.Table]) -> list[pa.Table]:
        """Scan -> per-block partial tables, no merge/finalize: the peer
        half of distributed partial-aggregate pushdown (the node-local
        scan reduces here, combine_partials folds the blocks into one
        wire-ready partial). Applies the same bounds filter + WHERE mask
        as _execute_aggregate's two-phase loop."""
        from parseable_tpu.query import partials as PT

        agg, _rewritten, _names = self.build_aggregator()
        sel = self.plan.select
        parts: list[pa.Table] = []
        for table in tables:
            self._check_deadline()
            table = self._bounds_filter(table)
            mask = self._where_mask(table)
            if mask is not None:
                table = table.filter(mask)
            pt = PT.partial_from_block(table, sel.group_by, agg.specs)
            if pt is not None:
                parts.append(pt)
        return parts

    def finalize_aggregate(
        self, agg: HashAggregator, rewritten: list[S.SelectItem], group_names: list[str]
    ) -> pa.Table:
        sel = self.plan.select
        if not agg.groups and not sel.group_by:
            agg.groups[()] = agg._new_state()
        # build a table of group keys + agg slots
        cols: dict[str, list] = {f"__g{i}": [] for i in range(len(sel.group_by))}
        for si in range(len(agg.specs)):
            cols[f"__agg{si}"] = []
        for key, st in agg.groups.items():
            for i, kv in enumerate(key):
                cols[f"__g{i}"].append(kv)
            for si in range(len(agg.specs)):
                cols[f"__agg{si}"].append(agg.finalize_value(st, si))
        interim = pa.table(cols) if cols else pa.table({"__dummy": [None] * len(agg.groups)})
        return self.finalize_from_interim(interim, rewritten)

    def finalize_from_interim(self, interim: pa.Table, rewritten: list[S.SelectItem]) -> pa.Table:
        """Post-aggregation: HAVING, projection over __g/__agg slots, ORDER
        BY/LIMIT. Shared by the sparse (dict) fold and the TPU engine's
        vectorized dense finalize."""
        if self.interim_sink is not None:
            self.interim_sink(interim)
        sel = self.plan.select

        # group exprs referenced post-agg resolve to the key columns.
        # Keyed by structural repr, not display name: `l.a` and `o.a` share
        # the name "a" but are different group keys.
        remap: dict[str, str] = {}
        for i, g in enumerate(sel.group_by):
            remap[repr(g)] = f"__g{i}"
            remap.setdefault(S.expr_name(g), f"__g{i}")

        def rewrite_groups(e: S.Expr) -> S.Expr:
            nm = repr(e)
            if nm in remap:
                return S.Column(remap[nm])
            nm = S.expr_name(e)
            if nm in remap and not isinstance(e, S.Column):
                return S.Column(remap[nm])
            if isinstance(e, S.Column) and e.table is None and nm in remap:
                return S.Column(remap[nm])
            if isinstance(e, S.BinaryOp):
                return S.BinaryOp(e.op, rewrite_groups(e.left), rewrite_groups(e.right))
            if isinstance(e, S.UnaryOp):
                return S.UnaryOp(e.op, rewrite_groups(e.operand))
            if isinstance(e, S.Cast):
                return S.Cast(rewrite_groups(e.expr), e.type_name)
            if isinstance(e, S.WindowCall):
                return S.WindowCall(
                    e.name,
                    [rewrite_groups(a) for a in e.args],
                    [rewrite_groups(p) for p in e.partition_by],
                    [S.OrderItem(rewrite_groups(o.expr), o.desc) for o in e.order_by],
                    e.frame,
                )
            return e

        def project(interim: pa.Table) -> pa.Table:
            if getattr(self, "_having", None) is not None:
                hmask = _arr(evaluate(rewrite_groups(self._having), interim), interim)
                interim = interim.filter(hmask)

            items = [S.SelectItem(rewrite_groups(i.expr), i.alias) for i in rewritten]
            if any(S.contains_window(i.expr) for i in items):
                # windows over the aggregated output (one row per group):
                # `rank() OVER (ORDER BY sum(b) DESC)` etc.
                from parseable_tpu.query import window as W

                windows: list[S.WindowCall] = []
                for i in items:
                    windows.extend(W.window_calls(i.expr))
                interim, mapping = W.attach_window_columns(interim, windows)
                items = [
                    S.SelectItem(W.rewrite_windows(i.expr, mapping), i.alias)
                    for i in items
                ]

            names, arrays = [], []
            for item in items:
                names.append(item.alias)
                arrays.append(_arr(evaluate(item.expr, interim), interim))
            return pa.table(_dedup(names, arrays))

        from parseable_tpu.query.partials import decode_dictionary_columns

        try:
            result = project(interim)
        except (pa.ArrowNotImplementedError, pa.ArrowInvalid, pa.ArrowTypeError):
            # a kernel without dictionary support hit a dictionary-typed key
            # column (high-cardinality interims keep string keys encoded):
            # decode once and retry
            result = project(decode_dictionary_columns(interim))
        result = self._order_limit(result)
        # dictionary keys stay encoded through group/merge/order-limit;
        # the boundary decodes them so downstream consumers (union, joins,
        # serializers) see plain columns — post-LIMIT this is rows-out work
        return decode_dictionary_columns(result)

    # -- order / limit -------------------------------------------------------

    def _sort_keys(self, table: pa.Table) -> tuple[pa.Table, list[tuple[str, str]]]:
        """Resolve ORDER BY keys (aux columns appended for expression keys)."""
        sel = self.plan.select
        keys: list[tuple[str, str]] = []
        aux_cols = 0
        for o in sel.order_by:
            name = S.expr_name(o.expr)
            if isinstance(o.expr, S.Column) and o.expr.name in table.column_names:
                keys.append((o.expr.name, "descending" if o.desc else "ascending"))
            elif name in table.column_names:
                keys.append((name, "descending" if o.desc else "ascending"))
            else:
                if S.contains_window(o.expr):
                    raise ExecError(
                        "a window function in ORDER BY of an aggregate query "
                        "must also appear in the SELECT list (alias it and "
                        "order by the alias)"
                    )
                aux = f"__sort{aux_cols}"
                aux_cols += 1
                table = table.append_column(aux, _arr(evaluate(o.expr, table), table))
                keys.append((aux, "descending" if o.desc else "ascending"))
        return table, keys

    @staticmethod
    def _drop_aux(table: pa.Table) -> pa.Table:
        return table.select([c for c in table.column_names if not c.startswith("__sort")])

    def _sorted(self, table: pa.Table) -> pa.Table:
        """ORDER BY sort (aux columns for expression keys, dropped after)."""
        table, keys = self._sort_keys(table)
        try:
            table = table.sort_by(keys)
        except (pa.ArrowNotImplementedError, pa.ArrowInvalid, pa.ArrowTypeError):
            from parseable_tpu.query.partials import decode_dictionary_columns

            table = decode_dictionary_columns(table).sort_by(keys)
        return self._drop_aux(table)

    def _order_limit(self, table: pa.Table) -> pa.Table:
        sel = self.plan.select
        off = sel.offset or 0
        if sel.order_by:
            k = None if sel.limit is None else off + sel.limit
            if k is not None and 0 < k and table.num_rows > max(k * 4, 1024):
                # top-K selection instead of a full sort: a LIMIT over a
                # million-group aggregate is a partial-select, not a sort
                # (DataFusion's TopK operator; reference gets this from
                # /root/reference/src/query/mod.rs DataFusion planner)
                keyed, keys = self._sort_keys(table)
                if any(
                    pa.types.is_dictionary(keyed.column(name).type) for name, _ in keys
                ):
                    # select_k_unstable SEGFAULTS (not raises) on dictionary
                    # sort keys (pyarrow 25) — decode before selecting
                    from parseable_tpu.query.partials import decode_dictionary_columns

                    keyed = decode_dictionary_columns(keyed)
                try:
                    idx = pc.select_k_unstable(
                        keyed, options=pc.SelectKOptions(k=k, sort_keys=keys)
                    )
                    table = self._drop_aux(keyed.take(idx))
                except (pa.ArrowNotImplementedError, pa.ArrowInvalid, pa.ArrowTypeError):
                    table = self._sorted(table)
            else:
                table = self._sorted(table)
        if off:
            table = table.slice(off)
        if sel.limit is not None:
            table = table.slice(0, sel.limit)
        return table


def _unify_parts(parts: list[pa.Table]) -> pa.Table:
    from parseable_tpu.utils.arrowutil import adapt_batch, merge_schemas

    schema = merge_schemas([t.schema for t in parts])
    unified = []
    for t in parts:
        for b in t.to_batches():
            unified.append(adapt_batch(schema, b))
    return pa.Table.from_batches(unified, schema=schema)


def _dedup(names: list[str], arrays: list) -> dict:
    out = {}
    for n, a in zip(names, arrays):
        base, k = n, 1
        while n in out:
            n = f"{base}_{k}"
            k += 1
        out[n] = a
    return out


def _empty_like(plan: LogicalPlan) -> pa.Table:
    """Zero-row table typed from the stream schema (string for unknowns) so
    select-list expressions still evaluate when the scan matched nothing."""
    hint: pa.Schema | None = plan.schema_hint  # type: ignore[assignment]
    known = {f.name: f.type for f in hint} if hint is not None else {}
    cols = plan.needed_columns if plan.needed_columns is not None else set(known)
    out = {c: pa.array([], type=known.get(c, pa.string())) for c in sorted(cols)}
    return pa.table(out or {"__empty": pa.array([], pa.int64())})
