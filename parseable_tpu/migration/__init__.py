"""Versioned metadata migrations + deployment reconcile.

Parity targets:
- stream-json migration v1 -> v7 (reference:
  src/migration/stream_metadata_migration.rs): older stream.json layouts —
  flat stats, scalar log_source, objectstore-format/camelCase key drift —
  load and upgrade to the current ObjectStoreFormat shape, so data written
  by any earlier deployment stays queryable.
- parseable metadata migration v1 -> v4 (reference:
  src/migration/metadata_migration.rs): .parseable.json upgrades in place.
- `resolve_parseable_metadata` (reference: src/storage/store_metadata.rs):
  staging-vs-remote reconciliation at boot decides whether this process is
  a brand-new deployment, a new node joining an existing one, or a stale
  staging dir pointed at the wrong store (hard error rather than silent
  cross-deployment writes).
"""

from __future__ import annotations

import json
import logging

from parseable_tpu.storage import (
    CURRENT_OBJECT_STORE_VERSION,
    rfc3339_now,
)

logger = logging.getLogger(__name__)

CURRENT_METADATA_VERSION = "v4"


class MigrationError(Exception):
    pass


# ------------------------------------------------------------- stream json


# v5->v6 scalar log_source enum -> snake/kebab format names (reference:
# stream_metadata_migration.rs map_log_source_format; unknown -> json)
_LOG_SOURCE_FORMATS = {
    "Kinesis": "kinesis",
    "OtelLogs": "otel-logs",
    "OtelTraces": "otel-traces",
    "OtelMetrics": "otel-metrics",
    "Pmeta": "pmeta",
    "Json": "json",
    # already-migrated spellings pass through
    "kinesis": "kinesis",
    "otel-logs": "otel-logs",
    "otel-traces": "otel-traces",
    "otel-metrics": "otel-metrics",
    "pmeta": "pmeta",
    "json": "json",
}

# v6->v7: telemetry type derived from the (migrated) log source
_TELEMETRY_BY_SOURCE = {
    "otel-logs": "logs",
    "otel-traces": "traces",
    "otel-metrics": "metrics",
}


def _migrate_snapshot_v1(snapshot: dict) -> dict:
    """v1 snapshot manifests lack the per-manifest rollup counters
    (reference: v1_v2_snapshot_migration): add zeroed counters + bump."""
    new_list = []
    for m in snapshot.get("manifest_list", []) or []:
        new_list.append(
            {
                "manifest_path": m.get("manifest_path"),
                "time_lower_bound": m.get("time_lower_bound"),
                "time_upper_bound": m.get("time_upper_bound"),
                "events_ingested": m.get("events_ingested", 0),
                "ingestion_size": m.get("ingestion_size", 0),
                "storage_size": m.get("storage_size", 0),
            }
        )
    return {"version": "v2", "manifest_list": new_list}


def migrate_stream_json(obj: dict, stream_name: str | None = None) -> dict:
    """Upgrade any historical stream.json shape to the current one.

    Handled drift (mirroring v1->v7 in stream_metadata_migration.rs):
    - v1-v3 flat `stats` {events, ingestion, storage} -> current/lifetime/
      deleted triplet (lifetime seeded from current; deleted zero);
    - v1 snapshot manifests without rollup counters -> zeroed counters
      (v1_v2_snapshot_migration);
    - v4->v5 missing `stream_type` -> Internal for pmeta else UserDefined;
    - v5->v6 scalar `log_source` enum -> [{log_source_format, fields}]
      with the reference's format-name mapping (unknown -> json);
    - v6->v7 missing `telemetry_type` derived from the log source;
    - `objectstore-format` missing or under `object_store_format`;
    - camelCase keys (createdAt, firstEventAt, staticSchemaFlag,
      timePartition, customPartition, streamType) -> current names;
    - missing snapshot -> empty manifest list.
    Idempotent: current-format documents pass through unchanged.
    """
    out = dict(obj)
    version = str(out.get("version", "v1"))

    # key drift ---------------------------------------------------------
    renames = {
        "createdAt": "created-at",
        "firstEventAt": "first-event-at",
        "staticSchemaFlag": "static_schema_flag",
        "timePartition": "time_partition",
        "timePartitionLimit": "time_partition_limit",
        "customPartition": "custom_partition",
        "streamType": "stream_type",
        "object_store_format": "objectstore-format",
    }
    for old, new in renames.items():
        if old in out and new not in out:
            out[new] = out.pop(old)

    # stats -------------------------------------------------------------
    stats = out.get("stats") or {}
    if stats and "current_stats" not in stats:
        flat = {
            "events": stats.get("events", 0),
            "ingestion": stats.get("ingestion", 0),
            "storage": stats.get("storage", 0),
        }
        out["stats"] = {
            "current_stats": flat,
            "lifetime_stats": dict(flat),
            "deleted_stats": {"events": 0, "ingestion": 0, "storage": 0},
        }

    # stream type (v4->v5) ---------------------------------------------
    if "stream_type" not in out:
        from parseable_tpu import INTERNAL_STREAM_NAME

        out["stream_type"] = (
            "Internal" if stream_name == INTERNAL_STREAM_NAME else "UserDefined"
        )

    # log source (v5->v6) ----------------------------------------------
    ls = out.get("log_source")
    if isinstance(ls, str):
        fmt = _LOG_SOURCE_FORMATS.get(ls, "json")
        out["log_source"] = [{"log_source_format": fmt, "fields": []}]
    elif ls is None:
        out["log_source"] = [{"log_source_format": "json", "fields": []}]

    # telemetry type (v6->v7) ------------------------------------------
    if "telemetry_type" not in out:
        first = (
            out["log_source"][0].get("log_source_format", "json")
            if isinstance(out.get("log_source"), list) and out["log_source"]
            else "json"
        )
        out["telemetry_type"] = _TELEMETRY_BY_SOURCE.get(first, "logs")

    # snapshot ----------------------------------------------------------
    snap = out.get("snapshot")
    if not snap:
        out["snapshot"] = {"version": "v2", "manifest_list": []}
    elif str(snap.get("version", "v1")) == "v1":
        out["snapshot"] = _migrate_snapshot_v1(snap)

    if "created-at" not in out:
        out["created-at"] = rfc3339_now()
    out["version"] = CURRENT_OBJECT_STORE_VERSION
    out.setdefault("objectstore-format", CURRENT_OBJECT_STORE_VERSION)
    if version != CURRENT_OBJECT_STORE_VERSION:
        logger.info("migrated stream.json %s -> %s", version, CURRENT_OBJECT_STORE_VERSION)
    return out


# --------------------------------------------------------- parseable json


def migrate_parseable_metadata(obj: dict) -> dict:
    """Upgrade .parseable.json to the current shape
    (reference: metadata_migration.rs v1->v4: version bump, staging/server
    mode fields, user block moved out to RBAC)."""
    out = dict(obj)
    version = str(out.get("version", "v1"))
    renames = {"deployment_id": "deployment_id", "deploymentId": "deployment_id"}
    for old, new in renames.items():
        if old in out and new not in out:
            out[new] = out.pop(old)
    out.pop("users", None)  # pre-v3 embedded users; RBAC owns them now
    out.pop("streams", None)  # pre-v2 embedded stream list
    out.setdefault("server_mode", out.pop("mode", "All"))
    out["version"] = CURRENT_METADATA_VERSION
    if version != CURRENT_METADATA_VERSION:
        logger.info(
            "migrated .parseable.json %s -> %s", version, CURRENT_METADATA_VERSION
        )
    return out


# ------------------------------------------------------------- reconcile


def resolve_parseable_metadata(p) -> dict:
    """Staging-vs-remote deployment reconciliation at boot
    (reference: store_metadata.rs resolve_parseable_metadata).

    Outcomes:
    - neither side has metadata  -> NEW deployment: mint an id, write both;
    - remote only                -> new node joining: adopt remote, copy to
      staging;
    - staging only               -> the store was wiped or this staging dir
      points at the wrong store: hard error (silent re-create would corrupt
      a different deployment's catalog);
    - both, same deployment id   -> ok; run metadata migration and update;
    - both, different ids        -> hard error.
    """
    staging_path = p.options.staging_dir() / ".parseable.json"
    staging_doc = None
    if staging_path.is_file():
        try:
            staging_doc = json.loads(staging_path.read_text())
        except ValueError:
            logger.warning("unreadable staging .parseable.json; ignoring")
    remote_doc = p.metastore.get_parseable_metadata()

    if remote_doc is None and staging_doc is None:
        doc = {
            "version": CURRENT_METADATA_VERSION,
            "deployment_id": p.node_id,
            "server_mode": p.options.mode.to_str(),
            "created-at": rfc3339_now(),
        }
        p.metastore.put_parseable_metadata(doc)
        staging_path.parent.mkdir(parents=True, exist_ok=True)
        staging_path.write_text(json.dumps(doc))
        logger.info("new deployment %s", doc["deployment_id"])
        return doc

    if remote_doc is not None and staging_doc is None:
        doc = migrate_parseable_metadata(remote_doc)
        staging_path.parent.mkdir(parents=True, exist_ok=True)
        staging_path.write_text(json.dumps(doc))
        logger.info("joined existing deployment %s", doc.get("deployment_id"))
        return doc

    if remote_doc is None and staging_doc is not None:
        raise MigrationError(
            "staging has deployment metadata but the object store has none — "
            "the store was wiped or P_FS_DIR/bucket points at the wrong "
            "location; refusing to silently re-create the deployment"
        )

    # both present
    staged = migrate_parseable_metadata(staging_doc)
    remote = migrate_parseable_metadata(remote_doc)
    sid = staged.get("deployment_id")
    rid = remote.get("deployment_id")
    if sid and rid and sid != rid:
        raise MigrationError(
            f"staging belongs to deployment {sid} but the store is deployment "
            f"{rid}; refusing to mix deployments"
        )
    p.metastore.put_parseable_metadata(remote)
    staging_path.write_text(json.dumps(remote))
    return remote


def run_migrations(p) -> int:
    """Boot-time pass (reference: migration/mod.rs:117-520): migrate every
    stream.json in place. Returns how many documents were upgraded."""
    upgraded = 0
    try:
        names = p.metastore.list_streams()
    except Exception:
        return 0
    for name in names:
        try:
            for node_id, raw in p.metastore.list_stream_json_raw(name):
                migrated = migrate_stream_json(raw, stream_name=name)
                if migrated != raw:
                    p.metastore.put_stream_json_raw(name, migrated, node_id)
                    upgraded += 1
        except Exception:
            logger.exception("migration failed for stream %s", name)
    return upgraded
