"""plint FFI rules: the ctypes boundary's contracts, enforced statically.

PR 12's nsan gate diffs the declared ABI (abicheck) and beats on the
native code itself (sanitizers + fuzzing); these two rules close the
remaining gap — Python-side *usage* of the boundary:

- ffi-restype    no ctypes call on a `ptpu_*` symbol the same module has
                 not declared BOTH `restype` and `argtypes` for. An
                 undeclared restype silently defaults to c_int and
                 truncates 64-bit pointers/lengths; undeclared argtypes
                 let every call site guess its own conversions.
- ffi-ownership  native columnar buffers have exactly one custody story:
                 the producer handle must flow into the `_ColumnarBufs`
                 owner machinery (or be freed), every `pa.foreign_buffer`
                 must carry an owner base (a bare foreign_buffer is a
                 use-after-free the moment the GC drops the handle), and
                 `ptpu_cols_free` may only run from the owner's __del__ —
                 anywhere else is a double-free in waiting.

Both are lexical per-file checks, matching the rest of plint: cheap,
conservative, and specific to the invariants fastpath.cpp's comments can
state but not enforce.
"""

from __future__ import annotations

import ast
from typing import Iterable

from parseable_tpu.analysis.framework import (
    Finding,
    Rule,
    SourceFile,
    attr_chain,
    enclosing_context,
)

# the sinks that take custody of a raw columnar handle
_CUSTODY_SINKS = {"_ColumnarBufs", "_import_columnar", "ptpu_cols_free"}
_COLUMNAR_PRODUCERS = {"ptpu_flatten_columnar", "ptpu_otel_logs_columnar"}


def _enclosing_function(
    tree: ast.Module, target: ast.AST
) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
    """Innermost function whose body contains `target`."""
    best: ast.FunctionDef | ast.AsyncFunctionDef | None = None

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            self._consider(node)

        def visit_AsyncFunctionDef(self, node):
            self._consider(node)

        def _consider(self, node):
            nonlocal best
            for sub in ast.walk(node):
                if sub is target:
                    best = node  # keep descending: innermost wins
                    break
            self.generic_visit(node)

    V().visit(tree)
    return best


class FfiRestypeRule(Rule):
    """Every ctypes call on a `ptpu_*` symbol needs the module to have
    declared that symbol's `restype` AND `argtypes` (the `_bind*` family
    in native/__init__.py). ctypes' restype default is c_int: on this ABI
    a 64-bit pointer or length returned through an undeclared symbol comes
    back truncated — the bug works on small heaps and corrupts memory on
    big ones, the worst possible failure mode to find dynamically."""

    name = "ffi-restype"
    description = (
        "ctypes calls on ptpu_* symbols require declared restype + argtypes"
    )
    rationale = (
        "an undeclared restype defaults to c_int and truncates 64-bit "
        "returns; undeclared argtypes make every call site guess conversions"
    )

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        declared_restype: set[str] = set()
        declared_argtypes: set[str] = set()
        calls: list[tuple[str, ast.Call]] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Attribute)
                    and t.value.attr.startswith("ptpu_")
                ):
                    if t.attr == "restype":
                        declared_restype.add(t.value.attr)
                    elif t.attr == "argtypes":
                        declared_argtypes.add(t.value.attr)
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and node.func.attr.startswith(
                    "ptpu_"
                ):
                    calls.append((node.func.attr, node))
        for name, call in calls:
            missing = []
            if name not in declared_restype:
                missing.append("restype")
            if name not in declared_argtypes:
                missing.append("argtypes")
            if missing:
                yield Finding(
                    rule=self.name,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f"call to {name} without declared {' or '.join(missing)} "
                        "in this module — ctypes falls back to c_int returns "
                        "and per-call-site argument guessing"
                    ),
                    context=enclosing_context(sf.tree, call),
                )


class FfiOwnershipRule(Rule):
    """Columnar buffer custody: one producer handle, one owner, one free.

    Three checks on the zero-copy import path:
    - `pa.foreign_buffer(ptr, size)` without the third `base` argument
      gives Arrow a raw pointer with no liveness anchor — the native batch
      can be freed while the Array still reads it;
    - a function that calls a columnar producer (`ptpu_flatten_columnar`,
      `ptpu_otel_logs_columnar`) must hand the handle to the custody
      machinery (`_ColumnarBufs` / `_import_columnar`) or free it —
      otherwise the handle leaks (ptpu_cols_live drifts, the nsan session
      gate goes red at runtime; this catches it at review time);
    - `ptpu_cols_free` belongs to `_ColumnarBufs.__del__` alone: a second
      call site is a double-free the moment both run."""

    name = "ffi-ownership"
    description = (
        "native columnar buffers need an owner base and exactly one free path"
    )
    rationale = (
        "a foreign_buffer without a base is a use-after-free; a producer "
        "handle that skips the owner leaks; a second ptpu_cols_free site "
        "is a double-free"
    )

    def check(self, sf: SourceFile) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            tail = chain[-1] if chain else ""
            if tail == "foreign_buffer":
                has_base = len(node.args) >= 3 or any(
                    kw.arg == "base" for kw in node.keywords
                )
                if not has_base:
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            "pa.foreign_buffer without an owner base: the "
                            "Arrow buffer holds a raw pointer with nothing "
                            "keeping the native allocation alive"
                        ),
                        context=enclosing_context(sf.tree, node),
                    )
            elif tail in _COLUMNAR_PRODUCERS:
                fn = _enclosing_function(sf.tree, node)
                scope_names = {
                    n
                    for sub in ast.walk(fn if fn is not None else sf.tree)
                    for n in (
                        [sub.id]
                        if isinstance(sub, ast.Name)
                        else [sub.attr]
                        if isinstance(sub, ast.Attribute)
                        else []
                    )
                }
                if not (scope_names & _CUSTODY_SINKS):
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"{tail} produces an owned columnar handle but "
                            "this function never passes it to _ColumnarBufs/"
                            "_import_columnar or ptpu_cols_free — the batch "
                            "leaks (ptpu_cols_live will drift)"
                        ),
                        context=enclosing_context(sf.tree, node),
                    )
            elif tail == "ptpu_cols_free":
                ctx = enclosing_context(sf.tree, node)
                if not ctx.endswith("_ColumnarBufs.__del__"):
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            "ptpu_cols_free outside _ColumnarBufs.__del__: "
                            "the owner already frees on last release, so a "
                            "second call site is a double-free in waiting"
                        ),
                        context=ctx,
                    )


FFI_RULES = [FfiRestypeRule, FfiOwnershipRule]
