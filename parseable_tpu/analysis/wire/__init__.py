"""wlint — cross-boundary wire-contract static analysis.

plint sees one Python file's AST, psan one process at runtime, nsan one
library's memory. None of them sees a contract whose two halves live in
different sources — a route the C++ edge classifies that aiohttp renamed, a
header fan-out reads that no peer produces, a Flight ticket kind the server
stopped dispatching, a metric family that flatlined, a stages key a test
asserts that the query path never emits, an owned ABI pointer that misses
its free on one path. wlint extracts both sides of each such contract from
source and diffs them.

Rules (each is one contract family):

- route-drift      client path literals vs the aiohttp route table, and the
                   C++ hot-route classifier vs registered routes
- header-contract  X-P-* reads vs writes across Python and fastpath.cpp
- ticket-drift     Flight ticket kinds and ptpu.* schema-metadata keys,
                   client vs server
- metric-discipline  constructed-but-never-ticked families, .labels()
                   arity/order, README coverage
- stages-contract  stats.stages.* produced vs consumed (advisory for
                   produced-but-unwatched)
- ffi-custody      owned ABI pointers must reach their paired release on
                   all paths (static complement of the *_live()==0 gates)

Reuses plint's Finding/fingerprint/baseline machinery verbatim; the
suppression marker is ``# wlint: disable[=rule,...]`` (C++:
``// wlint: disable=...``) so a plint suppression never silences a wire
finding or vice versa. Run as ``python -m parseable_tpu.analysis.wire``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace
from pathlib import Path

from parseable_tpu.analysis.framework import (
    AnalysisReport,
    Finding,
    Rule,
    SourceFile,
    iter_python_files,
    load_baseline,
    write_baseline,
)
from parseable_tpu.analysis.wire.csource import CSourceFile
from parseable_tpu.analysis.wire.extract import WireProject
from parseable_tpu.analysis.wire.rules_contracts import (
    HeaderContractRule,
    RouteDriftRule,
    TicketDriftRule,
)
from parseable_tpu.analysis.wire.rules_custody import FfiCustodyRule
from parseable_tpu.analysis.wire.rules_telemetry import (
    MetricDisciplineRule,
    StagesContractRule,
)

WLINT_VERSION = "1"

WIRE_RULES: list[type[Rule]] = [
    RouteDriftRule,
    HeaderContractRule,
    TicketDriftRule,
    MetricDisciplineRule,
    StagesContractRule,
    FfiCustodyRule,
]

DEFAULT_PATHS = ["parseable_tpu", "scripts", "tests", "bench.py"]

_SUPPRESS_RE = re.compile(r"wlint:\s*disable(?:=([A-Za-z0-9_,-]+))?")


@dataclass
class WireReport(AnalysisReport):
    """plint's report shape plus non-gating advisories (stages-contract's
    produced-but-never-consumed keys): printed as notes, serialized under
    their own key, never part of the exit code."""

    advisories: list[Finding] = field(default_factory=list)

    def to_json(self) -> dict:
        doc = super().to_json()
        doc["advisories"] = [f.to_json() for f in self.advisories]
        return doc


def _wlint_suppressions(sf: SourceFile) -> dict[int, set[str] | None]:
    """SourceFile's own suppression table answers to `plint:` markers; wire
    findings answer only to `wlint:` ones, scanned from the same comments."""
    out: dict[int, set[str] | None] = {}
    for line, comment in sf.comments.items():
        m = _SUPPRESS_RE.search(comment)
        if m:
            names = m.group(1)
            out[line] = (
                {s.strip() for s in names.split(",") if s.strip()} if names else None
            )
    return out


def run_wire_analysis(
    root: Path,
    paths: list[str] | None = None,
    rules: list[Rule] | None = None,
    baseline_path: Path | None = None,
    report_only: set[str] | None = None,
) -> WireReport:
    """Analyze `paths` under `root` with the wire rules. Same contract as
    framework.run_analysis; differences: the project also carries the C++
    sources (``*.cpp`` under parseable_tpu/), analyzer sources are excluded
    from the project outright (finalize rules never see them), and
    suppression/baseline use wlint's own marker and file."""
    root = Path(root)
    rules = rules if rules is not None else [cls() for cls in WIRE_RULES]
    paths = paths or DEFAULT_PATHS
    project = WireProject(root=root)
    parse_errors: list[str] = []
    for p in iter_python_files(root, paths):
        rel = p.relative_to(root).as_posix()
        if rel.startswith("parseable_tpu/analysis/"):
            continue  # the analyzer does not lint itself
        try:
            project.files.append(SourceFile.from_path(root, p))
        except (SyntaxError, UnicodeDecodeError) as e:
            parse_errors.append(f"{p}: {e}")
    native_dir = root / "parseable_tpu"
    if native_dir.is_dir():
        for p in sorted(native_dir.rglob("*.cpp")):
            try:
                project.csources.append(CSourceFile.from_path(root, p))
            except UnicodeDecodeError as e:
                parse_errors.append(f"{p}: {e}")

    by_rel = {sf.rel: sf for sf in project.files}
    c_by_rel = {cf.rel: cf for cf in project.csources}
    py_suppress = {sf.rel: _wlint_suppressions(sf) for sf in project.files}

    def suppressed(f: Finding) -> bool:
        cf = c_by_rel.get(f.path)
        if cf is not None:
            return cf.is_suppressed(f.rule, f.line)
        table = py_suppress.get(f.path)
        if table is None or f.line not in table:
            return False
        names = table[f.line]
        return names is None or f.rule in names

    def finish(f: Finding) -> Finding:
        if f.snippet:
            return f
        src = by_rel.get(f.path) or c_by_rel.get(f.path)
        return replace(f, snippet=src.snippet(f.line)) if src is not None else f

    findings: list[Finding] = []
    advisories: list[Finding] = []
    for sf in project.files:
        for rule in rules:
            if not rule.applies(sf.rel):
                continue
            for f in rule.check(sf):
                if not suppressed(f):
                    findings.append(finish(f))
    for rule in rules:
        for f in rule.finalize(project):
            if not suppressed(f):
                findings.append(finish(f))
        advise = getattr(rule, "advisories", None)
        if advise is not None:
            for f in advise(project):
                if not suppressed(f):
                    advisories.append(finish(f))

    if report_only is not None:
        findings = [f for f in findings if f.path in report_only]
        advisories = [f for f in advisories if f.path in report_only]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    advisories.sort(key=lambda f: (f.path, f.line, f.rule))
    baseline = load_baseline(baseline_path)
    baselined = [
        f
        for f in findings
        if f.fingerprint in baseline or f.legacy_fingerprint in baseline
    ]
    unbaselined = [
        f
        for f in findings
        if f.fingerprint not in baseline and f.legacy_fingerprint not in baseline
    ]
    return WireReport(
        findings=findings,
        baselined=baselined,
        unbaselined=unbaselined,
        files_checked=len(project.files) + len(project.csources),
        parse_errors=parse_errors,
        advisories=advisories,
    )


__all__ = [
    "WLINT_VERSION",
    "WIRE_RULES",
    "DEFAULT_PATHS",
    "WireReport",
    "run_wire_analysis",
    "write_baseline",
]
