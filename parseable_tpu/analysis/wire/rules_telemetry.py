"""wlint telemetry rules: Prometheus metric discipline + stats.stages keys.

- metric-discipline  every family constructed in utils/metrics.py must be
                     ticked somewhere in shipped code, every ``.labels()``
                     call site must pass the declared label names in
                     order, and every exported family must appear in
                     README (verbatim or via a ``parseable_foo_*`` family
                     row — config-drift's doc-enforcement idiom, applied
                     to metrics).
- stages-contract    the `stats.stages.*` keys the query path produces vs
                     the keys tests/EXPLAIN ANALYZE/bench consume. A
                     consumed-but-never-produced key is an error (dead
                     assertion surface — the check can never see the value
                     it names); a produced-but-never-consumed key is an
                     advisory (exported but unwatched).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Iterable

from parseable_tpu.analysis.framework import (
    Finding,
    Rule,
    attr_chain,
    enclosing_context,
)
from parseable_tpu.analysis.wire.extract import WireProject

_METRICS_REL = "parseable_tpu/utils/metrics.py"
_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}
# methods that tick/observe/describe a family at a use site
_TICK_METHODS = {
    "inc",
    "dec",
    "set",
    "observe",
    "labels",
    "remove",
    "clear",
    "set_function",
}


@dataclass(frozen=True)
class MetricDef:
    var: str
    kind: str
    full_name: str  # exposition base name incl. namespace prefix
    labels: tuple[str, ...]
    line: int


def _module_str_consts(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _resolve_ns(node: ast.expr | None, consts: dict[str, str]) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id, "")
    return ""


def _ctor_helpers(tree: ast.Module, consts: dict[str, str]) -> dict[str, tuple[str, str]]:
    """Local wrappers like ``def _counter(name, doc, labels): return
    Counter(name, doc, labels, namespace=METRICS_NAMESPACE, ...)`` —
    helper name -> (metric kind, resolved namespace)."""
    out: dict[str, tuple[str, str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.Return) and isinstance(stmt.value, ast.Call):
                fn = stmt.value.func
                ctor = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
                if ctor in _METRIC_CTORS:
                    ns = ""
                    for kw in stmt.value.keywords:
                        if kw.arg == "namespace":
                            ns = _resolve_ns(kw.value, consts)
                    out[node.name] = (ctor, ns)
    return out


def metrics_registry(project: WireProject) -> dict[str, MetricDef]:
    by_rel = {sf.rel: sf for sf in project.files}
    sf = by_rel.get(_METRICS_REL)
    if sf is None:
        return {}
    consts = _module_str_consts(sf.tree)
    helpers = _ctor_helpers(sf.tree, consts)
    out: dict[str, MetricDef] = {}
    for node in sf.tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            continue
        fn = node.value.func
        ctor = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
        namespace = ""
        if ctor in helpers:
            ctor, namespace = helpers[ctor]
        elif ctor not in _METRIC_CTORS:
            continue
        args, kws = node.value.args, node.value.keywords
        if not args or not isinstance(args[0], ast.Constant):
            continue
        name = args[0].value
        labels: tuple[str, ...] = ()
        if len(args) >= 3 and isinstance(args[2], (ast.List, ast.Tuple)):
            labels = tuple(
                e.value for e in args[2].elts if isinstance(e, ast.Constant)
            )
        for kw in kws:
            if kw.arg == "labelnames" and isinstance(kw.value, (ast.List, ast.Tuple)):
                labels = tuple(
                    e.value for e in kw.value.elts if isinstance(e, ast.Constant)
                )
            elif kw.arg == "namespace":
                namespace = _resolve_ns(kw.value, consts) or namespace
        full = f"{namespace}_{name}" if namespace else name
        var = node.targets[0].id
        out[var] = MetricDef(var=var, kind=ctor, full_name=full, labels=labels, line=node.lineno)
    return out


_FAMILY_ROW_RE = re.compile(r"`?([a-z][a-z0-9_]+)\*`?")


class MetricDisciplineRule(Rule):
    """See module docstring. Tick sites are scanned in shipped code only
    (parseable_tpu/, scripts/, bench.py) — a family only tests keep alive
    is still dead surface on a running node."""

    name = "metric-discipline"
    description = (
        "metric never ticked, .labels() args drifted from declaration, or "
        "family missing from README"
    )
    rationale = (
        "an unticked family is a flatline on every dashboard that trusts "
        "it; a labels() mismatch raises at the first scrape-path tick; an "
        "undocumented family is invisible to operators"
    )

    def _scan(self, rel: str) -> bool:
        return rel.endswith(".py") and (
            rel.startswith("parseable_tpu/")
            or rel.startswith("scripts/")
            or rel == "bench.py"
        )

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        registry = metrics_registry(project)
        if not registry:
            return
        ticked: set[str] = set()
        label_sites: list[tuple[str, int, str, str, list, list]] = []
        for sf in project.files:
            if not self._scan(sf.rel) or sf.rel == _METRICS_REL:
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                    continue
                if node.func.attr not in _TICK_METHODS:
                    continue
                chain = attr_chain(node.func)
                var = next((p for p in chain if p in registry), None)
                if var is None:
                    continue
                ticked.add(var)
                if node.func.attr == "labels":
                    ctx = enclosing_context(sf.tree, node)
                    label_sites.append(
                        (sf.rel, node.lineno, ctx, var, node.args, node.keywords)
                    )

        for rel, line, ctx, var, args, keywords in label_sites:
            decl = registry[var].labels
            if any(isinstance(a, ast.Starred) for a in args) or any(
                kw.arg is None for kw in keywords
            ):
                continue  # *args/**kwargs: arity is not statically knowable
            npos = len(args)
            kw_names = [kw.arg for kw in keywords]
            total = npos + len(kw_names)
            if total != len(decl):
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=line,
                    context=ctx,
                    message=(
                        f"{var}.labels() passes {total} label(s) but the "
                        f"family declares {len(decl)} ({', '.join(decl) or 'none'})"
                    ),
                )
            elif kw_names and kw_names != list(decl[npos:]):
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=line,
                    context=ctx,
                    message=(
                        f"{var}.labels() keyword order {kw_names} drifted from "
                        f"the declared label order {list(decl[npos:])}"
                    ),
                )

        readme = project.readme_text()
        families = [m.group(1) for m in _FAMILY_ROW_RE.finditer(readme)]
        for var, md in sorted(registry.items()):
            if var not in ticked:
                yield Finding(
                    rule=self.name,
                    path=_METRICS_REL,
                    line=md.line,
                    message=(
                        f"metric family {md.full_name} ({var}) is constructed "
                        "but never ticked in shipped code — flatline surface"
                    ),
                )
            documented = (
                md.full_name in readme
                or f"{md.full_name}_total" in readme
                or any(md.full_name.startswith(fam) for fam in families)
            )
            if not documented:
                yield Finding(
                    rule=self.name,
                    path=_METRICS_REL,
                    line=md.line,
                    context="README",
                    message=(
                        f"metric family {md.full_name} is exported but not "
                        "documented in README.md (add it, or a family_* row)"
                    ),
                )


# --------------------------------------------------------------------------
# stages-contract


def _const_keys(node: ast.Dict) -> Iterable[tuple[str, int]]:
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            yield k.value, k.lineno


class StagesContractRule(Rule):
    """stats.stages key accounting (see module docstring).

    Producers: the dict literal under a ``"stages"`` key, subscript-assigns
    onto a name called ``stages``, plus — for nested stage payloads — dict
    keys, subscript-assign keys, and loop-tuple constants inside functions
    named ``*_stage``/``stats_snapshot`` and keys written to the fan-out
    run's ``self.stats``.

    Consumers: constant keys read off a ``X["stages"]``/``X.get("stages")``
    expression, off a local previously bound to one, or off a name called
    ``stages`` — in tests/, bench.py, scripts/ and the package itself."""

    name = "stages-contract"
    description = "stats.stages key consumed but never produced (or produced and unwatched)"
    rationale = (
        "a consumed-but-never-produced key is dead assertion surface: the "
        "test or EXPLAIN row reads a value the query path cannot emit"
    )

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        produced = self._produced(project)
        if not produced:
            return
        consumed = self._consumed(project)
        for key, (rel, line) in sorted(consumed.items()):
            if key not in produced:
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=line,
                    message=(
                        f"stats.stages key {key!r} is consumed here but the "
                        "query path never produces it — dead assertion surface"
                    ),
                )

    def advisories(self, project: WireProject) -> Iterable[Finding]:
        produced = self._produced(project)
        if not produced:
            return
        consumed = self._consumed(project)
        for key, (rel, line, top) in sorted(produced.items()):
            if top and key not in consumed:
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=line,
                    message=(
                        f"stats.stages key {key!r} is produced but nothing in "
                        "tests/bench/scripts consumes it (advisory)"
                    ),
                )

    # ------------------------------------------------------------ producers

    def _produced(self, project: WireProject) -> dict[str, tuple[str, int, bool]]:
        out: dict[str, tuple[str, int, bool]] = {}

        def rec(key: str, rel: str, line: int, top: bool) -> None:
            out.setdefault(key, (rel, line, top))

        for sf in project.files:
            if not sf.rel.startswith("parseable_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                # {"stages": {...literal...}} — the canonical producer
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "stages"
                            and isinstance(v, ast.Dict)
                        ):
                            for key, line in _const_keys(v):
                                rec(key, sf.rel, line, True)
                # stages["x"] = ... (incremental producer)
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if (
                            isinstance(tgt, ast.Subscript)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "stages"
                            and isinstance(tgt.slice, ast.Constant)
                            and isinstance(tgt.slice.value, str)
                        ):
                            rec(tgt.slice.value, sf.rel, node.lineno, True)
                # nested stage payload producers
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                    node.name.endswith("_stage") or node.name == "stats_snapshot"
                ):
                    yield_nested = self._nested_keys(node)
                    for key, line in yield_nested:
                        rec(key, sf.rel, line, False)
            # the fan-out run's stats dict feeds stages.fanout verbatim
            if sf.rel == "parseable_tpu/query/fanout.py":
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.AnnAssign):
                        # self.stats: dict = {...}
                        if (
                            isinstance(node.target, ast.Attribute)
                            and node.target.attr == "stats"
                            and isinstance(node.value, ast.Dict)
                        ):
                            for key, line in _const_keys(node.value):
                                rec(key, sf.rel, line, False)
                    if isinstance(node, ast.Assign):
                        for tgt in node.targets:
                            if (
                                isinstance(tgt, ast.Subscript)
                                and attr_chain(tgt.value)[-1:] == ["stats"]
                                and isinstance(tgt.slice, ast.Constant)
                                and isinstance(tgt.slice.value, str)
                            ):
                                rec(tgt.slice.value, sf.rel, node.lineno, False)
                            elif (
                                isinstance(tgt, ast.Attribute)
                                and tgt.attr == "stats"
                                and isinstance(node.value, ast.Dict)
                            ):
                                for key, line in _const_keys(node.value):
                                    rec(key, sf.rel, line, False)
                    if isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Subscript):
                        tgt = node.target
                        if (
                            attr_chain(tgt.value)[-1:] == ["stats"]
                            and isinstance(tgt.slice, ast.Constant)
                            and isinstance(tgt.slice.value, str)
                        ):
                            rec(tgt.slice.value, sf.rel, node.lineno, False)
        return out

    def _nested_keys(self, fn: ast.AST) -> list[tuple[str, int]]:
        out: list[tuple[str, int]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                out.extend(_const_keys(node))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in tgts:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)
                    ):
                        out.append((tgt.slice.value, node.lineno))
            elif isinstance(node, ast.For) and isinstance(node.iter, (ast.Tuple, ast.List)):
                for e in node.iter.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.append((e.value, e.lineno))
        return out

    # ------------------------------------------------------------ consumers

    def _consumed(self, project: WireProject) -> dict[str, tuple[str, int]]:
        out: dict[str, tuple[str, int]] = {}
        for sf in project.files:
            rel = sf.rel
            if not rel.endswith(".py"):
                continue
            if not (
                rel.startswith("tests/")
                or rel.startswith("scripts/")
                or rel.startswith("parseable_tpu/")
                or rel == "bench.py"
            ):
                continue
            for key, line in self._file_consumed(sf):
                out.setdefault(key, (rel, line))
        return out

    def _file_consumed(self, sf) -> Iterable[tuple[str, int]]:
        # names bound (anywhere in the file — cheap over-approximation) to
        # a stages expression or one of its sub-dicts
        stagesish: set[str] = {"stages"}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name) and self._is_stages_expr(node.value):
                    stagesish.add(tgt.id)
        for node in ast.walk(sf.tree):
            key = None
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
                and isinstance(node.ctx, ast.Load)
            ):
                if self._reads_stages(node.value, stagesish):
                    key = node.slice.value
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                if self._reads_stages(node.func.value, stagesish):
                    key = node.args[0].value
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                # "key" in stages  /  set(stages) >= {"key", ...}
                left, op, right = node.left, node.ops[0], node.comparators[0]
                if (
                    isinstance(op, (ast.In, ast.NotIn))
                    and isinstance(left, ast.Constant)
                    and isinstance(left.value, str)
                    and self._reads_stages(right, stagesish)
                ):
                    key = left.value
                else:
                    for side, other in ((left, right), (right, left)):
                        if (
                            isinstance(side, ast.Call)
                            and isinstance(side.func, ast.Name)
                            and side.func.id == "set"
                            and side.args
                            and self._reads_stages(side.args[0], stagesish)
                            and isinstance(other, ast.Set)
                        ):
                            for e in other.elts:
                                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                                    yield e.value, node.lineno
            if key is not None and key != "stages":
                yield key, node.lineno

    def _is_stages_expr(self, node: ast.AST) -> bool:
        """X["stages"], X.get("stages"), (expr or {}), or a subscript/get
        hanging off one of those (a sub-dict still consumes stage keys)."""
        if isinstance(node, ast.BoolOp):
            return any(self._is_stages_expr(v) for v in node.values)
        if isinstance(node, ast.Subscript):
            if (
                isinstance(node.slice, ast.Constant)
                and node.slice.value == "stages"
            ):
                return True
            return self._is_stages_expr(node.value)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "get" and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and a0.value == "stages":
                    return True
                return self._is_stages_expr(node.func.value)
        return False

    def _reads_stages(self, base: ast.AST, stagesish: set[str]) -> bool:
        if isinstance(base, ast.Name):
            return base.id in stagesish
        return self._is_stages_expr(base)
