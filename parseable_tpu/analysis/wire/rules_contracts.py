"""wlint contract rules: routes, headers, Flight tickets.

Each rule extracts BOTH halves of one process-boundary agreement and
diffs them:

- route-drift      client-side path templates (cluster fan-out, query
                   scatter, blackbox harness) must resolve against the
                   aiohttp route table; the C++ edge classifier's route
                   strings must be a subset of registered routes.
- header-contract  every `X-P-*` header read somewhere must be written
                   somewhere (and vice versa), across Python AND
                   fastpath.cpp, modulo the allowlists for headers that
                   originate from or terminate at external clients.
- ticket-drift     Flight ticket `kind` values constructed client-side
                   must be dispatched in server/flight.py and vice versa;
                   the `ptpu.*` schema-metadata keys written server-side
                   must exactly equal the set the client-side strip
                   removes.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from parseable_tpu.analysis.framework import (
    Finding,
    Rule,
    SourceFile,
    attr_chain,
    enclosing_context,
)
from parseable_tpu.analysis.wire.extract import (
    ConstIndex,
    WireProject,
    client_paths,
    cpp_route_literals,
    path_matches,
    route_table,
)

# client files whose path literals must resolve against the route table
CLIENT_FILES = (
    "parseable_tpu/server/cluster.py",
    "parseable_tpu/query/fanout.py",
    "parseable_tpu/native/edge.py",
    "scripts/blackbox.py",
)


class RouteDriftRule(Rule):
    """Client path templates vs the aiohttp route table.

    The server half is built from every ``r.add_get/add_post/...`` call
    under parseable_tpu/server/ (constants, `base + "/{id}"` concats, and
    the crud_routes literal-tuple loop all resolve). The client half is
    every path-shaped literal/f-string in the cluster fan-out, the query
    scatter, the native edge, and the blackbox harness; f-string
    interpolations become `{_}` placeholders that match any one template
    segment. The C++ edge classifier's route strings are checked the same
    way — a prefix compare (trailing `/`) must be extended by a registered
    template."""

    name = "route-drift"
    description = "client path literal does not resolve against the aiohttp route table"
    rationale = (
        "a path the server never registered 404s at runtime on exactly the "
        "distributed paths (fan-out, staging pulls) tests exercise least"
    )

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        consts = ConstIndex(project)
        routes = route_table(project, consts)
        if not routes:
            return  # fixture trees without a server half stay quiet
        templates = [r.template for r in routes]
        by_rel = {sf.rel: sf for sf in project.files}
        for rel in CLIENT_FILES:
            sf = by_rel.get(rel)
            if sf is None:
                continue
            for cp in client_paths(sf, consts):
                hits = [t for t in templates if path_matches(t, cp.template)]
                if not hits:
                    yield Finding(
                        rule=self.name,
                        path=cp.rel,
                        line=cp.line,
                        context=enclosing_context(sf.tree, _node_at(sf, cp.line)),
                        message=(
                            f"client path {cp.template!r} matches no registered "
                            "aiohttp route (server/app.py route table)"
                        ),
                    )
                elif cp.method is not None and not any(
                    r.method == cp.method for r in routes if path_matches(r.template, cp.template)
                ):
                    methods = sorted(
                        {r.method for r in routes if path_matches(r.template, cp.template)}
                    )
                    yield Finding(
                        rule=self.name,
                        path=cp.rel,
                        line=cp.line,
                        context=enclosing_context(sf.tree, _node_at(sf, cp.line)),
                        message=(
                            f"client sends {cp.method} to {cp.template!r} but the "
                            f"route is registered for {'/'.join(methods)} only"
                        ),
                    )
        # C++ hot-route classifier strings must be a subset of the table
        for cf in project.csources:
            for line, literal in cpp_route_literals(cf):
                if any(path_matches(t, literal) for t in templates):
                    continue
                yield _c_finding(
                    self.name,
                    cf,
                    line,
                    f"edge classifier route {literal!r} matches no registered "
                    "aiohttp route — the C++ hot set drifted from app.py",
                )


def _node_at(sf: SourceFile, line: int) -> ast.AST:
    for node in ast.walk(sf.tree):
        if getattr(node, "lineno", None) == line:
            return node
    return sf.tree


def _c_finding(rule: str, cf, line: int, message: str) -> Finding:
    return Finding(
        rule=rule,
        path=cf.rel,
        line=line,
        message=message,
        snippet=cf.snippet(line),
    )


# --------------------------------------------------------------------------
# header-contract


_HEADER_RE = re.compile(r"^x-p-[a-z0-9-]+$", re.IGNORECASE)

# request headers external clients originate: consumed here, produced by
# the world (SDKs, curl, the console). The C++ edge declines unknown X-P-*
# so this list is closed on purpose — extending it is a wire change.
EXTERNAL_REQUEST_HEADERS = {
    "x-p-stream",
    "x-p-log-source",
    "x-p-api-key",
    "x-p-tenant",
    "x-p-update-stream",
    "x-p-time-partition",
    "x-p-custom-partition",
    "x-p-static-schema-flag",
    "x-p-telemetry-type",
}
# prefix families with open-ended external producers (custom field headers)
EXTERNAL_REQUEST_PREFIXES = ("x-p-meta-",)
# response/beacon headers whose consumer is outside this tree
EXTERNAL_RESPONSE_HEADERS = {"x-p-version"}

_CONSUME_METHODS = {"get", "getone", "getall", "pop"}


class HeaderContractRule(Rule):
    """Two-sided X-P-* header accounting across Python and fastpath.cpp.

    A site *consumes* a header when it reads it (``headers.get(H)``,
    ``headers[H]`` loads, ``H in headers``) and *produces* one when it
    writes it (dict-literal key, ``headers[H] = v`` stores). The C++ side
    classifies lowercase ``"x-p-..."`` comparison literals as consumers
    and ``"X-P-Name: "`` response-emission literals as producers. Every
    consumed header needs a producer (or the external-request allowlist);
    every produced header needs a consumer (or the external-response
    allowlist)."""

    name = "header-contract"
    description = "X-P-* header consumed but never produced, or vice versa"
    rationale = (
        "an orphaned header read is dead protocol surface; an orphaned "
        "write is data silently dropped on the floor at the other end"
    )

    # scan the shipped tree, not tests: test clients play the external role
    def _scan(self, rel: str) -> bool:
        return (
            rel.endswith(".py")
            and (rel.startswith("parseable_tpu/") or rel.startswith("scripts/"))
        )

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        consts = ConstIndex(project)
        produced: dict[str, tuple[str, int]] = {}
        consumed: dict[str, tuple[str, int]] = {}

        def record(table: dict, header: str, rel: str, line: int) -> None:
            table.setdefault(header.lower(), (rel, line))

        for sf in project.files:
            if not self._scan(sf.rel):
                continue
            for node in ast.walk(sf.tree):
                for kind, header, line in self._classify(node, sf, consts):
                    record(produced if kind == "produce" else consumed, header, sf.rel, line)
        for cf in project.csources:
            for line, val in cf.strings:
                name = val.rstrip()
                is_emit = name.endswith(":")
                name = name.rstrip(":").strip()
                if not _HEADER_RE.match(name):
                    continue
                record(produced if is_emit else consumed, name, cf.rel, line)

        for header, (rel, line) in sorted(consumed.items()):
            if header in produced or header in EXTERNAL_REQUEST_HEADERS:
                continue
            if any(header.startswith(p) for p in EXTERNAL_REQUEST_PREFIXES):
                continue
            yield Finding(
                rule=self.name,
                path=rel,
                line=line,
                message=(
                    f"header {header!r} is consumed here but produced nowhere "
                    "in the tree (and is not an allowlisted external request "
                    "header) — dead read or missing producer"
                ),
            )
        for header, (rel, line) in sorted(produced.items()):
            if header in consumed or header in EXTERNAL_RESPONSE_HEADERS:
                continue
            if any(header.startswith(p) for p in EXTERNAL_REQUEST_PREFIXES):
                continue
            if header in EXTERNAL_REQUEST_HEADERS:
                continue  # internal harness producing a request header is fine
            yield Finding(
                rule=self.name,
                path=rel,
                line=line,
                message=(
                    f"header {header!r} is produced here but consumed nowhere "
                    "in the tree — the value is dropped on the floor at the "
                    "other end of the wire"
                ),
            )

    def _classify(
        self, node: ast.AST, sf: SourceFile, consts: ConstIndex
    ) -> Iterable[tuple[str, str, int]]:
        def hdr(expr: ast.AST) -> str | None:
            v = consts.resolve(expr, sf)
            return v if v is not None and _HEADER_RE.match(v) else None

        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _CONSUME_METHODS and node.args:
                h = hdr(node.args[0])
                if h:
                    yield ("consume", h, node.lineno)
        elif isinstance(node, ast.Subscript):
            h = hdr(node.slice)
            if h:
                kind = "produce" if isinstance(node.ctx, ast.Store) else "consume"
                yield (kind, h, node.lineno)
        elif isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            h = hdr(node.left)
            if h:
                yield ("consume", h, node.lineno)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is None:
                    continue
                h = hdr(k)
                if h:
                    yield ("produce", h, k.lineno)


# --------------------------------------------------------------------------
# ticket-drift


_FLIGHT_SERVER_REL = "parseable_tpu/server/flight.py"
_META_PREFIX = "ptpu."


class TicketDriftRule(Rule):
    """Flight ticket kinds and `ptpu.*` schema-metadata keys, both sides.

    Client half: every ``{"kind": "..."}`` dict literal (or
    ``dict(..., kind="...")``) in a module that touches the Flight plane.
    Server half: the string literals ``kind`` is compared against in
    server/flight.py's do_get dispatch. Both directions are errors — an
    unconstructed dispatch arm is dead server code, an undispatched client
    kind is a guaranteed FlightServerError.

    Metadata: the ``ptpu.*`` keys flight.py defines (META_* constants)
    must exactly equal the strip set (``_META_KEYS``) — a written key the
    client strip misses leaks internal metadata into user-facing schemas;
    a stripped key nobody writes is dead wire surface. Stray `ptpu.*`
    literals elsewhere must be one of the defined keys."""

    name = "ticket-drift"
    description = "Flight ticket kind or ptpu.* metadata key drifted between client and server"
    rationale = (
        "the ticket vocabulary IS the data-plane API: an unknown kind "
        "fails every DoGet, a missed metadata key leaks transport innards"
    )

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        by_rel = {sf.rel: sf for sf in project.files}
        server = by_rel.get(_FLIGHT_SERVER_REL)
        if server is None:
            return

        dispatched: dict[str, int] = {}
        for node in ast.walk(server.tree):
            if isinstance(node, ast.Compare) and len(node.comparators) == 1:
                sides = [node.left, node.comparators[0]]
                names = [s for s in sides if attr_chain(s)[-1:] == ["kind"]]
                lits = [
                    s.value
                    for s in sides
                    if isinstance(s, ast.Constant) and isinstance(s.value, str)
                ]
                if names and lits:
                    dispatched.setdefault(lits[0], node.lineno)

        constructed: dict[str, tuple[str, int, str]] = {}
        for sf in project.files:
            if not sf.rel.startswith("parseable_tpu/") or sf.rel == _FLIGHT_SERVER_REL:
                continue
            if "flight" not in sf.text.lower():
                continue  # only modules touching the Flight plane build tickets
            for node in ast.walk(sf.tree):
                kind_val, line = None, None
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (
                            isinstance(k, ast.Constant)
                            and k.value == "kind"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                        ):
                            kind_val, line = v.value, k.lineno
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "dict"
                ):
                    for kw in node.keywords:
                        if (
                            kw.arg == "kind"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)
                        ):
                            kind_val, line = kw.value.value, node.lineno
                if kind_val is not None:
                    ctx = enclosing_context(sf.tree, node)
                    constructed.setdefault(kind_val, (sf.rel, line, ctx))

        for kind, (rel, line, ctx) in sorted(constructed.items()):
            if kind not in dispatched:
                yield Finding(
                    rule=self.name,
                    path=rel,
                    line=line,
                    context=ctx,
                    message=(
                        f"Flight ticket kind {kind!r} is constructed here but "
                        "server/flight.py's do_get never dispatches it — every "
                        "such DoGet fails at the peer"
                    ),
                )
        for kind, line in sorted(dispatched.items()):
            if kind not in constructed and constructed:
                yield Finding(
                    rule=self.name,
                    path=_FLIGHT_SERVER_REL,
                    line=line,
                    message=(
                        f"do_get dispatches ticket kind {kind!r} but no client "
                        "in the tree constructs it — dead dispatch arm"
                    ),
                )

        yield from self._check_meta(project, server)

    def _check_meta(self, project: WireProject, server: SourceFile) -> Iterable[Finding]:
        defined: dict[str, tuple[int, str]] = {}  # key -> (line, const name)
        strip_set: dict[str, int] = {}
        strip_names: dict[str, int] = {}  # _META_KEYS entries given as names
        const_by_name: dict[str, str] = {}
        for node in server.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                tname = node.targets[0].id
                if isinstance(node.value, ast.Constant):
                    v = node.value.value
                    if isinstance(v, bytes):
                        v = v.decode(errors="replace")
                    if isinstance(v, str) and v.startswith(_META_PREFIX):
                        defined[v] = (node.lineno, tname)
                        const_by_name[tname] = v
                elif tname == "_META_KEYS" and isinstance(node.value, (ast.Tuple, ast.List)):
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant):
                            v = e.value
                            v = v.decode(errors="replace") if isinstance(v, bytes) else v
                            if isinstance(v, str):
                                strip_set[v] = e.lineno
                        elif isinstance(e, ast.Name):
                            strip_names[e.id] = e.lineno
        for nm, ln in strip_names.items():
            if nm in const_by_name:
                strip_set[const_by_name[nm]] = ln
        if not defined and not strip_set:
            return
        for key, (line, tname) in sorted(defined.items()):
            if key not in strip_set:
                yield Finding(
                    rule=self.name,
                    path=_FLIGHT_SERVER_REL,
                    line=line,
                    message=(
                        f"schema-metadata key {key!r} ({tname}) is written "
                        "server-side but missing from _META_KEYS — the client "
                        "strip leaks it into user-facing schemas"
                    ),
                )
        for key, line in sorted(strip_set.items()):
            if key not in defined:
                yield Finding(
                    rule=self.name,
                    path=_FLIGHT_SERVER_REL,
                    line=line,
                    message=(
                        f"_META_KEYS strips {key!r} but no server-side write "
                        "defines that key — dead strip entry (typo'd key?)"
                    ),
                )
        # stray ptpu.* literals outside flight.py must be defined keys
        for sf in project.files:
            if sf.rel == _FLIGHT_SERVER_REL or not sf.rel.startswith("parseable_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Constant):
                    continue
                v = node.value
                v = v.decode(errors="replace") if isinstance(v, bytes) else v
                if isinstance(v, str) and v.startswith(_META_PREFIX) and v not in defined:
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        context=enclosing_context(sf.tree, node),
                        message=(
                            f"ptpu.* metadata literal {v!r} matches no key "
                            "defined in server/flight.py — typo'd wire key"
                        ),
                    )
