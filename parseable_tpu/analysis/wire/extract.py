"""wlint shared extractors: both halves of each wire contract, from source.

Everything here is pure extraction — no judgement. Rules diff the tables
these functions return. The extraction idioms:

- string resolution follows constants one step: module-level
  ``NAME = "literal"`` assigns, enclosing-function locals, for-loop
  bindings over literal tuple tables (app.py's crud_routes loop), `+`
  concatenation, and f-strings (unresolvable interpolations become the
  ``{_}`` placeholder segment);
- imports are honored so a producer writing ``FO.H_TAG`` and a consumer
  reading the literal ``"X-P-Owner-Tag"`` land on the same header;
- aiohttp route templates (`{name}`, `{name:regex}`) match client-side
  path templates segment-by-segment; a client ``{_}`` placeholder matches
  any one template segment.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from parseable_tpu.analysis.framework import Project, SourceFile
from parseable_tpu.analysis.wire.csource import CSourceFile

PLACEHOLDER = "{_}"

_ADD_ROUTE = {
    "add_get": "GET",
    "add_post": "POST",
    "add_put": "PUT",
    "add_delete": "DELETE",
}


@dataclass
class WireProject(Project):
    """plint's Project plus the C/C++ translation units wire rules diff
    against (today: parseable_tpu/native/fastpath.cpp)."""

    csources: list[CSourceFile] = field(default_factory=list)


@dataclass(frozen=True)
class Route:
    method: str
    template: str  # "/api/v1/logstream/{name}"
    rel: str
    line: int
    handler: str  # display only


@dataclass(frozen=True)
class ClientPath:
    template: str  # "/api/v1/internal/staging/{_}"
    method: str | None  # None when the call site doesn't name one
    rel: str
    line: int


# ------------------------------------------------------------ constant maps


def module_constants(sf: SourceFile) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` (str or bytes, decoded) assigns."""
    out: dict[str, str] = {}
    for node in sf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
        ):
            v = node.value.value
            if isinstance(v, bytes):
                try:
                    v = v.decode()
                except UnicodeDecodeError:
                    continue
            if isinstance(v, str):
                out[node.targets[0].id] = v
    return out


def _rel_to_module(rel: str) -> str:
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


def import_map(sf: SourceFile) -> dict[str, str]:
    """local alias -> dotted module it refers to (``import x.y as z`` and
    ``from pkg import mod [as alias]`` both land here; ``from mod import
    NAME`` maps NAME to ``mod.NAME`` so constant lookups can split it)."""
    out: dict[str, str] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


class ConstIndex:
    """Project-wide constant resolution: Name/Attribute nodes -> string,
    following module-level constants across imports."""

    def __init__(self, project: Project):
        self.by_module: dict[str, dict[str, str]] = {}
        self.imports: dict[str, dict[str, str]] = {}
        for sf in project.files:
            mod = _rel_to_module(sf.rel)
            self.by_module[mod] = module_constants(sf)
            self.imports[mod] = import_map(sf)

    def _lookup(self, dotted: str) -> str | None:
        mod, _, name = dotted.rpartition(".")
        consts = self.by_module.get(mod)
        return consts.get(name) if consts else None

    def resolve(self, node: ast.AST, sf: SourceFile) -> str | None:
        """Constant / Name / alias.NAME -> string value, or None."""
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bytes):
                try:
                    return v.decode()
                except UnicodeDecodeError:
                    return None
            return v if isinstance(v, str) else None
        mod = _rel_to_module(sf.rel)
        if isinstance(node, ast.Name):
            local = self.by_module.get(mod, {}).get(node.id)
            if local is not None:
                return local
            target = self.imports.get(mod, {}).get(node.id)
            return self._lookup(target) if target else None
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            target = self.imports.get(mod, {}).get(node.value.id)
            if target:
                return self._lookup(f"{target}.{node.attr}")
        return None


# -------------------------------------------------------- string templates


def _loop_candidates(fn: ast.AST, name: str) -> list[str]:
    """Values `name` takes in ``for a, name, c in ((..), (..))`` loops over
    literal tuple tables inside `fn` — the app.py crud_routes idiom."""
    out: list[str] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        tgt, it = node.target, node.iter
        if not isinstance(it, (ast.Tuple, ast.List)):
            continue
        if isinstance(tgt, ast.Name) and tgt.id == name:
            idx = None
        elif isinstance(tgt, ast.Tuple):
            idx = next(
                (
                    i
                    for i, e in enumerate(tgt.elts)
                    if isinstance(e, ast.Name) and e.id == name
                ),
                -1,
            )
            if idx < 0:
                continue
        else:
            continue
        for row in it.elts:
            cell = row if idx is None else None
            if idx is not None and isinstance(row, (ast.Tuple, ast.List)) and idx < len(row.elts):
                cell = row.elts[idx]
            if isinstance(cell, ast.Constant) and isinstance(cell.value, str):
                out.append(cell.value)
    return out


def _local_assigns(fn: ast.AST, name: str) -> list[str]:
    out: list[str] = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out.append(node.value.value)
    return out


def str_templates(
    node: ast.AST,
    sf: SourceFile,
    consts: ConstIndex,
    scope: ast.AST | None = None,
) -> list[str]:
    """Every string value/template `node` can evaluate to, with ``{_}``
    standing in for unresolvable f-string interpolations. Empty list when
    the expression isn't string-shaped at all."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else []
    if isinstance(node, ast.JoinedStr):
        parts: list[list[str]] = [[""]]
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                opts = [str(piece.value)]
            elif isinstance(piece, ast.FormattedValue):
                resolved = consts.resolve(piece.value, sf)
                opts = [resolved if resolved is not None else PLACEHOLDER]
            else:  # pragma: no cover - JoinedStr only holds those two
                opts = [PLACEHOLDER]
            parts = [p + [o] for p in parts for o in opts]
        return ["".join(p) for p in parts]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        lefts = str_templates(node.left, sf, consts, scope)
        rights = str_templates(node.right, sf, consts, scope)
        return [a + b for a in lefts for b in rights]
    if isinstance(node, ast.Name):
        if scope is not None:
            vals = _local_assigns(scope, node.id) or _loop_candidates(scope, node.id)
            if vals:
                return vals
        v = consts.resolve(node, sf)
        return [v] if v is not None else []
    v = consts.resolve(node, sf)
    return [v] if v is not None else []


def scope_of(tree: ast.Module, line: int) -> ast.AST:
    """Innermost function containing `line`, else the module."""
    best: ast.AST = tree
    best_span = float("inf")
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lo, hi = node.lineno, getattr(node, "end_lineno", node.lineno)
            if lo <= line <= hi and hi - lo < best_span:
                best, best_span = node, hi - lo
    return best


# -------------------------------------------------------------- route table


def route_table(project: Project, consts: ConstIndex | None = None) -> list[Route]:
    """The aiohttp route table: every ``r.add_get/add_post/add_put/
    add_delete(path, handler)`` call under parseable_tpu/server/."""
    consts = consts or ConstIndex(project)
    routes: list[Route] = []
    for sf in project.files:
        if not sf.rel.startswith("parseable_tpu/server/"):
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _ADD_ROUTE
                and node.args
            ):
                continue
            handler = ""
            if len(node.args) > 1:
                h = node.args[1]
                handler = h.id if isinstance(h, ast.Name) else getattr(h, "attr", "")
            scope = scope_of(sf.tree, node.lineno)
            for tpl in str_templates(node.args[0], sf, consts, scope):
                routes.append(
                    Route(
                        method=_ADD_ROUTE[node.func.attr],
                        template=tpl,
                        rel=sf.rel,
                        line=node.lineno,
                        handler=handler,
                    )
                )
    return routes


_TEMPLATE_SEG_RE = re.compile(r"^\{([A-Za-z_][A-Za-z0-9_]*)(?::(.*))?\}$")


def _segments(path: str) -> list[str]:
    return [s for s in path.split("/")][1:] if path.startswith("/") else path.split("/")


def path_matches(route_template: str, client_template: str) -> bool:
    """Does a client path template resolve against an aiohttp route
    template? Segment-wise: a route ``{name}``/``{name:re}`` segment
    matches any client segment (regexes are checked against literal client
    segments); a client ``{_}`` placeholder matches any route segment. A
    client template ending in ``/`` is a prefix probe (the C++ classifier's
    ``/api/v1/logstream/`` compare) and matches when the route extends it
    by exactly its templated tail."""
    if client_template.endswith("/") and len(client_template) > 1:
        prefix = _segments(client_template[:-1])
        rsegs = _segments(route_template)
        if len(rsegs) <= len(prefix):
            return False
        return all(
            _seg_match(r, c) for r, c in zip(rsegs[: len(prefix)], prefix)
        )
    rsegs, csegs = _segments(route_template), _segments(client_template)
    if len(rsegs) != len(csegs):
        return False
    return all(_seg_match(r, c) for r, c in zip(rsegs, csegs))


def _seg_match(route_seg: str, client_seg: str) -> bool:
    m = _TEMPLATE_SEG_RE.match(route_seg)
    if m:
        if not client_seg:
            return False
        if client_seg == PLACEHOLDER or client_seg.startswith("{"):
            return True
        rx = m.group(2)
        if rx:
            try:
                return re.fullmatch(rx, client_seg) is not None
            except re.error:  # pragma: no cover - bad route regex
                return True
        return True
    return client_seg == route_seg or client_seg == PLACEHOLDER


# ------------------------------------------------------------ client paths

_PATH_HINT_RE = re.compile(r"/api/|^/v1/")


def client_paths(sf: SourceFile, consts: ConstIndex) -> list[ClientPath]:
    """Server-path templates a client file constructs: constants and
    f-strings containing ``/api/`` (anything before it — the domain
    interpolation — is dropped) or rooted at ``/v1/``. Query strings are
    stripped; module-level constant *definitions* are skipped (they're
    resolved at their use sites instead)."""
    module_def_lines = {
        node.lineno
        for node in sf.tree.body
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant)
    }
    out: list[ClientPath] = []
    seen: set[tuple[int, str]] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.Constant, ast.JoinedStr)):
            continue
        if isinstance(node, ast.Constant) and not isinstance(node.value, str):
            continue
        if node.lineno in module_def_lines and isinstance(node, ast.Constant):
            continue
        scope = scope_of(sf.tree, node.lineno)
        for tpl in str_templates(node, sf, consts, scope):
            if not _PATH_HINT_RE.search(tpl):
                continue
            idx = tpl.find("/api/")
            path = tpl[idx:] if idx >= 0 else tpl
            path = path.split("?", 1)[0]
            # prose mentioning a path (docstrings, log messages) is not a
            # request: a real path template has no whitespace
            if any(c.isspace() for c in path):
                continue
            if not path.startswith("/") or len(_segments(path)) < 2:
                continue
            method = _call_method_around(sf.tree, node)
            key = (node.lineno, path)
            if key in seen:
                continue
            seen.add(key)
            out.append(ClientPath(template=path, method=method, rel=sf.rel, line=node.lineno))
    return out


_METHOD_NAMES = {
    "get": "GET",
    "post": "POST",
    "put": "PUT",
    "delete": "DELETE",
    "request": None,
}


def _call_method_around(tree: ast.Module, target: ast.AST) -> str | None:
    """HTTP method of the call the path literal appears in, when the call
    spells it: ``session.get(url)`` -> GET, ``http_json("POST", url)`` ->
    POST. None when the call shape doesn't say."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not any(sub is target for a in node.args for sub in ast.walk(a)) and not any(
            sub is target for kw in node.keywords for sub in ast.walk(kw.value)
        ):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr in _METHOD_NAMES:
            return _METHOD_NAMES[node.func.attr]
        if node.args and isinstance(node.args[0], ast.Constant):
            v = node.args[0].value
            if isinstance(v, str) and v.upper() in ("GET", "POST", "PUT", "DELETE"):
                return v.upper()
    return None


def cpp_route_literals(cf: CSourceFile) -> list[tuple[int, str]]:
    """The edge classifier's route strings: every C++ string literal that
    looks like a server path (``/api/...`` or ``/v1/...``)."""
    out = []
    for line, val in cf.strings:
        if val.startswith("/api/") or (val.startswith("/v1/") and len(val) > 4):
            out.append((line, val))
    return out
