"""wlint C/C++ source model: token-level string extraction, no libclang.

The wire contracts' C++ half lives in string literals — route prefixes the
edge classifier compares against, lowercased header names, response-header
emission like ``"X-P-Trace-Id: "``. A full C++ parse buys nothing for that;
what matters is extracting every string literal with its line number while
ignoring comments and char literals, plus the `extern "C"` block spans so
rules can tell exported-surface strings from internal ones. `.clang-tidy`
remains the optional deep pass (nsan); this scanner is the cheap, always-on
one.

Suppression syntax mirrors plint's, on the same line as the finding:

    classify(target);  // wlint: disable=route-drift
"""

from __future__ import annotations

import re
from pathlib import Path

_SUPPRESS_RE = re.compile(r"wlint:\s*disable(?:=([A-Za-z0-9_,-]+))?")


class CSourceFile:
    """One C/C++ translation unit, reduced to what wire rules consume:
    ``strings`` (line, value) outside comments, per-line comment text,
    suppressions, and `extern "C"` line spans."""

    def __init__(self, rel: str, text: str):
        self.rel = rel.replace("\\", "/")
        self.text = text
        self.lines = text.splitlines()
        self.strings: list[tuple[int, str]] = []
        self.comments: dict[int, str] = {}
        self.suppressions: dict[int, set[str] | None] = {}
        self._scan()
        self.extern_c_spans = self._extern_c_spans()

    @classmethod
    def from_path(cls, root: Path, path: Path) -> "CSourceFile":
        rel = path.relative_to(root).as_posix()
        return cls(rel, path.read_text(encoding="utf-8"))

    # ------------------------------------------------------------- scanner

    def _scan(self) -> None:
        """One pass over the text tracking which of five states we are in:
        code, line comment, block comment, string literal, char literal.
        Escapes honored inside literals; raw strings are not used by
        fastpath.cpp and are deliberately out of scope (a raw string would
        be scanned as a plain one — wrong contents, right line)."""
        text = self.text
        i, n, line = 0, len(text), 1
        while i < n:
            ch = text[i]
            nxt = text[i + 1] if i + 1 < n else ""
            if ch == "\n":
                line += 1
                i += 1
            elif ch == "/" and nxt == "/":
                j = text.find("\n", i)
                j = n if j < 0 else j
                self._comment(line, text[i + 2 : j].strip())
                i = j
            elif ch == "/" and nxt == "*":
                j = text.find("*/", i + 2)
                j = n - 2 if j < 0 else j
                body = text[i + 2 : j]
                self._comment(line, body.strip().splitlines()[0] if body.strip() else "")
                line += body.count("\n")
                i = j + 2
            elif ch == '"':
                start_line = line
                j = i + 1
                buf: list[str] = []
                while j < n and text[j] != '"':
                    if text[j] == "\\" and j + 1 < n:
                        esc = text[j + 1]
                        buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0"}.get(esc, esc))
                        j += 2
                    else:
                        if text[j] == "\n":
                            line += 1  # unterminated — keep line count honest
                        buf.append(text[j])
                        j += 1
                self.strings.append((start_line, "".join(buf)))
                i = j + 1
            elif ch == "'":
                j = i + 1
                while j < n and text[j] != "'":
                    j += 2 if text[j] == "\\" else 1
                i = j + 1
            else:
                i += 1

    def _comment(self, line: int, comment: str) -> None:
        self.comments[line] = comment
        m = _SUPPRESS_RE.search(comment)
        if m:
            names = m.group(1)
            self.suppressions[line] = (
                {s.strip() for s in names.split(",") if s.strip()} if names else None
            )

    def _extern_c_spans(self) -> list[tuple[int, int]]:
        """(start_line, end_line) of every `extern "C" { ... }` block, by
        brace-depth matching on the comment/string-stripped text (the same
        approach abicheck.py uses for the ABI diff)."""
        spans: list[tuple[int, int]] = []
        # rebuild a literal-free view so braces inside strings don't count
        clean_lines = list(self.lines)
        for ln, val in self.strings:
            if 1 <= ln <= len(clean_lines) and val:
                clean_lines[ln - 1] = clean_lines[ln - 1].replace('"%s"' % val, '""')
        for idx, raw in enumerate(self.lines):
            # marker detection on the ORIGINAL line (the cleaned view has
            # the "C" literal blanked); depth counting on the cleaned one
            if 'extern "C"' not in raw.split("//")[0]:
                continue
            depth, started = 0, False
            for j in range(idx, len(clean_lines)):
                for ch in clean_lines[j].split("//")[0]:
                    if ch == "{":
                        depth += 1
                        started = True
                    elif ch == "}":
                        depth -= 1
                        if started and depth == 0:
                            spans.append((idx + 1, j + 1))
                            break
                if started and depth == 0:
                    break
        return spans

    # ------------------------------------------------------------- queries

    def is_suppressed(self, rule: str, line: int) -> bool:
        if line not in self.suppressions:
            return False
        names = self.suppressions[line]
        return names is None or rule in names

    def snippet(self, line: int) -> str:
        from parseable_tpu.analysis.framework import normalize_snippet

        if 1 <= line <= len(self.lines):
            # C line comments use //, not # — strip them before normalizing
            src = self.lines[line - 1].split("//")[0]
            return normalize_snippet(src)
        return ""
