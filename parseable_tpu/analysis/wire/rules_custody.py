"""wlint ffi-custody: owned ABI pointers must reach their paired release.

Static complement to nsan's runtime `ptpu_cols_live()==0` /
`ptpu_telem_live()==0` / `ptpu_edge_live()==0` session gates. The ABI's
ownership contract lives in one table — `abicheck.OWNERSHIP` — mapping each
producer export to its release entry points. This rule finds every ctypes
call of a producer (`lib.ptpu_flatten_ndjson(...)`, `_lib.ptpu_telem_drain(
...)`) and, with the resource-leak rule's path logic, demands the owned
handle reaches a release on all paths:

- a release call inside a ``finally:`` discharges every path;
- a straight-line release is fine unless a ``return``/``raise`` sits
  between acquisition and release — *unless* that early exit is the
  decline-guard idiom (guarded by an ``if`` whose test reads the rc or
  the handle, e.g. ``if rc != 0: return None`` — on that path the C side
  never allocated);
- custody transfer is fine: returning the handle, storing it on
  ``self``, or handing it to `_ColumnarBufs`/`_import_columnar`
  (abicheck.CUSTODY_SINKS) whose destructor owns the free;
- handing the handle to another function is fine when that callee —
  resolved through the PR 5 call graph — transitively reaches the
  release (an unresolvable callee is assumed to take custody: this rule
  errs quiet, the runtime live-gates err loud).

``ctypes.*`` helpers (string_at/cast/byref) never take custody.

A second, Python-level check covers the edge wrappers: a function claiming
a request with ``.edge_next(...)`` must answer it — lexically reach an
``edge_respond*`` call or hand the rid to a callee.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from parseable_tpu.analysis.callgraph import CallGraph, build_call_graph
from parseable_tpu.analysis.framework import (
    Finding,
    Rule,
    SourceFile,
    attr_chain,
    enclosing_context,
)
from parseable_tpu.analysis.nsan.abicheck import CUSTODY_SINKS, OWNERSHIP
from parseable_tpu.analysis.wire.extract import WireProject

_RESPOND_TAILS = {
    "edge_respond",
    "edge_respond_ack",
    "edge_respond_raw",
    "ptpu_edge_respond",
    "ptpu_edge_respond_ack",
    "ptpu_edge_respond_raw",
}


def _own_statements(fn) -> list[ast.stmt]:
    """fn's own statements top-down, nested defs excluded (the resource-leak
    rule's traversal — a nested function is its own custody scope)."""
    own: list[ast.stmt] = []
    stack = list(fn.body)
    while stack:
        s = stack.pop(0)
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        own.append(s)
        for child in ast.iter_child_nodes(s):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                stack.append(child)
    return own


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _byref_handle_names(call: ast.Call) -> set[str]:
    out: set[str] = set()
    for a in call.args:
        if isinstance(a, ast.Call):
            chain = attr_chain(a.func)
            if chain and chain[-1] == "byref" and a.args:
                out |= _names_in(a.args[0])
    return out


_POINTER_CTORS = {"c_void_p", "c_char_p"}


def _pointer_locals(own: list[ast.stmt]) -> set[str]:
    """Names bound to ctypes pointer objects (``out = ctypes.c_void_p()``,
    ``p = ctypes.POINTER(T)()``) — the byref args that can carry ownership,
    as opposed to scalar out-params (c_uint64 counts, lengths, row counts)."""
    out: set[str] = set()
    for s in own:
        if not (isinstance(s, ast.Assign) and isinstance(s.value, ast.Call)):
            continue
        fn = s.value.func
        chain = attr_chain(fn)
        is_ptr = bool(chain) and chain[-1] in _POINTER_CTORS
        if not is_ptr and isinstance(fn, ast.Call):
            inner = attr_chain(fn.func)
            is_ptr = bool(inner) and inner[-1] == "POINTER"
        if is_ptr:
            for t in s.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _bare_names(root: ast.AST) -> set[str]:
    """Names occurring bare (not as the base of an attribute read): in
    ``return out, int(n.value)`` only ``out`` is bare — ``n.value`` reads a
    scalar copy out of the ctypes object, it does not hand over ``n``."""
    bare: set[str] = set()

    def rec(n: ast.AST, parent: ast.AST | None) -> None:
        if isinstance(n, ast.Name) and not isinstance(parent, ast.Attribute):
            bare.add(n.id)
        for c in ast.iter_child_nodes(n):
            rec(c, n)

    rec(root, None)
    return bare


def _mentions_release(tree: ast.AST, releases: tuple[str, ...]) -> bool:
    tails = set(releases) | CUSTODY_SINKS
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in tails:
            return True
        if isinstance(node, ast.Name) and node.id in tails:
            return True
    return False


class FfiCustodyRule(Rule):
    """See module docstring."""

    name = "ffi-custody"
    description = "owned ABI pointer does not reach its paired release on all paths"
    rationale = (
        "the runtime live-gates only catch a leak the test suite happens to "
        "execute; the static pairing catches the early-return path nobody "
        "drives — the exact shape of the native arena leaks PRs 16-18 fixed"
    )

    def applies(self, rel: str) -> bool:
        return False  # finalize-only (needs the call graph)

    def finalize(self, project: WireProject) -> Iterable[Finding]:
        graph = build_call_graph(project)
        by_loc: dict[tuple[str, int], str] = {
            (fi.rel, fi.line): key for key, fi in graph.funcs.items()
        }
        for sf in project.files:
            if not sf.rel.startswith("parseable_tpu/") or not sf.rel.endswith(".py"):
                continue
            for fn in ast.walk(sf.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._check_func(sf, fn, graph, by_loc)

    # ----------------------------------------------------------- ctypes side

    def _check_func(
        self,
        sf: SourceFile,
        fn,
        graph: CallGraph,
        by_loc: dict[tuple[str, int], str],
    ) -> Iterator[Finding]:
        own = _own_statements(fn)
        producers: list[tuple[ast.Call, str]] = []
        for s in own:
            for node in ast.walk(s):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if len(chain) >= 2 and chain[-1] in OWNERSHIP:
                        producers.append((node, chain[-1]))
        if producers:
            for call, export in producers:
                yield from self._check_producer(sf, fn, own, call, export, graph, by_loc)
        yield from self._check_edge_claims(sf, fn, own)

    def _check_producer(
        self,
        sf: SourceFile,
        fn,
        own: list[ast.stmt],
        call: ast.Call,
        export: str,
        graph: CallGraph,
        by_loc: dict[tuple[str, int], str],
    ) -> Iterator[Finding]:
        releases, kind = OWNERSHIP[export]
        byref_names = _byref_handle_names(call)
        if kind == "claim":
            # a claim token is a scalar (request id); any byref out-param
            # can carry it
            handles = set(byref_names)
        else:
            # ownership rides the pointer-typed out-params only; scalar
            # out-params (lengths, row counts) are copies
            ptrs = _pointer_locals(own)
            handles = (byref_names & ptrs) or set(byref_names)
        rc_names: set[str] = set()
        stored = False
        returned_raw = False
        # the statement that binds the producer's value
        for s in own:
            if any(n is call for n in ast.walk(s)):
                if isinstance(s, ast.Assign):
                    for t in s.targets:
                        if isinstance(t, ast.Name):
                            if kind == "handle" and not handles:
                                handles.add(t.id)
                            else:
                                rc_names.add(t.id)
                        elif isinstance(t, (ast.Attribute, ast.Subscript)):
                            stored = True
                elif isinstance(s, (ast.Return, ast.Expr)) and kind == "handle":
                    returned_raw = isinstance(s, ast.Return)
                break
        guard_names = handles | rc_names
        ctx = enclosing_context(sf.tree, fn) or fn.name

        if kind == "handle" and not handles:
            if stored or returned_raw:
                return  # custody moved to the holder / the caller
            yield self._finding(
                sf,
                call.lineno,
                ctx,
                f"{export}() returns an owned {kind} that is neither bound, "
                "stored, nor returned — it can never be released",
            )
            return

        release_lines: list[int] = []
        finally_release = False
        escapes = False
        for s in own:
            if isinstance(s, ast.Try):
                for b in s.finalbody:
                    for sub in ast.walk(b):
                        if self._is_release(sub, releases, handles):
                            finally_release = True
        for s in own:
            for sub in ast.walk(s):
                if self._is_release(sub, releases, handles):
                    release_lines.append(sub.lineno)
                elif isinstance(sub, ast.Return) and sub.value is not None:
                    # a claim token escapes via any mention (returning
                    # rid.value IS the transfer); a pointer escapes only
                    # bare (returning n.value copies a scalar out)
                    mentioned = (
                        _names_in(sub.value)
                        if kind == "claim"
                        else _bare_names(sub.value)
                    )
                    if handles & mentioned and not self._is_guarded(
                        own, sub, guard_names
                    ):
                        escapes = True  # handle handed to the caller
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, (ast.Attribute, ast.Subscript)) and (
                            handles & _names_in(sub.value)
                        ):
                            escapes = True  # stored: owner is elsewhere now
                elif isinstance(sub, ast.Call) and sub is not call:
                    fchain = attr_chain(sub.func)
                    if not fchain or fchain[0] == "ctypes" or fchain[-1] == "byref":
                        continue
                    arg_names: set[str] = set()
                    for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                        arg_names |= _names_in(a)
                    if not (handles & arg_names):
                        continue
                    if fchain[-1] in releases:
                        continue  # already counted
                    if fchain[-1] in CUSTODY_SINKS:
                        escapes = True
                    elif self._callee_discharges(sub, releases, graph, by_loc, sf):
                        escapes = True

        if finally_release or escapes:
            return
        if not release_lines:
            yield self._finding(
                sf,
                call.lineno,
                ctx,
                f"{export}() hands this function an owned {kind} but no "
                f"paired release ({'/'.join(releases)}) is reachable from it",
            )
            return
        first_release = min(release_lines)
        for s in own:
            for sub in ast.walk(s):
                if (
                    isinstance(sub, (ast.Return, ast.Raise))
                    and call.lineno < sub.lineno < first_release
                    and not self._is_guarded(own, sub, guard_names)
                ):
                    yield self._finding(
                        sf,
                        call.lineno,
                        ctx,
                        f"{export}()'s owned {kind} leaks on the early exit at "
                        f"line {sub.lineno} (release only runs on the "
                        "fall-through path): use `finally:` or guard the exit "
                        "on the rc/handle",
                    )
                    return

    @staticmethod
    def _is_release(node: ast.AST, releases: tuple[str, ...], handles: set[str]) -> bool:
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            return False
        if node.func.attr not in releases:
            return False
        if not handles:
            return True
        args = set()
        for a in node.args:
            args |= _names_in(a)
        return bool(handles & args) or not node.args

    @staticmethod
    def _is_guarded(own: list[ast.stmt], exit_stmt: ast.AST, guard_names: set[str]) -> bool:
        """True when `exit_stmt` sits under an `if` whose test reads an rc or
        handle name — the decline-guard idiom (`if rc != 0: return None`)."""
        if not guard_names:
            return False
        for s in own:
            if isinstance(s, ast.If) and guard_names & _names_in(s.test):
                for sub in ast.walk(s):
                    if sub is exit_stmt:
                        return True
        return False

    def _callee_discharges(
        self,
        callsite: ast.Call,
        releases: tuple[str, ...],
        graph: CallGraph,
        by_loc: dict[tuple[str, int], str],
        sf: SourceFile,
    ) -> bool:
        """Does the callee (resolved via the call graph, BFS two hops down)
        lexically reach the paired release or a custody sink? Unresolvable
        callees are assumed to take custody — see module docstring."""
        tail = attr_chain(callsite.func)[-1]
        start_keys = [
            key
            for key, fi in graph.funcs.items()
            if fi.name == tail and (fi.rel == sf.rel or ":" not in tail)
        ] or [key for key, fi in graph.funcs.items() if fi.name == tail]
        if not start_keys:
            return True  # not in the graph: external/unknown — assume custody
        seen: set[str] = set()
        frontier = list(start_keys)
        for _ in range(3):
            nxt: list[str] = []
            for key in frontier:
                if key in seen:
                    continue
                seen.add(key)
                fi = graph.funcs.get(key)
                if fi is None:
                    continue
                if _mentions_release(fi.node, releases):
                    return True
                nxt.extend(e.callee for e in fi.edges)
            frontier = nxt
        return False

    # ------------------------------------------------------------- edge side

    def _check_edge_claims(self, sf: SourceFile, fn, own: list[ast.stmt]) -> Iterator[Finding]:
        """Python-level claim/respond pairing for the edge wrappers."""
        if sf.rel == "parseable_tpu/native/__init__.py":
            return  # the ctypes-level check above already covers the wrappers
        claims: list[tuple[int, str | None]] = []
        for s in own:
            if not isinstance(s, ast.Assign) or not isinstance(s.value, ast.Call):
                continue
            chain = attr_chain(s.value.func)
            if not chain or chain[-1] != "edge_next":
                continue
            rid: str | None = None
            tgt = s.targets[0]
            if isinstance(tgt, (ast.Tuple, ast.List)) and len(tgt.elts) >= 2:
                if isinstance(tgt.elts[1], ast.Name):
                    rid = tgt.elts[1].id
            elif isinstance(tgt, ast.Name):
                rid = tgt.id
            claims.append((s.value.lineno, rid))
        if not claims:
            return
        responds = False
        rid_escapes = False
        rid_names = {r for _, r in claims if r}
        for s in own:
            for sub in ast.walk(s):
                if not isinstance(sub, ast.Call):
                    continue
                chain = attr_chain(sub.func)
                if chain and chain[-1] in _RESPOND_TAILS:
                    responds = True
                elif chain and rid_names:
                    for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                        if rid_names & _names_in(a):
                            rid_escapes = True
        if responds or rid_escapes:
            return
        line, rid = claims[0]
        ctx = enclosing_context(sf.tree, fn) or fn.name
        yield self._finding(
            sf,
            line,
            ctx,
            "edge_next() claims a request here but this function neither "
            "responds (edge_respond*/ack/raw) nor hands the rid to a callee "
            "— the claimed request can never drain and edge_live() sticks",
        )

    # ---------------------------------------------------------------- misc

    def _finding(self, sf: SourceFile, line: int, ctx: str, message: str) -> Finding:
        return Finding(
            rule=self.name, path=sf.rel, line=line, context=ctx, message=message
        )
