"""plint — parseable_tpu's AST + call-graph concurrency & invariant gate.

Run it as `python -m parseable_tpu.analysis` (wired into
scripts/check_green.sh after tier-1; `--changed` + result cache by
default there, PLINT_FULL=1 for the authoritative full run). See
framework.py for the machinery, rules.py / rules_interproc.py for the rule
catalog, callgraph.py for the whole-program symbol table + call graph, and
the README "Static analysis" section for the workflow (suppressions,
baseline policy, lock-order annotations, adding a rule).
"""

from parseable_tpu.analysis.callgraph import CallGraph, build_call_graph
from parseable_tpu.analysis.framework import (
    AnalysisReport,
    Finding,
    Project,
    Rule,
    SourceFile,
    run_analysis,
)
from parseable_tpu.analysis.rules import DEFAULT_RULES

__all__ = [
    "AnalysisReport",
    "CallGraph",
    "DEFAULT_RULES",
    "Finding",
    "Project",
    "Rule",
    "SourceFile",
    "build_call_graph",
    "run_analysis",
]
