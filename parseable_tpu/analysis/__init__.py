"""plint — parseable_tpu's AST-based concurrency & invariant lint gate.

Run it as `python -m parseable_tpu.analysis` (wired into
scripts/check_green.sh after tier-1). See framework.py for the machinery,
rules.py for the rule catalog, and the README "Static analysis" section for
the workflow (suppressions, baseline policy, adding a rule).
"""

from parseable_tpu.analysis.framework import (
    AnalysisReport,
    Finding,
    Project,
    Rule,
    SourceFile,
    run_analysis,
)
from parseable_tpu.analysis.rules import DEFAULT_RULES

__all__ = [
    "AnalysisReport",
    "DEFAULT_RULES",
    "Finding",
    "Project",
    "Rule",
    "SourceFile",
    "run_analysis",
]
