"""nsan ABI-drift checker: fastpath.cpp extern "C" decls vs ctypes bindings.

The FFI boundary is enforced by nothing at runtime: ctypes happily calls a
function with the wrong arity, the wrong pointer width, or — the classic —
no declared `restype`, which silently defaults to c_int and truncates
64-bit pointers/lengths to 32 bits on this ABI. This pass makes the two
sides of the boundary diff-able:

- `parse_exports`  — regex+brace scan of fastpath.cpp's `extern "C"`
  blocks into (name, return type, arg types) declarations;
- `parse_bindings` — AST scan of native/__init__.py's `_bind*` functions
  into (name, restype, argtypes) declarations;
- `run_abicheck`   — the diff, as plint `Finding`s gated against the
  shared empty baseline (`.nsan-baseline.json`).

Rules emitted: nsan-abi-unbound-export, nsan-abi-unexported-binding,
nsan-abi-missing-restype, nsan-abi-missing-argtypes, nsan-abi-arity,
nsan-abi-type.

Type compatibility is deliberately coarse where ctypes itself is coarse:
`c_void_p` may stand in for any C pointer (that is how opaque handles and
numpy `.ctypes.data_as` buffers cross), `c_char_p` only for byte pointers
(char/uint8_t/int8_t), `POINTER(T)` must match the pointee width, and
scalars must match width and signedness exactly. A void return REQUIRES an
explicit `restype = None` — an absent restype is a finding even for
int-returning functions, because "explicit everywhere" is the only policy
a checker can hold the line on.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from parseable_tpu.analysis.framework import Finding, normalize_snippet

CPP_REL = "parseable_tpu/native/fastpath.cpp"
PY_REL = "parseable_tpu/native/__init__.py"

# ------------------------------------------------------------- ownership
#
# The ABI's custody contract, one row per exported producer that hands the
# caller an owned resource: which release entry points discharge it, and
# what shape the resource takes ("buffer" = an out-pointer filled via
# byref(), "handle" = an opaque value the producer returns, "claim" = a
# request id that must be answered). nsan's runtime `*_live()==0` gates
# check the same contract dynamically; wlint's ffi-custody rule checks it
# statically on the call graph, and both read this table so the pairing
# lives in exactly one place.

OWNERSHIP: dict[str, tuple[tuple[str, ...], str]] = {
    "ptpu_flatten_ndjson": (("ptpu_free",), "buffer"),
    "ptpu_otel_logs_ndjson": (("ptpu_free",), "buffer"),
    "ptpu_flatten_columnar": (("ptpu_cols_free",), "handle"),
    "ptpu_flatten_columnar_sharded": (("ptpu_cols_free",), "handle"),
    "ptpu_otel_logs_columnar": (("ptpu_cols_free",), "handle"),
    "ptpu_otel_logs_columnar_sharded": (("ptpu_cols_free",), "handle"),
    "ptpu_otel_metrics_columnar": (("ptpu_cols_free",), "handle"),
    "ptpu_otel_traces_columnar": (("ptpu_cols_free",), "handle"),
    "ptpu_telem_drain": (("ptpu_telem_free",), "buffer"),
    "ptpu_hll_create": (("ptpu_hll_free",), "handle"),
    "ptpu_edge_next": (
        ("ptpu_edge_respond", "ptpu_edge_respond_ack", "ptpu_edge_respond_raw"),
        "claim",
    ),
}

# Python-side constructs that take over custody of a columnar handle: once
# the raw pointer is handed to one of these, its __del__/internal finally
# owns the ptpu_cols_free call.
CUSTODY_SINKS = {"_ColumnarBufs", "_import_columnar"}

# ---------------------------------------------------------------- C side


@dataclass
class CDecl:
    name: str
    ret: str  # canonical type token (see _canon_c_type)
    args: list[str]
    line: int
    raw: str = ""  # first declaration line, for snippets/messages


_SCALARS = {
    "void": "void",
    "char": "i8",
    "int8_t": "i8",
    "uint8_t": "u8",
    "int32_t": "i32",
    "uint32_t": "u32",
    "int64_t": "i64",
    "uint64_t": "u64",
    "int": "int",
    "unsigned": "uint",
    "unsigned int": "uint",
    "long long": "i64",
    "unsigned long long": "u64",
    "double": "double",
    "float": "float",
}


def _canon_c_type(text: str) -> str:
    """Canonical token for one C type: scalars map through _SCALARS, one
    level of pointer becomes `ptr:<pointee>`, two or more become
    `ptr:ptr`. const and whitespace are erased."""
    stars = text.count("*")
    base = re.sub(r"\bconst\b", " ", text.replace("*", " "))
    base = " ".join(base.split())
    tok = _SCALARS.get(base, base or "?")
    if stars == 0:
        return tok
    if stars == 1:
        return f"ptr:{tok}"
    return "ptr:ptr"


def _split_params(params: str) -> list[str]:
    params = params.strip()
    if not params or params == "void":
        return []
    out = []
    for piece in params.split(","):
        piece = " ".join(piece.split())
        # strip the trailing parameter name (an identifier not part of the
        # type); "void** out" -> "void**", "uint64_t n" -> "uint64_t"
        m = re.match(r"^(.*?[\s*])([A-Za-z_][A-Za-z0-9_]*)$", piece)
        ty = m.group(1) if m else piece
        out.append(_canon_c_type(ty))
    return out


def _extern_c_blocks(text: str) -> list[tuple[int, int]]:
    """(start_offset, end_offset) of every `extern "C" { ... }` body,
    brace-depth matched (the blocks contain nested braces throughout)."""
    blocks = []
    for m in re.finditer(r'extern\s+"C"\s*\{', text):
        depth = 1
        i = m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        blocks.append((m.end(), i - 1))
    return blocks


_FUNC_RE = re.compile(
    r"^[ \t]*((?:const[ \t]+)?[A-Za-z_][A-Za-z0-9_]*(?:[ \t]+[A-Za-z_][A-Za-z0-9_]*)*[ \t*]*?)"
    r"[ \t]+\**[ \t]*(ptpu_[A-Za-z0-9_]+)[ \t]*\(([^)]*)\)[ \t\n]*\{",
    re.M | re.S,
)


def parse_exports(text: str) -> dict[str, CDecl]:
    """Every `ptpu_*` function DEFINED inside an extern "C" block. static
    helpers are skipped (not exported); so is anything outside a block."""
    blocks = _extern_c_blocks(text)
    decls: dict[str, CDecl] = {}
    for m in _FUNC_RE.finditer(text):
        if not any(s <= m.start() < e for s, e in blocks):
            continue
        head = " ".join(m.group(1).split())
        if head.startswith("static") or "inline" in head.split():
            continue
        # pointer stars can attach to the head or the name side; count all
        stars_src = m.group(0)[: m.group(0).index(m.group(2))]
        ret_text = head + "*" * (stars_src.count("*") - head.count("*"))
        name = m.group(2)
        line = text.count("\n", 0, m.start()) + 1
        first_line = m.group(0).splitlines()[0].strip()
        decls[name] = CDecl(
            name=name,
            ret=_canon_c_type(ret_text),
            args=_split_params(m.group(3)),
            line=line,
            raw=first_line,
        )
    return decls


# ----------------------------------------------------------- Python side


@dataclass
class PyDecl:
    name: str
    restype: str | None = None  # "None"/"c_uint64"/... ; None = undeclared
    argtypes: list[str] | None = None
    restype_line: int = 0
    argtypes_line: int = 0
    lines: list[int] = field(default_factory=list)  # every reference


def _ctype_token(node: ast.AST) -> str:
    """Textual token for one ctypes expression: `ctypes.c_uint64` ->
    "c_uint64", `ctypes.POINTER(ctypes.c_void_p)` -> "POINTER(c_void_p)",
    `None` -> "None"."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        fn = _ctype_token(node.func)
        inner = ", ".join(_ctype_token(a) for a in node.args)
        return f"{fn}({inner})"
    return "?"


def parse_bindings(text: str) -> dict[str, PyDecl]:
    """Every `<obj>.ptpu_*` attribute touched anywhere in the module, with
    its declared restype/argtypes. Declarations are recognized from
    `X.ptpu_N.restype = ...` / `X.ptpu_N.argtypes = [...]` assignments in
    any function (the `_bind*` family in practice)."""
    tree = ast.parse(text)
    decls: dict[str, PyDecl] = {}

    def decl(name: str) -> PyDecl:
        if name not in decls:
            decls[name] = PyDecl(name=name)
        return decls[name]

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Attribute)
                and t.attr in ("restype", "argtypes")
                and isinstance(t.value, ast.Attribute)
                and t.value.attr.startswith("ptpu_")
            ):
                d = decl(t.value.attr)
                d.lines.append(node.lineno)
                if t.attr == "restype":
                    d.restype = _ctype_token(node.value)
                    d.restype_line = node.lineno
                else:
                    elems = (
                        node.value.elts
                        if isinstance(node.value, (ast.List, ast.Tuple))
                        else []
                    )
                    d.argtypes = [_ctype_token(e) for e in elems]
                    d.argtypes_line = node.lineno
                continue
        if isinstance(node, ast.Attribute) and node.attr.startswith("ptpu_"):
            d = decl(node.attr)
            if getattr(node, "lineno", 0):
                d.lines.append(node.lineno)
    return decls


# ------------------------------------------------------------- the diff

_BYTE_PTRS = {"ptr:i8", "ptr:u8"}

# restype tokens acceptable per canonical C return type
_RET_OK: dict[str, set[str]] = {
    "void": {"None"},
    "u64": {"c_uint64"},
    "u32": {"c_uint32"},
    "i32": {"c_int32"},
    "i64": {"c_longlong", "c_int64"},
    "int": {"c_int"},
    "uint": {"c_uint"},
    "double": {"c_double"},
    "float": {"c_float"},
}

_SCALAR_ARG_OK = {
    "u64": {"c_uint64"},
    "u32": {"c_uint32"},
    "i32": {"c_int32"},
    "i64": {"c_longlong", "c_int64"},
    "int": {"c_int"},
    "uint": {"c_uint"},
    "double": {"c_double"},
    "float": {"c_float"},
}

_PTR_POINTEE_OK = {
    "u64": "POINTER(c_uint64)",
    "i64": "POINTER(c_longlong)",
    "u32": "POINTER(c_uint32)",
    "i32": "POINTER(c_int32)",
    "int": "POINTER(c_int)",
    "uint": "POINTER(c_uint)",
    "void": "POINTER(None)",
    "ptr": "POINTER(c_void_p)",
}


def _ret_compatible(c_ret: str, restype: str) -> bool:
    if c_ret.startswith("ptr:"):
        if restype == "c_void_p":
            return True
        return restype == "c_char_p" and c_ret in _BYTE_PTRS
    return restype in _RET_OK.get(c_ret, set())


def _arg_compatible(c_arg: str, pytype: str) -> bool:
    if c_arg.startswith("ptr:"):
        if pytype == "c_void_p":
            return True  # opaque handle / raw buffer address
        if pytype == "c_char_p":
            return c_arg in _BYTE_PTRS
        pointee = c_arg.split(":", 1)[1]
        return pytype == _PTR_POINTEE_OK.get(pointee, "?")
    return pytype in _SCALAR_ARG_OK.get(c_arg, set())


def _finding(rule: str, path: str, line: int, msg: str, snippet: str) -> Finding:
    return Finding(
        rule=rule,
        path=path,
        line=line,
        message=msg,
        context="",
        snippet=normalize_snippet(snippet),
    )


def diff_abi(
    exports: dict[str, CDecl], bindings: dict[str, PyDecl], py_lines: list[str]
) -> list[Finding]:
    findings: list[Finding] = []

    def py_snip(line: int) -> str:
        return py_lines[line - 1] if 1 <= line <= len(py_lines) else ""

    for name, c in sorted(exports.items()):
        b = bindings.get(name)
        if b is None:
            findings.append(
                _finding(
                    "nsan-abi-unbound-export",
                    CPP_REL,
                    c.line,
                    f"extern \"C\" {name} is exported but never bound in "
                    f"{PY_REL} — dead ABI surface, or a binding someone "
                    "forgot (a later caller would get implicit c_int "
                    "defaults)",
                    c.raw,
                )
            )
            continue
        ref_line = b.restype_line or b.argtypes_line or (b.lines[0] if b.lines else 1)
        if b.restype is None:
            findings.append(
                _finding(
                    "nsan-abi-missing-restype",
                    PY_REL,
                    ref_line,
                    f"{name} has no declared restype: ctypes defaults to "
                    f"c_int, truncating the C return ({c.ret}) to 32 bits; "
                    "declare it explicitly (None for void)",
                    py_snip(ref_line),
                )
            )
        elif not _ret_compatible(c.ret, b.restype):
            findings.append(
                _finding(
                    "nsan-abi-type",
                    PY_REL,
                    b.restype_line,
                    f"{name} restype {b.restype} is incompatible with the "
                    f"C return type ({c.ret})",
                    py_snip(b.restype_line),
                )
            )
        if b.argtypes is None:
            findings.append(
                _finding(
                    "nsan-abi-missing-argtypes",
                    PY_REL,
                    ref_line,
                    f"{name} has no declared argtypes: ctypes will accept "
                    "any arity and guess conversions per call site",
                    py_snip(ref_line),
                )
            )
        else:
            if len(b.argtypes) != len(c.args):
                findings.append(
                    _finding(
                        "nsan-abi-arity",
                        PY_REL,
                        b.argtypes_line,
                        f"{name} declares {len(b.argtypes)} argtypes but the "
                        f"C signature takes {len(c.args)}",
                        py_snip(b.argtypes_line),
                    )
                )
            else:
                for i, (ca, pa) in enumerate(zip(c.args, b.argtypes)):
                    if not _arg_compatible(ca, pa):
                        findings.append(
                            _finding(
                                "nsan-abi-type",
                                PY_REL,
                                b.argtypes_line,
                                f"{name} argtypes[{i}] is {pa}, incompatible "
                                f"with the C parameter type ({ca})",
                                py_snip(b.argtypes_line),
                            )
                        )
    for name, b in sorted(bindings.items()):
        if name not in exports:
            line = b.restype_line or b.argtypes_line or (b.lines[0] if b.lines else 1)
            findings.append(
                _finding(
                    "nsan-abi-unexported-binding",
                    PY_REL,
                    line,
                    f"{name} is bound/called in {PY_REL} but fastpath.cpp "
                    "exports no such symbol — the dlopen-time AttributeError "
                    "will disable a whole lane at runtime",
                    py_snip(line),
                )
            )
    return findings


def run_abicheck(root: Path) -> tuple[list[Finding], dict]:
    cpp = (root / CPP_REL).read_text(encoding="utf-8")
    py = (root / PY_REL).read_text(encoding="utf-8")
    exports = parse_exports(cpp)
    bindings = parse_bindings(py)
    findings = diff_abi(exports, bindings, py.splitlines())
    stats = {
        "exports": len(exports),
        "bindings": len(bindings),
        "extern_c_blocks": len(_extern_c_blocks(cpp)),
        "declaration_sites": sum(
            (1 if b.restype is not None else 0) + (1 if b.argtypes is not None else 0)
            for b in bindings.values()
        ),
    }
    return findings, stats
