"""nsan reporting: baseline gate + JSON artifact, plint-shaped.

Findings carry plint `Finding` fingerprints, so the baseline file
(`.nsan-baseline.json`, same schema as `.plint-baseline.json`) and the
JSON artifact (`/tmp/nsan.json` by default, `P_NSAN_JSON` to move it) are
diffable with the same tooling. Policy matches plint and psan: the
baseline stays EMPTY — an ABI-drift or sanitizer finding is either fixed
or explicitly suppressed at the site with a justification, never parked.

One artifact, two writers: the CLI gate (`python -m parseable_tpu.analysis
.nsan`) writes it first in check_green.sh, and the `P_NSAN=1` pytest run
merges its own section in afterwards (`merge_report`), so the artifact
carries the whole picture — ABI diff, corpus replay, fuzz-campaign
bookkeeping, and the sanitized in-process test session.
"""

from __future__ import annotations

import json
from pathlib import Path

from parseable_tpu.analysis.framework import Finding, load_baseline

DEFAULT_BASELINE = ".nsan-baseline.json"


def assemble_report(
    findings: list[Finding],
    stats: dict,
    root: Path,
    baseline: str = DEFAULT_BASELINE,
) -> dict:
    baseline_fps = load_baseline(Path(root) / baseline)
    baselined = [
        f
        for f in findings
        if f.fingerprint in baseline_fps or f.legacy_fingerprint in baseline_fps
    ]
    unbaselined = [
        f
        for f in findings
        if f.fingerprint not in baseline_fps
        and f.legacy_fingerprint not in baseline_fps
    ]
    return {
        "tool": "nsan",
        "stats": stats,
        "baselined": [f.to_json() for f in baselined],
        "findings": [f.to_json() for f in unbaselined],
        "clean": not unbaselined,
    }


def write_report(report: dict, path: str) -> None:
    Path(path).write_text(json.dumps(report, indent=2) + "\n", encoding="utf-8")


def merge_report(report: dict, path: str) -> dict:
    """Fold `report` into an existing artifact at `path` (if any): findings
    and baselined concatenate, stats nest under the writer's `section`
    key, `clean` ANDs. Returns the merged dict (also written back)."""
    merged = report
    p = Path(path)
    if p.is_file():
        try:
            prior = json.loads(p.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            prior = None
        if isinstance(prior, dict) and prior.get("tool") == "nsan":
            merged = {
                "tool": "nsan",
                "stats": {**prior.get("stats", {}), **report.get("stats", {})},
                "baselined": prior.get("baselined", []) + report.get("baselined", []),
                "findings": prior.get("findings", []) + report.get("findings", []),
                "clean": bool(prior.get("clean", True)) and bool(report.get("clean")),
            }
    write_report(merged, path)
    return merged


def render_lines(report: dict) -> list[str]:
    lines = []
    for f in report["findings"]:
        ctx = f" [{f['context']}]" if f.get("context") else ""
        lines.append(f"{f['path']}:{f['line']}: {f['rule']}{ctx}: {f['message']}")
    stats = report.get("stats", {})
    n_base = len(report.get("baselined", []))
    base_note = f" ({n_base} baselined)" if n_base else ""
    abi = stats.get("abi", {})
    fuzz = stats.get("fuzz", {})
    lines.append(
        f"nsan: {len(report['findings'])} finding(s){base_note}; "
        f"{abi.get('exports', 0)} exports vs {abi.get('bindings', 0)} bindings "
        f"diffed, corpus replayed {fuzz.get('corpus_replayed', 0)} case(s), "
        f"campaign {stats.get('fuzz_campaign', {}).get('total_cpu_seconds', 0):.0f}s "
        "CPU recorded"
    )
    return lines
