"""nsan pytest plugin: run the native-touching test set against the
sanitizer-instrumented library.

Registered by tests/conftest.py when `P_NSAN=1`:

- `pytest_configure` builds (or reuses) the instrumented library
  (`libptpu_fastpath_ubsan.so` by default) and points
  `parseable_tpu.native` at it via P_NSAN_LIB *before collection imports
  anything native*. In this mode jax can stay loaded: UBSan checks run at
  full fidelity in-process, and the build's -fno-sanitize-recover makes
  UB fatal. UBSan is the default because it is the only mode SOUND under
  late dlopen — ASan's allocator interposition false-aborts on
  std::string buffers allocated by libstdc++'s out-of-line code (see the
  package docstring); ASan/LSan fidelity lives in the preloaded jax-free
  fuzz child (fuzz.py), not here. P_NSAN_SAN=asan remains available for
  targeted stack/global-redzone hunts, with that caveat.
- `pytest_sessionfinish` gc-collects, reads `ptpu_cols_live()` and turns
  a nonzero count into an `nsan-columnar-leak` finding, then MERGES its
  section into the gate artifact (`P_NSAN_JSON`, default /tmp/nsan.json —
  the CLI gate writes the ABI/corpus sections first in check_green.sh),
  flipping a green exit red on unbaselined findings.
"""

from __future__ import annotations

import os
from pathlib import Path

import parseable_tpu
from parseable_tpu.analysis.framework import Finding


def _repo_root() -> Path:
    return Path(parseable_tpu.__file__).resolve().parent.parent


class NsanPytestPlugin:
    def __init__(self):
        self.root = _repo_root()
        self.report: dict | None = None
        self.san_lib: Path | None = None

    # ------------------------------------------------------------ lifecycle

    def pytest_configure(self, config):
        from parseable_tpu.analysis.nsan import build_san_lib
        from parseable_tpu.config import nsan_options

        opts = nsan_options()
        self.san_lib = build_san_lib(self.root, opts["san_mode"])
        if self.san_lib is None:
            raise RuntimeError(
                "nsan: cannot build the sanitized native library "
                "(toolchain missing?) — run without P_NSAN=1"
            )
        os.environ["P_NSAN_LIB"] = str(self.san_lib)
        # asan mode late-dlopens a library whose runtime needs
        # verify_asan_link_order=0 in the PROCESS environment: libasan
        # reads /proc/self/environ, so a mutation here would be invisible —
        # tests/conftest.py re-execs the interpreter with the option before
        # anything imports. If that didn't happen (custom runner), fail
        # fast instead of aborting at first dlopen.
        if opts["san_mode"] == "asan" and "verify_asan_link_order" not in os.environ.get(
            "ASAN_OPTIONS", ""
        ):
            raise RuntimeError(
                "nsan: P_NSAN_SAN=asan but ASAN_OPTIONS lacks "
                "verify_asan_link_order=0 — the sanitized library cannot "
                "dlopen into this process. Run via tests/conftest.py (it "
                "re-execs with the right environment) or set "
                "ASAN_OPTIONS=verify_asan_link_order=0:detect_leaks=0 "
                "before starting pytest."
            )
        config._nsan_lib = str(self.san_lib)

    # ------------------------------------------------------------- wrap-up

    def pytest_sessionfinish(self, session, exitstatus):
        import gc

        from parseable_tpu import native
        from parseable_tpu.analysis.nsan import report as _report
        from parseable_tpu.config import nsan_options

        findings: list[Finding] = []
        gc.collect()
        live = native.columnar_live()
        if live != 0:
            findings.append(
                Finding(
                    rule="nsan-columnar-leak",
                    path="parseable_tpu/native/fastpath.cpp",
                    line=1,
                    message=f"ptpu_cols_live() == {live} after the sanitized "
                    "test session (expected 0): a ColumnarBatch handle was "
                    "never released through ptpu_cols_free",
                    context="",
                    snippet=f"cols_live={live}",
                )
            )
        stats = {
            "sanitized_session": {
                "lib": str(self.san_lib),
                "tests_exitstatus": int(session.exitstatus),
                "cols_live": int(live),
                "native_loaded": bool(native.native_available()),
            }
        }
        self.report = _report.assemble_report(findings, stats, self.root)
        out = nsan_options()["json_path"] or "/tmp/nsan.json"
        try:
            self.report = _report.merge_report(self.report, out)
        except OSError as e:  # pragma: no cover - artifact is best-effort
            print(f"nsan: cannot write report to {out}: {e}")
        if findings and session.exitstatus == 0:
            # judge only THIS session's findings: merged CLI sections were
            # already gated by the CLI process itself
            fresh = {f.fingerprint for f in findings}
            unbaselined = [
                f for f in self.report["findings"] if f["fingerprint"] in fresh
            ]
            if unbaselined:
                session.exitstatus = 1

    def pytest_terminal_summary(self, terminalreporter):
        if self.report is None:
            return
        from parseable_tpu.analysis.nsan import report as _report

        terminalreporter.section("nsan (native safety gate, sanitized build)")
        for line in _report.render_lines(self.report):
            terminalreporter.write_line(line)
