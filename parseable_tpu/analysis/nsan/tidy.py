"""clang-tidy pass over fastpath.cpp (best-effort, toolchain-gated).

The checks ride the repo's `.clang-tidy` (bugprone-*, cert-*,
clang-analyzer-*). This container ships g++ only, so the pass degrades to
a stats note when `clang-tidy` is absent — the .clang-tidy file is still
authoritative config for any environment that has it, and findings gate
against the same empty `.nsan-baseline.json` as every other nsan pass.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from pathlib import Path

from parseable_tpu.analysis.framework import Finding, normalize_snippet

from .abicheck import CPP_REL

# clang-tidy diagnostic: /abs/path.cpp:LINE:COL: warning: message [check-name]
_DIAG_RE = re.compile(
    r"^(?P<path>[^:\n]+):(?P<line>\d+):\d+:\s+(?:warning|error):\s+"
    r"(?P<msg>.*?)\s+\[(?P<check>[A-Za-z0-9.,_-]+)\]\s*$",
    re.M,
)


def tidy_available() -> bool:
    return shutil.which("clang-tidy") is not None


def run_tidy(root: Path) -> tuple[list[Finding], dict]:
    stats: dict = {"ran": False}
    if not tidy_available():
        stats["skip_reason"] = "clang-tidy not installed"
        return [], stats
    cpp = root / CPP_REL
    try:
        proc = subprocess.run(
            ["clang-tidy", str(cpp), "--quiet", "--", "-std=c++17"],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=str(root),
        )
    except (OSError, subprocess.SubprocessError) as e:
        stats["skip_reason"] = f"clang-tidy failed to run: {e}"
        return [], stats
    stats["ran"] = True
    lines = cpp.read_text(encoding="utf-8").splitlines()
    findings: list[Finding] = []
    for m in _DIAG_RE.finditer(proc.stdout):
        try:
            if Path(m.group("path")).resolve() != cpp.resolve():
                continue  # headers outside the repo are not ours to gate
        except OSError:
            continue
        line = int(m.group("line"))
        snippet = lines[line - 1] if 1 <= line <= len(lines) else ""
        # the [check] list can name several; the first is the primary
        check = m.group("check").split(",")[0]
        findings.append(
            Finding(
                rule=f"nsan-tidy-{check}",
                path=CPP_REL,
                line=line,
                message=m.group("msg"),
                context="",
                snippet=normalize_snippet(snippet),
            )
        )
    stats["diagnostics"] = len(findings)
    return findings, stats
