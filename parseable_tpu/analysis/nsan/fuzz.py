"""nsan structured fuzzer: adversarial payloads through the real FFI.

Two halves, one module:

- **Child** (`python -m parseable_tpu.analysis.nsan.fuzz --lib ... `):
  a jax-free interpreter that imports `parseable_tpu.native` against the
  sanitizer-instrumented library (via P_NSAN_LIB) and drives every parse
  entry point — flatten_ndjson, otel_logs_ndjson, both columnar lanes
  (including the zero-copy pyarrow import and its ownership machinery),
  and the HLL/xxh64 batch kernels — with each payload. The parent runs it
  under FULL `LD_PRELOAD=libasan.so`, which jax's import machinery cannot
  survive but this child (numpy + pyarrow only) can: heap redzones, UAF
  detection and LSan all at full fidelity. After every payload the child
  asserts `ptpu_cols_live() == 0` (exit 78 on drift) and, with
  `--leak-check`, finishes with `__lsan_do_recoverable_leak_check` (exit
  77 on leak; libpython's own arenas are suppressed via lsan.supp).

- **Parent** helpers (`replay_corpus`, `fuzz_campaign`, `minimize`): build
  the preload environment, spawn children, classify failures into plint
  `Finding`s (nsan-fuzz-crash / nsan-fuzz-leak / nsan-fuzz-cols-live),
  shrink crashing payloads with a bounded halve-removal loop, and bank
  them in `tests/corpus/nsan/` for tier-1 replay.

Payload generation is seeded (`random.Random(seed)`) and family-based:
every adversarial class the C scanner has to survive gets its own
generator, and a mutation family cross-breeds them with raw byte noise.
The child writes each payload to a scratch file *before* executing it, so
a SIGSEGV/SIGABRT leaves the offending input on disk for minimization.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
from hashlib import sha1
from pathlib import Path

from parseable_tpu.analysis.framework import Finding

from . import asan_runtime, corpus_dir, san_lib_path

CHILD_TIMEOUT = 120  # seconds per child invocation
EXIT_LSAN_LEAK = 77
EXIT_COLS_LIVE = 78
EXIT_ASAN_ERROR = 99  # set via ASAN_OPTIONS exitcode=

# ------------------------------------------------------------ generators


def _rand_scalar(rng: random.Random):
    pick = rng.randrange(6)
    if pick == 0:
        return rng.randrange(-(10**6), 10**6)
    if pick == 1:
        return rng.random() * 10 ** rng.randrange(-300, 300)
    if pick == 2:
        return rng.choice([True, False, None])
    if pick == 3:
        return "".join(chr(rng.randrange(32, 0x2FFF)) for _ in range(rng.randrange(24)))
    if pick == 4:
        return "x" * rng.randrange(0, 300)
    return rng.choice(["", " ", "\t", "null", "true", "-0", "1e999"])


def _rand_record(rng: random.Random, depth: int = 0) -> dict:
    rec = {}
    for _ in range(rng.randrange(1, 8)):
        key = rng.choice(["a", "b", "msg", "ts", "level", "кл", "k" * 40, ""])
        if depth < 3 and rng.random() < 0.25:
            rec[key] = _rand_record(rng, depth + 1)
        elif rng.random() < 0.15:
            rec[key] = [_rand_scalar(rng) for _ in range(rng.randrange(5))]
        else:
            rec[key] = _rand_scalar(rng)
    return rec


def gen_valid_ndjson(rng: random.Random) -> bytes:
    lines = [json.dumps(_rand_record(rng)) for _ in range(rng.randrange(1, 12))]
    return "\n".join(lines).encode()


def gen_truncated_utf8(rng: random.Random) -> bytes:
    base = json.dumps({"msg": "päyload-☃-" + "é" * rng.randrange(1, 20)}).encode()
    # cut inside a multibyte sequence
    cut = rng.randrange(1, len(base))
    while cut > 1 and (base[cut] & 0xC0) != 0x80:
        cut -= 1
    return base[:cut]


def gen_lone_surrogate(rng: random.Random) -> bytes:
    esc = rng.choice(["\\ud800", "\\udfff", "\\ud83d", "\\ude00\\ud800"])
    return ('{"msg": "pre' + esc + 'post", "n": 1}').encode()


def gen_deep_nesting(rng: random.Random) -> bytes:
    depth = rng.randrange(20, 120)
    opener = rng.choice(['{"a":', "["])
    closer = "}" if opener.startswith("{") else "]"
    return (opener * depth + "1" + closer * depth).encode()


def gen_huge_numbers(rng: random.Random) -> bytes:
    nums = [
        "1" * rng.randrange(20, 400),
        "-" + "9" * 309,
        "1e" + str(rng.randrange(300, 9999)),
        "-1e-" + str(rng.randrange(300, 9999)),
        "0." + "0" * 400 + "1",
        "-0",
        str(2**63),
        str(-(2**63) - 1),
    ]
    rec = ",".join(f'"n{i}": {v}' for i, v in enumerate(nums))
    return ("{" + rec + "}").encode()


def gen_nul_bytes(rng: random.Random) -> bytes:
    body = json.dumps({"msg": "a\\u0000b", "k": 1}).encode()
    out = bytearray(body)
    for _ in range(rng.randrange(1, 4)):
        out.insert(rng.randrange(len(out)), 0)
    return bytes(out)


def gen_pathological_escapes(rng: random.Random) -> bytes:
    runs = [
        "\\\\" * rng.randrange(1, 200),
        "\\u00" + rng.choice(["4", "zz", "GG", ""]),
        "\\" + rng.choice(["q", "x41", "u12", "u", ""]),
        "\\n\\t\\r\\f\\b\\/" * rng.randrange(1, 40),
    ]
    return ('{"s": "' + rng.choice(runs) + '"}').encode()


def gen_boundary_split(rng: random.Random) -> bytes:
    full = gen_valid_ndjson(rng)
    if len(full) < 2:
        return full
    return full[: rng.randrange(1, len(full))]


def gen_otel_shaped(rng: random.Random) -> bytes:
    rec = {
        "resourceLogs": [
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name", "value": {"stringValue": "svc"}},
                        {"key": rng.choice(["", "k"]), "value": rng.choice([{}, 1, None])},
                    ]
                },
                "scopeLogs": [
                    {
                        "logRecords": [
                            {
                                "timeUnixNano": rng.choice(
                                    ["1700000000000000000", 17e17, "", None, "-1", "x"]
                                ),
                                "severityText": rng.choice(["INFO", "", None, 3]),
                                "body": rng.choice(
                                    [
                                        {"stringValue": "hello"},
                                        {"kvlistValue": {"values": []}},
                                        {},
                                        None,
                                        "bare",
                                    ]
                                ),
                                "attributes": rng.choice(
                                    [[], None, [{"key": "a"}], "notalist"]
                                ),
                            }
                        ]
                    }
                ],
            }
        ]
    }
    # structural mutations: drop/retype a random key by round-tripping text
    text = json.dumps(rec)
    if rng.random() < 0.5:
        victim = rng.choice(
            ['"resourceLogs"', '"scopeLogs"', '"logRecords"', '"value"', '"body"']
        )
        text = text.replace(victim, rng.choice(['"x"', victim.upper(), '""']), 1)
    return text.encode()


def gen_shard_boundary(rng: random.Random) -> bytes:
    """Payloads built to ambush the shard splitter: record sizes tuned so
    byte targets land on/inside record boundaries, '},{' sequences inside
    string values (false boundaries the optimistic scan bites on), multi-
    byte UTF-8 packed around every cut phase, and top-level OTel arrays
    with wildly unbalanced element sizes."""
    pick = rng.randrange(4)
    if pick == 0:
        # equal-size records: every byte target hits at/near a real comma
        width = rng.randrange(1, 40)
        recs = [{"m": "x" * width, "v": i} for i in range(rng.randrange(2, 80))]
        return json.dumps(
            recs, separators=rng.choice([(",", ":"), (", ", ": ")])
        ).encode()
    if pick == 1:
        # false boundaries inside strings + escapes right at the pattern
        evil = rng.choice(['a},{"b', "}ws , {", '\\"},{\\"', "},{" * 30])
        recs = [{"s": evil, "n": i} for i in range(rng.randrange(2, 60))]
        return json.dumps(recs).encode()
    if pick == 2:
        # multibyte runs shifted through every phase of the cut targets
        ch = rng.choice(["é", "☃", "漢", "🚀"])
        pad = rng.randrange(1, 9)
        recs = [
            {"m": ch * rng.randrange(1, 30), "k": "a" * pad}
            for _ in range(rng.randrange(2, 50))
        ]
        body = json.dumps(recs, ensure_ascii=False).encode()
        if rng.random() < 0.3 and len(body) > 4:
            body = body[: rng.randrange(2, len(body))]  # truncated mid-record
        return body
    # unbalanced OTel top-level arrays (logs/metrics/spans share the
    # element-span splitter)
    kind = rng.choice(["resourceLogs", "resourceMetrics", "resourceSpans"])
    big = {"scopeLogs": [{"logRecords": [{"body": {"stringValue": "y" * 400}}]}]}
    small = {"scopeLogs": [{"logRecords": []}]}
    n = rng.randrange(2, 12)
    groups = [rng.choice([big, small]) for _ in range(n)]
    return json.dumps({kind: groups}).encode()


def gen_byte_mutation(rng: random.Random) -> bytes:
    base = bytearray(rng.choice([gen_valid_ndjson, gen_otel_shaped])(rng))
    for _ in range(rng.randrange(1, 1 + max(1, len(base) // 16))):
        op = rng.randrange(3)
        pos = rng.randrange(len(base)) if base else 0
        if op == 0 and base:
            base[pos] = rng.randrange(256)
        elif op == 1 and base:
            del base[pos]
        else:
            base.insert(pos, rng.randrange(256))
    return bytes(base)


def gen_http_framing(rng: random.Random) -> bytes:
    """Adversarial HTTP/1.1 wire images for the edge acceptor's parser
    (driven through ptpu_edge_parse_probe at several recv-slice sizes, so
    request lines and chunk frames split across feed boundaries). Families:
    request smuggling shapes (duplicate Content-Length, CL+TE together),
    chunked-extension garbage, bare-LF and obs-fold headers, truncation at
    every phase, and pipelined keep-alive trains."""
    body = rng.choice([b"", b"{}", b'{"a":1}', b"x" * rng.randrange(1, 300)])
    target = rng.choice(
        [
            b"/api/v1/ingest",
            b"/api/v1/logstream/s1",
            b"/v1/logs",
            b"/v1/metrics",
            b"/other",
            b"/" + bytes(rng.randrange(1, 256) for _ in range(rng.randrange(1, 30))),
        ]
    )
    method = rng.choice([b"POST", b"GET", b"PUT", b"P\x00ST", b""])
    version = rng.choice([b"HTTP/1.1", b"HTTP/1.0", b"HTTP/9.9", b"HTTP", b""])
    pick = rng.randrange(6)
    if pick == 0:
        # smuggled framing: duplicate/conflicting Content-Length, CL+TE
        h = rng.choice(
            [
                b"Content-Length: %d\r\nContent-Length: %d\r\n"
                % (len(body), len(body) + rng.randrange(1, 9)),
                b"Content-Length: %d\r\nTransfer-Encoding: chunked\r\n" % len(body),
                b"Content-Length: -1\r\n",
                b"Content-Length: 99999999999999999999\r\n",
                b"Content-Length: %d \r\n" % len(body),
            ]
        )
        return b"%s %s %s\r\n%s\r\n%s" % (method, target, version, h, body)
    if pick == 1:
        # chunked with extension garbage / bad sizes / missing CRLFs
        size = b"%x" % len(body)
        ext = rng.choice([b"", b";ext=1", b";" + b";" * 200, b"\x80\xff", b" ; a=b"])
        tail = rng.choice([b"\r\n0\r\n\r\n", b"\r\n0\r\n", b"\r\n", b""])
        crlf = rng.choice([b"\r\n", b"\n", b""])
        return (
            b"POST %s HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n" % target
            + size + ext + crlf + body + tail
        )
    if pick == 2:
        # header pathology: obs-fold, bare LF, NULs, missing colon, huge
        sep = rng.choice([b"\r\n", b"\n"])
        h = rng.choice(
            [
                b"X-P-Stream: s1\r\n continued\r\n",
                b"NoColonHere\r\n",
                b"X-P-Stream\x00: s1\r\n",
                b"A: " + b"b" * rng.randrange(1, 9000) + b"\r\n",
                b": empty-name\r\n",
            ]
        )
        return (
            b"POST %s HTTP/1.1" % target + sep
            + b"Content-Length: %d" % len(body) + sep + h + sep + body
        )
    if pick == 3:
        # truncation at a random phase of an otherwise-valid request
        full = (
            b"POST %s HTTP/1.1\r\nAuthorization: Basic dTpw\r\n"
            b"X-P-Stream: s1\r\nContent-Length: %d\r\n\r\n%s"
            % (target, len(body), body)
        )
        return full[: rng.randrange(0, len(full) + 1)]
    if pick == 4:
        # pipelined keep-alive trains, valid and mixed with garbage
        reqs = []
        for _ in range(rng.randrange(2, 6)):
            b2 = rng.choice([b"{}", b'{"k":2}', b""])
            reqs.append(
                b"POST /api/v1/ingest HTTP/1.1\r\nX-P-Stream: s%d\r\n"
                b"Content-Length: %d\r\n\r\n%s" % (rng.randrange(9), len(b2), b2)
            )
        if rng.random() < 0.3:
            reqs.insert(rng.randrange(len(reqs)), gen_byte_mutation(rng)[:200])
        return b"".join(reqs)
    # pure noise through the HTTP state machine
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 400)))


FAMILIES = [
    ("valid_ndjson", gen_valid_ndjson),
    ("truncated_utf8", gen_truncated_utf8),
    ("lone_surrogate", gen_lone_surrogate),
    ("deep_nesting", gen_deep_nesting),
    ("huge_numbers", gen_huge_numbers),
    ("nul_bytes", gen_nul_bytes),
    ("pathological_escapes", gen_pathological_escapes),
    ("boundary_split", gen_boundary_split),
    ("shard_boundary", gen_shard_boundary),
    ("otel_shaped", gen_otel_shaped),
    ("byte_mutation", gen_byte_mutation),
    ("http_framing", gen_http_framing),
]


def gen_payload(rng: random.Random) -> tuple[str, bytes]:
    name, fn = FAMILIES[rng.randrange(len(FAMILIES))]
    return name, fn(rng)


# ------------------------------------------------------------ child mode


def _drive_payload(native, np, payload: bytes) -> int:
    """Push one payload through every native entry point; returns
    ptpu_cols_live after releasing everything."""
    import gc

    native.flatten_ndjson(payload, 6)
    native.flatten_ndjson(payload, 1, separator=".")
    native.otel_logs_ndjson(payload)
    native.otel_logs_ndjson(payload, ts_as_ms=False)
    r1 = native.flatten_columnar(payload, 6)
    r2 = native.otel_logs_columnar(payload)
    del r1, r2
    # sharded split/stitch paths: forced counts walk the boundary scanner,
    # the worker pool, and the stitch memcpy/offset-rebase machinery; the
    # pool shutdown in the middle exercises drain + lazy restart under load
    for shards in (2, 4, 16):
        rs = [
            native.flatten_columnar(payload, 6, shards=shards),
            native.otel_logs_columnar(payload, shards=shards),
            native.otel_metrics_columnar(payload, shards=shards),
            native.otel_traces_columnar(payload, shards=shards),
        ]
        del rs
        if shards == 4:
            native.shutdown_parse_pool()
    r3 = native.otel_metrics_columnar(payload, ts_as_ms=False)
    r4 = native.otel_traces_columnar(payload, ts_as_ms=False)
    del r3, r4

    # edge HTTP parser: every payload (not just http_framing) walks the
    # state machine whole, in 1-byte slices (every boundary split), and at
    # a prime step that shifts chunk frames across feed calls
    if getattr(native, "edge_available", lambda: False)():
        for chunk in (0, 1, 7):
            native.edge_parse_probe(payload, chunk)

    lines = payload.split(b"\n")[:256] or [b""]
    buf = bytearray()
    offs = [0]
    for ln in lines:
        buf += ln
        offs.append(len(buf))
    p = 4 + (payload[0] % 15) if payload else 14
    native.hll_idx_rank_batch(bytes(buf), np.asarray(offs, dtype=np.uint64), p)
    h = native.Hll(p)
    h.add_strings(ln.decode("utf-8", "replace") for ln in lines)
    h.add(payload)
    h.estimate()
    blob = h.serialize()
    native.Hll.deserialize(blob, p).estimate()
    native.xxh64(payload, seed=p)
    del h

    gc.collect()
    return native.columnar_live()


def child_main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="nsan.fuzz(child)")
    ap.add_argument("--lib", required=True)
    ap.add_argument("--replay", nargs="*", default=[])
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--seconds", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scratch", default="")
    ap.add_argument("--leak-check", action="store_true")
    args = ap.parse_args(argv)

    # the library choice must land before parseable_tpu.native imports
    os.environ["P_NSAN_LIB"] = args.lib
    import ctypes

    import numpy as np

    import parseable_tpu.native as native

    if not native.native_available():
        print(json.dumps({"error": "native library failed to load"}))
        return 2

    executed = 0
    deadline = time.monotonic() + args.seconds if args.seconds else None
    rng = random.Random(args.seed)

    def run_one(payload: bytes) -> int | None:
        nonlocal executed
        if args.scratch:
            Path(args.scratch).write_bytes(payload)
        live = _drive_payload(native, np, payload)
        executed += 1
        if live != 0:
            print(json.dumps({"executed": executed, "cols_live": live}))
            return EXIT_COLS_LIVE
        return None

    for rel in args.replay:
        rc = run_one(Path(rel).read_bytes())
        if rc is not None:
            return rc
    i = 0
    while i < args.iters or (deadline and time.monotonic() < deadline):
        _, payload = gen_payload(rng)
        rc = run_one(payload)
        if rc is not None:
            return rc
        i += 1

    if args.leak_check:
        # under the preload, libasan is in the flat namespace
        try:
            rt = ctypes.CDLL(None)
            rc = rt.__lsan_do_recoverable_leak_check()
        except (OSError, AttributeError):
            rc = 0  # no LSan runtime loaded: nothing to check
        if rc != 0:
            print(json.dumps({"executed": executed, "lsan": "leaked"}))
            return EXIT_LSAN_LEAK
    print(json.dumps({"executed": executed, "cols_live": 0}))
    return 0


# ----------------------------------------------------------- parent side


def child_env(root: Path, preload: bool = True) -> dict[str, str] | None:
    """Environment for a fuzz child: full ASan preload + LSan suppressions
    for the interpreter's own arenas. None when no ASan runtime exists."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("LD_PRELOAD", "ASAN_OPTIONS", "LSAN_OPTIONS", "PYTHONMALLOC")
    }
    asan_opts = [
        "halt_on_error=1",
        "abort_on_error=0",
        f"exitcode={EXIT_ASAN_ERROR}",
        "detect_leaks=1",
        "leak_check_at_exit=0",  # only the explicit mid-run check gates
        "allocator_may_return_null=1",
    ]
    if preload:
        rt = asan_runtime()
        if rt is None:
            return None
        env["LD_PRELOAD"] = rt
    else:
        asan_opts.append("verify_asan_link_order=0")
    env["ASAN_OPTIONS"] = ":".join(asan_opts)
    supp = Path(__file__).parent / "lsan.supp"
    if supp.is_file():
        env["LSAN_OPTIONS"] = f"suppressions={supp}"
    env["PYTHONMALLOC"] = "malloc"  # route CPython allocs through ASan's malloc
    return env


def run_child(
    root: Path,
    lib: Path,
    *,
    replay: list[Path] | None = None,
    iters: int = 0,
    seconds: float = 0.0,
    seed: int = 0,
    scratch: Path | None = None,
    leak_check: bool = True,
    env: dict[str, str] | None = None,
) -> subprocess.CompletedProcess | None:
    if env is None:
        env = child_env(root)
    if env is None:
        return None
    cmd = [
        sys.executable,
        "-m",
        "parseable_tpu.analysis.nsan.fuzz",
        "--lib",
        str(lib),
        "--seed",
        str(seed),
    ]
    if replay:
        cmd += ["--replay", *[str(p) for p in replay]]
    if iters:
        cmd += ["--iters", str(iters)]
    if seconds:
        cmd += ["--seconds", str(seconds)]
    if scratch:
        cmd += ["--scratch", str(scratch)]
    if leak_check:
        cmd += ["--leak-check"]
    try:
        return subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=CHILD_TIMEOUT + seconds,
            cwd=str(root),
            env=env,
        )
    except subprocess.TimeoutExpired as exc:
        return subprocess.CompletedProcess(
            cmd, returncode=-1, stdout=str(exc.stdout or ""), stderr="child timeout"
        )
    except OSError:
        return None


# The sanitizer runtime itself can die without having detected anything in
# the target: LSan's stop-the-world tracer segfaults or fails to fork under
# memory/scheduler pressure (observed with a concurrent full test run on a
# 1-CPU box), and the child then exits with the ASan exitcode even though no
# report names our code. Those deaths correlate with load, not with the
# payload — so they must never bank a "reproducer" or validate a minimizer
# removal. Callers retry once and only report when the failure sticks.
_INFRA_SIGNATURES = (
    "LeakSanitizer has encountered a fatal error",
    "Tracer caught signal",
    "failed to fork the tracer thread",
    "StopTheWorld",
)


def sanitizer_infra_failure(stderr: str) -> bool:
    """True when the child's death is sanitizer-runtime-internal (tracer
    crash, fork failure) rather than a detected bug in the target code."""
    if "ERROR: AddressSanitizer" in stderr or "runtime error:" in stderr:
        return False  # a real report trumps any tracer noise around it
    return any(sig in stderr for sig in _INFRA_SIGNATURES)


def classify_failure(rc: int, stderr: str) -> tuple[str, str] | None:
    """(rule, short message) for a failing child exit, None when clean."""
    if rc == 0:
        return None
    if rc == EXIT_LSAN_LEAK:
        return "nsan-fuzz-leak", "LSan reported a native leak after the payload run"
    if rc == EXIT_COLS_LIVE:
        return (
            "nsan-fuzz-cols-live",
            "ptpu_cols_live drifted above zero after releasing all batches",
        )
    if rc == EXIT_ASAN_ERROR or "AddressSanitizer" in stderr:
        # "CHECK failed" is ASan's INTERNAL assertion (no "ERROR:" prefix) —
        # it still dies with the configured exitcode, so grab it too or the
        # headline degrades to the useless fallback
        head = next(
            (
                ln.strip()
                for ln in stderr.splitlines()
                if "ERROR: AddressSanitizer" in ln
                or "CHECK failed" in ln
                or "runtime error:" in ln
            ),
            "AddressSanitizer error",
        )
        return "nsan-fuzz-crash", head
    if "runtime error:" in stderr:
        head = next(
            ln.strip() for ln in stderr.splitlines() if "runtime error:" in ln
        )
        return "nsan-fuzz-crash", f"UBSan: {head}"
    if rc < 0:
        return "nsan-fuzz-crash", f"child died with signal {-rc}"
    return "nsan-fuzz-crash", f"child exited {rc}"


def _payload_fails(root: Path, lib: Path, payload: bytes, env: dict) -> bool:
    tmp = root / "tests" / "corpus" / ".min-probe.bin"
    tmp.write_bytes(payload)
    try:
        proc = run_child(root, lib, replay=[tmp], leak_check=True, env=env)
        if proc is None:
            return True
        if proc.returncode != 0 and sanitizer_infra_failure(proc.stderr):
            return False  # tracer flake, not the payload — don't credit it
        return proc.returncode != 0
    finally:
        tmp.unlink(missing_ok=True)


def minimize(root: Path, lib: Path, payload: bytes, budget: int = 48) -> bytes:
    """Bounded halve-removal shrink: repeatedly try dropping chunks while
    the child still fails. `budget` caps total child invocations."""
    env = child_env(root)
    if env is None:
        return payload
    best = payload
    runs = 0
    chunk = max(1, len(best) // 2)
    while chunk >= 1 and runs < budget:
        i = 0
        shrunk = False
        while i < len(best) and runs < budget:
            cand = best[:i] + best[i + chunk :]
            runs += 1
            if cand and _payload_fails(root, lib, cand, env):
                best = cand
                shrunk = True
            else:
                i += chunk
        if not shrunk:
            chunk //= 2
    # a flaky child exit during the shrink (e.g. an ASan-internal abort
    # under memory pressure) would have "validated" a bogus removal — only
    # trust a shrunk payload that still fails on a confirming run
    if best is not payload and not _payload_fails(root, lib, best, env):
        return payload
    return best


def bank_case(root: Path, payload: bytes) -> Path:
    cdir = corpus_dir(root)
    cdir.mkdir(parents=True, exist_ok=True)
    name = f"case-{sha1(payload).hexdigest()[:12]}.bin"
    path = cdir / name
    path.write_bytes(payload)
    return path


def iter_corpus(root: Path) -> list[Path]:
    cdir = corpus_dir(root)
    if not cdir.is_dir():
        return []
    return sorted(p for p in cdir.iterdir() if p.suffix == ".bin")


def replay_corpus(
    root: Path, lib: Path | None = None
) -> tuple[list[Finding], dict]:
    """Replay the banked corpus under the sanitized build + full preload.
    One child for the whole corpus; on failure, per-case children assign
    blame. Skips (with a stats note) when the ASan runtime is absent."""
    cases = iter_corpus(root)
    stats: dict = {"corpus_replayed": 0, "corpus_skipped": False}
    if not cases:
        return [], stats
    if lib is None:
        lib = san_lib_path(root, "asan")
    env = child_env(root)
    if env is None or not lib.is_file():
        stats["corpus_skipped"] = True
        stats["corpus_skip_reason"] = (
            "no ASan runtime" if env is None else "sanitized library not built"
        )
        return [], stats
    proc = run_child(root, lib, replay=cases, env=env)
    stats["corpus_replayed"] = len(cases)
    if proc is not None and proc.returncode == 0:
        return [], stats
    if (
        proc is not None
        and sanitizer_infra_failure(proc.stderr)
        and (retry := run_child(root, lib, replay=cases, env=env)) is not None
        and retry.returncode == 0
    ):
        # the sanitizer runtime died (not the target); a clean re-run
        # settles it — record the flake instead of inventing a finding
        stats["infra_flakes"] = stats.get("infra_flakes", 0) + 1
        return [], stats
    findings: list[Finding] = []
    for case in cases:
        p = run_child(root, lib, replay=[case], env=env)
        rc = -2 if p is None else p.returncode
        if p is not None and rc != 0 and sanitizer_infra_failure(p.stderr):
            p = run_child(root, lib, replay=[case], env=env)
            rc = -2 if p is None else p.returncode
        verdict = classify_failure(rc, "" if p is None else p.stderr)
        if verdict:
            rule, msg = verdict
            rel = case.relative_to(root).as_posix()
            findings.append(
                Finding(
                    rule=rule,
                    path=rel,
                    line=1,
                    message=f"corpus case {case.name} failed under the "
                    f"sanitized build: {msg}",
                    context="",
                    snippet=case.name,
                )
            )
    if not findings:
        # whole-corpus run failed but cases pass individually (ordering /
        # accumulation effect) — still a finding, pinned to the corpus dir
        verdict = classify_failure(
            proc.returncode if proc else -2, proc.stderr if proc else ""
        )
        rule, msg = verdict or ("nsan-fuzz-crash", "corpus replay failed")
        findings.append(
            Finding(
                rule=rule,
                path="tests/corpus/nsan",
                line=1,
                message=f"corpus replay failed as a batch but no single case "
                f"reproduces: {msg}",
                context="",
                snippet="batch",
            )
        )
    return findings, stats


def fuzz_campaign(
    root: Path,
    *,
    seconds: float = 60.0,
    seed: int = 0,
    batch_iters: int = 400,
) -> tuple[list[Finding], dict]:
    """Open-ended campaign: batches of generated payloads in preloaded
    children until the time budget runs out. Crashing payloads are
    recovered from the scratch file, minimized, and banked in the corpus.
    Returns findings + bookkeeping (cpu seconds, batches, cases banked)."""
    from . import build_san_lib

    stats: dict = {
        "batches": 0,
        "executed": 0,
        "cpu_seconds": 0.0,
        "banked": [],
        "skipped": False,
    }
    lib = build_san_lib(root, "asan")
    env = child_env(root)
    if lib is None or env is None:
        stats["skipped"] = True
        stats["skip_reason"] = "toolchain or ASan runtime unavailable"
        return [], stats
    findings: list[Finding] = []
    scratch = corpus_dir(root).parent / ".nsan-scratch.bin"
    scratch.parent.mkdir(parents=True, exist_ok=True)
    deadline = time.monotonic() + seconds
    batch_seed = seed
    while time.monotonic() < deadline:
        t0 = time.process_time()
        w0 = time.monotonic()
        proc = run_child(
            root,
            lib,
            iters=batch_iters,
            seed=batch_seed,
            scratch=scratch,
            env=env,
        )
        stats["batches"] += 1
        # children burn their own CPU; wall time of the child is the
        # honest lower bound we can account from here
        stats["cpu_seconds"] += (time.monotonic() - w0) + (time.process_time() - t0)
        batch_seed += 1
        if proc is None:
            stats["skipped"] = True
            stats["skip_reason"] = "child failed to spawn"
            break
        try:
            tail = json.loads(proc.stdout.strip().splitlines()[-1])
            stats["executed"] += int(tail.get("executed", 0))
        except (ValueError, IndexError):
            pass
        if proc.returncode == 0:
            continue
        verdict = classify_failure(proc.returncode, proc.stderr)
        if not verdict:
            continue
        rule, msg = verdict
        payload = scratch.read_bytes() if scratch.exists() else b""
        if sanitizer_infra_failure(proc.stderr):
            # sanitizer-runtime death (tracer segfault under load), not a
            # detected bug — confirm against the recovered payload before
            # treating it as a finding
            if not payload or not _payload_fails(root, lib, payload, env):
                stats["infra_flakes"] = stats.get("infra_flakes", 0) + 1
                continue
        if payload:
            payload = minimize(root, lib, payload)
            banked = bank_case(root, payload)
            stats["banked"].append(banked.name)
            loc = banked.relative_to(root).as_posix()
            # the child's full sanitizer report, next to the reproducer —
            # triaging a crash that only fired once is hopeless without it
            # (iter_corpus replays *.bin only, so the .txt never runs)
            banked.with_suffix(".stderr.txt").write_text(proc.stderr or "")
        else:
            loc = "tests/corpus/nsan"
        findings.append(
            Finding(
                rule=rule,
                path=loc,
                line=1,
                message=f"fuzzer (seed {batch_seed - 1}) hit: {msg}; minimized "
                "reproducer banked in the corpus",
                context="",
                snippet=msg,
            )
        )
    scratch.unlink(missing_ok=True)
    return findings, stats


if __name__ == "__main__":
    sys.exit(child_main(sys.argv[1:]))
