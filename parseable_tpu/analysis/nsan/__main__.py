"""nsan CLI: `python -m parseable_tpu.analysis.nsan`.

Gate mode (default — what scripts/check_green.sh runs):
  1. ABI drift check (abicheck.py)           — always
  2. clang-tidy over fastpath.cpp (tidy.py)  — when clang-tidy exists
  3. corpus replay under the sanitized build + full ASan preload
     (fuzz.py)                               — when the toolchain exists
  4. fold tests/corpus/nsan/FUZZ_LOG.json (the recorded fuzz-campaign
     ledger) into the artifact stats

Findings gate against the shared empty baseline (`.nsan-baseline.json`);
the artifact (`--json-out`, default P_NSAN_JSON=/tmp/nsan.json) is
plint-shaped. The `P_NSAN=1` pytest run merges its own section into the
same artifact afterwards.

`--fuzz` runs the open-ended campaign instead: generated payloads in
preloaded children for `--seconds` (default P_NSAN_FUZZ_S), minimizing
and banking any reproducer, and appending a run record to FUZZ_LOG.json.

Exit codes: 0 = clean, 1 = unbaselined findings, 2 = usage/setup error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from parseable_tpu.analysis.framework import write_baseline

from . import abicheck, build_san_lib, corpus_dir, fuzz, repo_root, tidy
from .report import DEFAULT_BASELINE, assemble_report, render_lines, write_report

FUZZ_LOG = "FUZZ_LOG.json"


def _load_fuzz_log(root: Path) -> dict:
    path = corpus_dir(root) / FUZZ_LOG
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
        if isinstance(doc, dict):
            return doc
    except (OSError, ValueError):
        pass
    return {"runs": [], "total_cpu_seconds": 0.0, "findings": 0}


def _append_fuzz_log(root: Path, record: dict) -> dict:
    doc = _load_fuzz_log(root)
    doc["runs"].append(record)
    doc["total_cpu_seconds"] = round(
        sum(r.get("cpu_seconds", 0.0) for r in doc["runs"]), 1
    )
    doc["findings"] = sum(r.get("findings", 0) for r in doc["runs"])
    path = corpus_dir(root) / FUZZ_LOG
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
    return doc


def run_gate(root: Path, baseline: str) -> tuple[dict, list]:
    findings = []
    stats: dict = {}

    abi_findings, abi_stats = abicheck.run_abicheck(root)
    findings += abi_findings
    stats["abi"] = abi_stats

    tidy_findings, tidy_stats = tidy.run_tidy(root)
    findings += tidy_findings
    stats["tidy"] = tidy_stats

    lib = build_san_lib(root, "asan")
    replay_findings, fuzz_stats = fuzz.replay_corpus(root, lib)
    findings += replay_findings
    fuzz_stats["san_lib_built"] = lib is not None
    stats["fuzz"] = fuzz_stats

    stats["fuzz_campaign"] = _load_fuzz_log(root)
    return assemble_report(findings, stats, root, baseline), findings


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m parseable_tpu.analysis.nsan",
        description="nsan: native-code safety gate (ABI drift, sanitizers, fuzzing)",
    )
    p.add_argument("--root", default=None, help="repository root (default: detect)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--json-out",
        metavar="FILE",
        default=None,
        help="write the JSON artifact to FILE (default: P_NSAN_JSON)",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help=f"baseline file relative to --root (default: {DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="acknowledge every current finding into the baseline file",
    )
    p.add_argument(
        "--fuzz",
        action="store_true",
        help="run the open-ended fuzz campaign instead of the gate",
    )
    p.add_argument(
        "--seconds",
        type=float,
        default=None,
        help="fuzz time budget (default: P_NSAN_FUZZ_S)",
    )
    p.add_argument(
        "--seed", type=int, default=None, help="fuzz seed (default: P_NSAN_FUZZ_SEED)"
    )
    args = p.parse_args(argv)

    from parseable_tpu.config import nsan_options

    opts = nsan_options()
    root = Path(args.root).resolve() if args.root else repo_root()
    json_out = args.json_out or opts["json_path"]

    if args.fuzz:
        seconds = args.seconds if args.seconds is not None else opts["fuzz_seconds"]
        seed = args.seed if args.seed is not None else opts["fuzz_seed"]
        started = time.monotonic()
        findings, stats = fuzz.fuzz_campaign(root, seconds=seconds, seed=seed)
        if stats.get("skipped"):
            print(f"nsan --fuzz: skipped ({stats.get('skip_reason')})", file=sys.stderr)
            return 2
        record = {
            "seed": seed,
            "seconds_budget": seconds,
            "wall_seconds": round(time.monotonic() - started, 1),
            "cpu_seconds": round(stats["cpu_seconds"], 1),
            "batches": stats["batches"],
            "executed": stats["executed"],
            "findings": len(findings),
            "banked": stats["banked"],
            "infra_flakes": stats.get("infra_flakes", 0),
        }
        ledger = _append_fuzz_log(root, record)
        for f in findings:
            print(f"{f.path}:{f.line}: {f.rule}: {f.message}")
        print(
            f"nsan --fuzz: {stats['executed']} payloads in {stats['batches']} "
            f"batch(es), {len(findings)} finding(s); campaign total "
            f"{ledger['total_cpu_seconds']}s CPU across {len(ledger['runs'])} run(s)"
        )
        return 1 if findings else 0

    report, findings = run_gate(root, args.baseline)

    if args.write_baseline:
        write_baseline(root / args.baseline, findings)
        print(f"baseline written: {len(findings)} finding(s) -> {root / args.baseline}")
        return 0

    if json_out:
        try:
            write_report(report, json_out)
        except OSError as e:
            print(f"nsan: cannot write artifact to {json_out}: {e}", file=sys.stderr)

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for line in render_lines(report):
            print(line)
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
