"""nsan: the native-code safety gate for the C++ fast path.

The native sibling of plint (static, PR 4/5) and psan (runtime, PR 9),
covering the one layer those two cannot see: `native/fastpath.cpp` and the
ctypes FFI surface over it. Three passes, one plint-shaped artifact
(`/tmp/nsan.json`), one empty-baseline policy:

- **ABI drift** (`abicheck.py`): parse the `extern "C"` declarations out of
  fastpath.cpp and diff them against the ctypes `restype`/`argtypes`
  declarations in `native/__init__.py` — missing restype (ctypes defaults
  to c_int, truncating 64-bit pointers), arity/type mismatches,
  exported-but-unbound and bound-but-unexported symbols.
- **Sanitizers** (`build.sh SAN=asan|ubsan` -> libptpu_fastpath_{mode}.so):
  a `P_NSAN=1` pytest mode runs the native-touching test set against the
  instrumented library, UBSan-instrumented by default. UBSan is the only
  mode that is SOUND under late dlopen: ASan's inlined operator delete
  false-aborts ("not malloc()-ed") on std::string buffers that libstdc++'s
  out-of-line _M_create allocated with plain malloc — allocator identity
  is only consistent under a full LD_PRELOAD, which jax's import does not
  survive. So the pytest pass gets UBSan at full fidelity plus a
  `ptpu_cols_live == 0` leak gate; ASan/LSan fidelity lives in the
  preloaded jax-free fuzz child.
- **Structured fuzzing** (`fuzz.py`): adversarial JSON/OTel payloads driven
  through the real Python wrappers in a jax-free subprocess under FULL
  LD_PRELOAD ASan+UBSan+LSan; the minimized regression corpus lives in
  `tests/corpus/nsan/` and replays in tier-1.

CLI: `python -m parseable_tpu.analysis.nsan` (gate mode; check_green.sh
runs it), `--fuzz` for the open-ended campaign.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path


def repo_root() -> Path:
    import parseable_tpu

    return Path(parseable_tpu.__file__).resolve().parent.parent


def native_dir(root: Path) -> Path:
    return root / "parseable_tpu" / "native"


def san_lib_path(root: Path, mode: str = "asan") -> Path:
    """Mode-specific file name: the mtime cache in build_san_lib could not
    otherwise tell an asan build from a ubsan build of the same path."""
    return native_dir(root) / f"libptpu_fastpath_{mode}.so"


def corpus_dir(root: Path) -> Path:
    return root / "tests" / "corpus" / "nsan"


def toolchain_available() -> bool:
    return shutil.which("g++") is not None


def asan_runtime() -> str | None:
    """Path to the toolchain's libasan.so for LD_PRELOAD, or None when the
    toolchain has no (usable) ASan runtime."""
    if not toolchain_available():
        return None
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    path = out.stdout.strip()
    # an unknown file name is echoed back verbatim (no directory component)
    if not path or "/" not in path:
        return None
    resolved = Path(path).resolve()
    return str(resolved) if resolved.is_file() else None


def build_san_lib(root: Path, mode: str = "asan") -> Path | None:
    """Build (or reuse) the sanitizer-instrumented library. Returns its
    path, or None when the toolchain is absent or the build fails. Cached
    on mtime like the production lib: a san lib newer than fastpath.cpp
    and build.sh is reused as-is."""
    if not toolchain_available():
        return None
    lib = san_lib_path(root, mode)
    src_dir = native_dir(root)
    try:
        if lib.exists():
            lib_m = lib.stat().st_mtime
            if all(
                (src_dir / dep).stat().st_mtime <= lib_m
                for dep in ("fastpath.cpp", "build.sh")
            ):
                return lib
    except OSError:
        pass
    try:
        subprocess.run(
            ["sh", str(src_dir / "build.sh")],
            check=True,
            capture_output=True,
            timeout=300,
            env={**os.environ, "SAN": mode},
        )
    except (OSError, subprocess.SubprocessError):
        return None
    return lib if lib.exists() else None
