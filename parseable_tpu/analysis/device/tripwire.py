"""dlint's dynamic companion: the P_DLINT=1 recompilation tripwire.

The static rules prove every call-time ``jax.jit`` *claims* to ride a
program cache; this plugin proves the claim at runtime.  It wraps
``jax.jit`` for the whole pytest session (installed in pytest_configure,
before collection imports the package, so decorator-time jits are wrapped
too) and returns a thin proxy that detects real XLA compiles via the
jitted callable's ``_cache_size()`` delta per call.  Every creation site
is attributed to its declared program-cache name by reading the
``# jit-cache: <family>.<program>`` annotation off the creating source
line (the same grammar the static rules enforce, so the two halves cannot
drift).

Enforcement, for declared programs only:

* a single proxy compiling more than ``P_DLINT_BUDGET`` (default 1) times
  — a cached program is fetched once per shape class, so its proxy should
  compile exactly once;
* the same (program, cache-key) jit-created more than budget+1 times
  within one test — the program cache failed to serve a warm key.  The
  ``+1`` tolerates the one benign double-build the multithreaded query
  pool can race into on a cold key; the per-call-jit bug this tripwire
  exists for creates one per query and blows straight through.

Undeclared sites (module-level decorators in ops/kernels.py, the mesh
builders) are tracked in the report for visibility, never enforced —
they are import-time or per-config, not per-query.

Violations tick the shipped ``tpu_recompiles_total{program}`` counter
(wlint's metric-discipline rule then keeps the family honest), flip the
session exit status, and land in the P_DLINT_JSON artifact.
"""

from __future__ import annotations

import io
import json
import re
import sys
import tokenize
from collections import defaultdict
from pathlib import Path

_JIT_CACHE_RE = re.compile(r"jit-cache:\s*([A-Za-z_][A-Za-z0-9_.-]*)")

#: The active plugin instance, for tests and the executor stages hook.
_ACTIVE: "DlintPytestPlugin | None" = None


def get_tripwire() -> "DlintPytestPlugin | None":
    return _ACTIVE


class _JitProxy:
    """Wraps one jitted callable; counts real XLA compiles per call via
    the ``_cache_size()`` delta.  Everything else passes through."""

    __slots__ = ("_jitted", "_plugin", "_site", "compiles")

    def __init__(self, jitted, plugin: "DlintPytestPlugin", site: tuple) -> None:
        self._jitted = jitted
        self._plugin = plugin
        self._site = site
        self.compiles = 0

    def _cache_size(self) -> int:
        try:
            return self._jitted._cache_size()
        except Exception:
            return -1  # API drift: compile detection degrades, never breaks

    def __call__(self, *args, **kwargs):
        before = self._cache_size()
        out = self._jitted(*args, **kwargs)
        after = self._cache_size()
        if before >= 0 and after > before:
            self.compiles += after - before
            self._plugin._record_compile(self._site, self.compiles, after - before)
        return out

    def __getattr__(self, name):
        return getattr(self._jitted, name)


class DlintPytestPlugin:
    """pytest plugin enforcing the compiles-per-shape-class budget."""

    def __init__(self) -> None:
        self.root = Path(__file__).resolve().parents[3]
        self.budget = 1
        self.json_path = "/tmp/dlint_tripwire.json"
        self.programs: dict[str, dict] = {}
        self.undeclared: dict[str, dict] = {}
        self.violations: list[dict] = []
        self._creations: dict[tuple, int] = defaultdict(int)
        self._ann_cache: dict[str, dict[int, str]] = {}
        self._nodeid = "<collection>"
        self._orig_jit = None
        self.report: dict | None = None

    # ------------------------------------------------------------ plumbing

    def _declared_name(self, filename: str, lineno: int) -> str | None:
        """The `# jit-cache:` annotation on the creating line (or the line
        above it), from a cached tokenize scan of the source file."""
        table = self._ann_cache.get(filename)
        if table is None:
            table = {}
            try:
                text = Path(filename).read_text(encoding="utf-8")
                for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                    if tok.type == tokenize.COMMENT:
                        m = _JIT_CACHE_RE.search(tok.string)
                        if m:
                            table[tok.start[0]] = m.group(1)
            except (OSError, tokenize.TokenError, IndentationError, SyntaxError):
                pass
            self._ann_cache[filename] = table
        return table.get(lineno) or table.get(lineno - 1)

    def _site(self) -> tuple:
        """(rel, line, func, declared_program, key_repr) of the frame that
        called jax.jit — the first frame outside this module."""
        here = __file__
        f = sys._getframe(2)
        while f is not None and f.f_code.co_filename == here:
            f = f.f_back
        if f is None:
            return ("<unknown>", 0, "", None, "")
        filename = f.f_code.co_filename
        lineno = f.f_lineno
        try:
            rel = str(Path(filename).resolve().relative_to(self.root))
        except ValueError:
            rel = filename
        declared = self._declared_name(filename, lineno)
        # the executor convention names the cache key `key` — it IS the
        # shape class, so read it straight out of the creating frame
        key = f.f_locals.get("key")
        key_repr = repr(key)[:512] if key is not None else ""
        return (rel, lineno, f.f_code.co_name, declared, key_repr)

    def _program(self, name: str) -> dict:
        return self.programs.setdefault(
            name,
            {"creations": 0, "compiles": 0, "keys": set(), "over_budget": 0},
        )

    def _violate(self, kind: str, program: str, detail: str) -> None:
        self.violations.append(
            {
                "kind": kind,
                "program": program,
                "test": self._nodeid,
                "detail": detail,
            }
        )
        try:
            from parseable_tpu.utils.metrics import DEVICE_RECOMPILES

            DEVICE_RECOMPILES.labels(program).inc()
        except Exception:
            pass

    def _record_creation(self) -> tuple:
        site = self._site()
        rel, lineno, func, declared, key_repr = site
        if declared:
            prog = self._program(declared)
            prog["creations"] += 1
            if key_repr:
                prog["keys"].add(key_repr)
                self._creations[(declared, key_repr, self._nodeid)] += 1
                n = self._creations[(declared, key_repr, self._nodeid)]
                if n == self.budget + 2:  # +1 slack for one benign race
                    prog["over_budget"] += 1
                    self._violate(
                        "duplicate-creation",
                        declared,
                        f"jit program built {n}x for one cache key within "
                        f"one test (site {rel}:{lineno} in {func}; key "
                        f"{key_repr}) — the program cache is not serving "
                        "warm keys",
                    )
        else:
            und = self.undeclared.setdefault(
                f"{rel}:{lineno}", {"creations": 0, "compiles": 0, "func": func}
            )
            und["creations"] += 1
        return site

    def _record_compile(self, site: tuple, total: int, delta: int) -> None:
        rel, lineno, func, declared, _key = site
        if declared:
            prog = self._program(declared)
            prog["compiles"] += delta
            if total == self.budget + 1:
                prog["over_budget"] += 1
                self._violate(
                    "recompile",
                    declared,
                    f"one jit proxy compiled {total}x (budget "
                    f"{self.budget}; site {rel}:{lineno} in {func}) — a "
                    "cached program should compile once per shape class",
                )
        else:
            und = self.undeclared.setdefault(
                f"{rel}:{lineno}", {"creations": 0, "compiles": 0, "func": func}
            )
            und["compiles"] += delta

    # --------------------------------------------------------- pytest hooks

    def pytest_configure(self, config) -> None:
        global _ACTIVE
        import jax

        if self._orig_jit is not None:
            return
        self._orig_jit = jax.jit
        plugin = self
        orig = jax.jit

        def _dlint_jit(fun, *args, **kwargs):
            jitted = orig(fun, *args, **kwargs)
            site = plugin._record_creation()
            return _JitProxy(jitted, plugin, site)

        jax.jit = _dlint_jit
        _ACTIVE = self
        # read the knobs only after the patch is installed: this import
        # pulls in the package, which may jit at import time
        from parseable_tpu.config import dlint_options

        opts = dlint_options()
        self.budget = opts["budget"]
        self.json_path = opts["json_path"]

    def pytest_unconfigure(self, config) -> None:
        global _ACTIVE
        if self._orig_jit is not None:
            import jax

            jax.jit = self._orig_jit
            self._orig_jit = None
        if _ACTIVE is self:
            _ACTIVE = None

    def pytest_runtest_setup(self, item) -> None:
        self._nodeid = item.nodeid

    def assemble_report(self) -> dict:
        return {
            "version": 1,
            "clean": not self.violations,
            "budget": self.budget,
            "programs": {
                name: {
                    "creations": p["creations"],
                    "compiles": p["compiles"],
                    "distinct_keys": len(p["keys"]),
                    "over_budget": p["over_budget"],
                }
                for name, p in sorted(self.programs.items())
            },
            "undeclared": dict(sorted(self.undeclared.items())),
            "violations": self.violations,
        }

    def pytest_sessionfinish(self, session, exitstatus) -> None:
        self.report = self.assemble_report()
        try:
            Path(self.json_path).write_text(
                json.dumps(self.report, indent=2) + "\n", encoding="utf-8"
            )
        except OSError:
            pass
        if not self.report["clean"] and session.exitstatus == 0:
            session.exitstatus = 1

    def pytest_terminal_summary(self, terminalreporter) -> None:
        tr = terminalreporter
        report = self.report or self.assemble_report()
        tr.section("dlint recompilation tripwire")
        tr.write_line(
            f"budget: {report['budget']} compile(s) per program per shape class"
        )
        for name, p in report["programs"].items():
            tr.write_line(
                f"tpu_recompiles_total{{program=\"{name}\"}} "
                f"{p['over_budget']} (built {p['creations']}, compiled "
                f"{p['compiles']}, {p['distinct_keys']} shape class(es))"
            )
        if report["undeclared"]:
            tr.write_line(
                f"undeclared jit sites (tracked, not enforced): "
                f"{len(report['undeclared'])}"
            )
        for v in report["violations"]:
            tr.write_line(
                f"VIOLATION [{v['kind']}] {v['program']} in {v['test']}: "
                f"{v['detail']}"
            )
        if report["clean"]:
            tr.write_line("dlint tripwire: clean")
