"""dlint rules for device->host synchronization and transfer discipline.

* ``host-sync`` — a reachability rule over the PR 5 call graph.  Roots are
  functions containing a ``# device-hot`` annotation (the executor's block
  dispatch loops); from there the rule walks direct (non-deferred,
  non-executor) call edges, exactly like plint's ``blocking_reach``, and
  flags synchronizing constructs in any reachable device-layer function:
  ``.block_until_ready()`` and ``.item()`` on anything, and
  ``np.asarray``/``np.array``/``float()``/``int()``/``bool()`` on values the
  intraprocedural taint pass knows are device arrays.  A declared
  ``# sync-boundary: <why>`` (line or whole function) is exempt — the point
  is not "never sync" but "every sync is declared and priced".
* ``transfer-discipline`` — every ``jax.device_put``/``device_get`` in the
  query path must be priced into LinkProfile/route_stats byte accounting
  (``record_h2d``/``record_d2h``/``DEVICE_BYTES_TO_DEVICE``/ the
  ``h2d_bytes``/``d2h_bytes`` route counters) within its enclosing named
  function, or carry a ``# link-priced: <where>`` annotation pointing at
  the accounting.  Lambdas are opaque: a ship inside a lambda needs the
  line annotation.
* ``bench-sync`` (advisory) — a timed region (``t = perf_counter()`` …
  ``… - t``) that launches device work must call ``block_until_ready``
  after the last launch and before the clock stops, or the benchmark
  measures dispatch latency, not execution.
"""

from __future__ import annotations

import ast

from parseable_tpu.analysis.callgraph import build_call_graph
from parseable_tpu.analysis.framework import (
    Finding,
    Project,
    Rule,
    SourceFile,
    attr_chain,
    enclosing_context,
)

from .annotations import STATIC_ATTRS, annotations_for, is_device_module

#: Attribute-chain roots whose call results live on device.
_DEVICE_ROOTS = ("jnp",)
#: Cache variables whose ``.get()`` yields a compiled device program.
_PROGRAM_HINTS = ("program", "cache", "prog")

_PRICING_CALL_TAILS = frozenset({"record_h2d", "record_d2h"})
_PRICING_NAMES = frozenset(
    {"DEVICE_BYTES_TO_DEVICE", "DEVICE_TRANSFER_BYTES", "get_link"}
)
_PRICING_KEYS = frozenset({"h2d_bytes", "d2h_bytes"})


def _is_device_put_get(node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    ch = attr_chain(node.func)
    if ch[-1:] == ["device_put"] or ch == ["jax", "device_get"]:
        return ch[-1]
    return None


# ----------------------------------------------------- host-sync taint pass


def _own_nodes(fn: ast.AST):
    """Nodes of `fn`'s body excluding nested def/class bodies (lambdas are
    transparent — their body executes in this frame's dynamic extent)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _targets(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in node.elts:
            out.extend(_targets(el))
        return out
    if isinstance(node, ast.Starred):
        return _targets(node.value)
    return []


class _DeviceTaint:
    """Which local names hold device arrays / compiled device programs."""

    def __init__(self, fn: ast.AST) -> None:
        self.values: set[str] = set()
        self.callables: set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in _own_nodes(fn):
                value = None
                targets: list[str] = []
                if isinstance(node, ast.Assign):
                    value = node.value
                    for t in node.targets:
                        targets.extend(_targets(t))
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    value = node.value
                    targets.extend(_targets(node.target))
                elif isinstance(node, ast.For):
                    value = node.iter
                    targets.extend(_targets(node.target))
                elif isinstance(node, ast.NamedExpr):
                    value = node.value
                    targets.extend(_targets(node.target))
                if value is None or not targets:
                    continue
                if self._is_device_callable_source(value):
                    fresh = set(targets) - self.callables
                    if fresh:
                        self.callables |= fresh
                        changed = True
                elif self.is_device(value):
                    fresh = set(targets) - self.values
                    if fresh:
                        self.values |= fresh
                        changed = True

    def _is_device_callable_source(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        ch = attr_chain(node.func)
        if ch in (["jax", "jit"], ["jit"]):
            return True
        if ch[-1:] == ["get"] and len(ch) >= 2 and any(
            h in ch[-2].lower() for h in _PROGRAM_HINTS
        ):
            return True
        tail = ch[-1] if ch else ""
        return bool(tail) and "program" in tail.lower()

    def is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.values
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_device(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_device(node.value)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, ast.Call):
            ch = attr_chain(node.func)
            if ch:
                if ch[0] in _DEVICE_ROOTS:
                    return True
                if ch == ["jax", "device_put"]:
                    return True
                if ch[-1] == "trace":
                    return True  # PredicateCompiler.trace -> device mask
                if ch[0] in self.callables and len(ch) == 1:
                    return True
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr not in STATIC_ATTRS:
                # method on a device value returns a device value (x.sum())
                return self.is_device(f.value)
            return False
        return any(self.is_device(c) for c in ast.iter_child_nodes(node))


class HostSyncRule(Rule):
    """Undeclared device->host syncs reachable from hot loops.

    Every sync on the hot path must either go away or become a declared,
    priced boundary (``# sync-boundary: <why>``): the executor's
    ``_timed_readback`` feeds the link profile that adaptive routing and
    transfer budgeting read, so an undeclared ``np.asarray`` is both a
    stall *and* invisible to the cost model.
    """

    name = "host-sync"
    description = "undeclared device->host sync reachable from a # device-hot root"
    rationale = (
        "an implicit sync serializes dispatch against device completion "
        "and bypasses LinkProfile accounting; declared boundaries "
        "(_timed_readback, sampled link probes) are the only allowed syncs"
    )

    def applies(self, rel: str) -> bool:
        return False  # all work happens in finalize (needs the call graph)

    def finalize(self, project: Project):
        graph = build_call_graph(project)
        by_rel = {sf.rel: sf for sf in project.files}

        # roots: innermost functions containing a `# device-hot` line
        roots: list[str] = []
        for key, fi in graph.funcs.items():
            if not is_device_module(fi.rel) or fi.node is None:
                continue
            sf = by_rel.get(fi.rel)
            if sf is None:
                continue
            ann = annotations_for(sf)
            end = getattr(fi.node, "end_lineno", fi.line)
            for hot in ann.device_hot:
                if fi.line <= hot <= end:
                    inner = max(
                        (
                            g
                            for g in graph.funcs.values()
                            if g.rel == fi.rel
                            and g.node is not None
                            and g.line <= hot <= getattr(g.node, "end_lineno", g.line)
                        ),
                        key=lambda g: g.line,
                        default=fi,
                    )
                    if inner.key == key:
                        roots.append(key)
                    break

        reached: dict[str, tuple[str, ...]] = {r: (r,) for r in roots}
        queue = list(roots)
        while queue:
            k = queue.pop(0)
            fi = graph.funcs.get(k)
            if fi is None:
                continue
            for e in sorted(fi.edges, key=lambda e: e.line):
                if e.deferred or e.executor:
                    continue
                if e.callee in graph.funcs and e.callee not in reached:
                    reached[e.callee] = reached[k] + (e.callee,)
                    queue.append(e.callee)

        for key, chain in reached.items():
            fi = graph.funcs[key]
            if not is_device_module(fi.rel) or fi.node is None:
                continue
            sf = by_rel.get(fi.rel)
            if sf is None:
                continue
            ann = annotations_for(sf)
            taint = _DeviceTaint(fi.node)
            via = " -> ".join(
                graph.funcs[k].qualname for k in chain if k in graph.funcs
            )
            for node in _own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                label = self._sync_label(node, taint)
                if label is None:
                    continue
                if ann.sync_boundary_near(node, fi.node):
                    continue
                yield Finding(
                    rule=self.name,
                    path=fi.rel,
                    line=node.lineno,
                    message=(
                        f"undeclared device->host sync ({label}) on the hot "
                        f"path (device-hot root via {via}) — route through a "
                        "priced readback or declare `# sync-boundary: <why>`"
                    ),
                    context=fi.qualname,
                )

    @staticmethod
    def _sync_label(node: ast.Call, taint: _DeviceTaint) -> str | None:
        ch = attr_chain(node.func)
        tail = ch[-1] if ch else (
            node.func.attr if isinstance(node.func, ast.Attribute) else ""
        )
        if tail == "block_until_ready":
            return ".block_until_ready()"
        if tail == "item" and not node.args:
            return ".item()"
        if ch[-1:] in (["asarray"], ["array"]) and len(ch) == 2 and ch[0] in (
            "np",
            "numpy",
        ):
            if node.args and taint.is_device(node.args[0]):
                return f"np.{ch[-1]} on a device array"
            return None
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and taint.is_device(node.args[0])
        ):
            return f"{node.func.id}() on a device array"
        return None


class TransferDisciplineRule(Rule):
    """Unpriced device_put/device_get in the query path.

    Transfers are the resource the link profile exists to model — the
    adaptive router's device-vs-CPU decision is only as good as the byte
    accounting feeding it.  A ship that bypasses ``record_h2d``/route
    counters skews every routing decision after it.
    """

    name = "transfer-discipline"
    description = "device_put/device_get must be priced into link accounting"
    rationale = (
        "unpriced transfers starve the EWMA the adaptive router trusts; "
        "a data-sized ship inside a loop is the expensive variant of the "
        "same bug"
    )

    def applies(self, rel: str) -> bool:
        return rel.startswith(("parseable_tpu/query/", "parseable_tpu/ops/")) and (
            rel.endswith(".py")
        )

    def check(self, sf: SourceFile):
        if sf.tree is None:
            return
        ann = annotations_for(sf)

        sites: list[tuple[ast.Call, str, ast.AST | None, bool, bool]] = []

        def visit(node: ast.AST, fn: ast.AST | None, in_lambda: bool, in_loop: bool):
            for child in ast.iter_child_nodes(node):
                nfn, nlam, nloop = fn, in_lambda, in_loop
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nfn, nlam, nloop = child, False, False
                elif isinstance(child, ast.Lambda):
                    nlam = True
                elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    nloop = True
                kind = _is_device_put_get(child)
                if kind:
                    sites.append((child, kind, nfn, nlam, nloop))
                visit(child, nfn, nlam, nloop)

        visit(sf.tree, None, False, False)

        for call, kind, fn, in_lambda, in_loop in sites:
            if ann.link_priced_near(call, None if in_lambda else fn):
                continue
            if ann.sync_boundary_near(call, None if in_lambda else fn):
                continue
            if fn is not None and not in_lambda and self._priced(fn):
                continue
            where = " inside a lambda" if in_lambda else ""
            loop = " inside a loop" if in_loop else ""
            yield Finding(
                rule=self.name,
                path=sf.rel,
                line=call.lineno,
                message=(
                    f"jax.{kind}{where}{loop} is not priced into LinkProfile/"
                    "route_stats accounting — tick record_h2d/record_d2h or "
                    "the h2d_bytes/d2h_bytes route counters, or annotate "
                    "`# link-priced: <where the bytes are tallied>`"
                ),
                context=enclosing_context(sf.tree, call),
            )

    @staticmethod
    def _priced(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                ch = attr_chain(n.func)
                if ch and (
                    ch[-1] in _PRICING_CALL_TAILS or ch[-1] in _PRICING_NAMES
                ):
                    return True
            elif isinstance(n, ast.Name) and n.id in _PRICING_NAMES:
                return True
            elif isinstance(n, ast.Constant) and n.value in _PRICING_KEYS:
                return True
        return False


class BenchSyncRule(Rule):
    """Advisory: timed device regions must block before the clock stops.

    JAX dispatch is asynchronous — ``fn(x)`` returns before the device
    finishes.  A ``perf_counter()`` pair around device work without a
    ``block_until_ready`` between the last launch and the stop measures
    dispatch latency (microseconds) instead of execution (milliseconds),
    which is exactly the error that makes a bench table lie.
    """

    name = "bench-sync"
    description = "timed device region stops the clock before block_until_ready"
    rationale = (
        "async dispatch makes an unblocked timer read measure launch "
        "overhead, not device execution — the bench number becomes fiction"
    )

    _BENCH_FILES = ("bench.py", "scripts/hw_validate.py")
    _BENCH_PREFIX = "scripts/bench_"

    def applies(self, rel: str) -> bool:
        return False  # advisory-only; work happens in advisories()

    def _bench_file(self, rel: str) -> bool:
        return rel in self._BENCH_FILES or (
            rel.startswith(self._BENCH_PREFIX) and rel.endswith(".py")
        )

    def advisories(self, project: Project):
        for sf in project.files:
            if not self._bench_file(sf.rel) or sf.tree is None:
                continue
            scopes: list[ast.AST] = [sf.tree]
            scopes.extend(
                n
                for n in ast.walk(sf.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
            for scope in scopes:
                yield from self._scan_scope(sf, scope)

    def _scan_scope(self, sf: SourceFile, scope: ast.AST):
        starts: list[tuple[str, int]] = []
        for node in _own_nodes(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and any(
                    isinstance(c, ast.Call)
                    and attr_chain(c.func)[-1:] in (["perf_counter"], ["monotonic"])
                    for c in ast.walk(node.value)
                )
            ):
                starts.append((node.targets[0].id, node.lineno))

        for t_name, start_line in starts:
            stop_line = None
            for node in _own_nodes(scope):
                if (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)
                    and node.right.id == t_name
                    and node.lineno > start_line
                ):
                    if stop_line is None or node.lineno < stop_line:
                        stop_line = node.lineno
            if stop_line is None:
                continue
            device_lines = []
            block_lines = []
            for node in _own_nodes(scope):
                if not isinstance(node, ast.Call):
                    continue
                ch = attr_chain(node.func)
                tail = ch[-1] if ch else (
                    node.func.attr if isinstance(node.func, ast.Attribute) else ""
                )
                if tail == "block_until_ready" and start_line < node.lineno <= stop_line:
                    block_lines.append(node.lineno)
                elif ch and (
                    ch[0] in ("jnp",) or ch[:1] == ["jax"] or tail == "device_put"
                ) and start_line < node.lineno < stop_line:
                    device_lines.append(node.lineno)
            if not device_lines:
                continue
            if block_lines and max(block_lines) >= max(device_lines):
                continue
            yield Finding(
                rule=self.name,
                path=sf.rel,
                line=stop_line,
                message=(
                    f"timed region (clock starts line {start_line}) launches "
                    "device work but stops the clock without a trailing "
                    "block_until_ready — this measures dispatch, not "
                    "execution"
                ),
                context=enclosing_context(sf.tree, scope)
                or getattr(scope, "name", ""),
            )
