"""dlint rules for jit compilation discipline.

Four rules share one per-file index (`_index`): the set of call-time
``jax.jit(...)`` sites, the declared program caches, and the *traced
bodies* — functions whose Python source executes under a JAX trace,
discovered from ``@jax.jit``/``@partial(jax.jit, ...)`` decorators and by
resolving call-time ``jax.jit(name)`` through enclosing-scope local defs
and simple aliases (``body = shard_map(fold, ...)``).

* ``jit-cache-discipline`` — a call-time jit on a query path must carry a
  ``# jit-cache: <family>.<program>`` annotation naming a declared
  module-level cache, and the enclosing function must actually read from
  and store into that cache.  Otherwise every call recompiles.
* ``traced-control-flow`` — Python ``if``/``while``/``assert`` on a traced
  value inside a jit'd body: a silent per-branch recompile at best, a
  ConcretizationTypeError at worst.  Taint starts at the traced params
  (minus static_argnums) and flows through assignments; ``.shape`` and
  friends break taint.
* ``dtype-promotion`` — float64 references inside traced bodies (and
  ``jax_enable_x64`` flips anywhere in the device layer).  The kernels are
  f32; a single f64 leak doubles HBM traffic and recompiles everything.
* ``donation-hazard`` — reading a Python name after it was passed at a
  ``donate_argnums`` position is a use-after-donate error; call-time jit
  *without* donation is an advisory unless a nearby comment documents the
  no-donate rationale (see the tunneled-PJRT note in executor_tpu).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from parseable_tpu.analysis.framework import (
    Finding,
    Project,
    Rule,
    SourceFile,
    attr_chain,
    enclosing_context,
)

from .annotations import STATIC_ATTRS, annotations_for, is_device_module

_JIT_CHAINS = (["jax", "jit"], ["jit"])
_SHARD_MAP_TAILS = ("shard_map",)

#: Calls whose result is static under tracing even with traced arguments.
_STATIC_CALLS = frozenset({"len", "range"})


def _is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and attr_chain(node.func) in _JIT_CHAINS


def _int_positions(node: ast.AST) -> set[int]:
    """Literal int / tuple-of-int positions from a static_argnums value."""
    out: set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
    return out


def _str_names(node: ast.AST) -> set[str]:
    out: set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
    return out


def _static_from_keywords(call: ast.Call) -> tuple[set[int], set[str]]:
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _int_positions(kw.value)
        elif kw.arg == "static_argnames":
            names |= _str_names(kw.value)
    return nums, names


def _param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _static_param_names(
    fn: ast.FunctionDef | ast.AsyncFunctionDef, nums: set[int], names: set[str]
) -> set[str]:
    params = _param_names(fn)
    out = set(names)
    for i in nums:
        if 0 <= i < len(params):
            out.add(params[i])
    return out


def _own_statements(fn: ast.AST):
    """Every node in `fn`'s body, not descending into nested def/class
    bodies (lambdas are transparent)."""
    body = getattr(fn, "body", [])
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class _TracedBody:
    fn: ast.FunctionDef | ast.AsyncFunctionDef
    static_names: set[str]
    origin_line: int
    via: str


@dataclass
class _FileIndex:
    jit_sites: list[tuple[ast.Call, tuple]] = field(default_factory=list)
    module_jit: list[ast.Call] = field(default_factory=list)
    cache_decls: dict[str, tuple[str, int]] = field(default_factory=dict)
    traced: list[_TracedBody] = field(default_factory=list)


def _local_defs(fn: ast.AST) -> dict[str, ast.AST]:
    """Directly visible defs + simple aliases within one scope's own
    statements: ``name = other``, ``name = shard_map(f, ...)``."""
    out: dict[str, ast.AST] = {}
    aliases: dict[str, ast.AST] = {}
    for node in _own_statements(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            aliases[node.targets[0].id] = node.value
    # resolve one-step aliases against the defs we saw
    for name, value in aliases.items():
        target = value
        if isinstance(target, ast.Call) and attr_chain(target.func)[-1:] == list(
            _SHARD_MAP_TAILS
        ):
            target = target.args[0] if target.args else None
        if isinstance(target, ast.Name):
            out.setdefault(name, ast.Name(id=target.id))
        elif isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(name, target)
    return out


def _resolve_callable(name: str, scopes: list[dict]) -> ast.AST | None:
    """Innermost-out resolution of `name` to a def node, following Name
    aliases a bounded number of hops."""
    for _ in range(5):
        found = None
        for scope in reversed(scopes):
            if name in scope:
                found = scope[name]
                break
        if found is None:
            return None
        if isinstance(found, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return found
        if isinstance(found, ast.Name):
            name = found.id
            continue
        return None
    return None


def _index(sf: SourceFile) -> _FileIndex:
    cached = getattr(sf, "_dlint_jit_index", None)
    if cached is not None:
        return cached
    idx = _FileIndex()
    tree = sf.tree
    if tree is None:
        sf._dlint_jit_index = idx
        return idx
    ann = annotations_for(sf)

    # calls appearing inside decorator expressions are not call-time sites
    deco_calls: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for c in ast.walk(dec):
                    if isinstance(c, ast.Call):
                        deco_calls.add(id(c))

    # declared program caches: module-level assigns annotated `# jit-cache: fam`
    for stmt in tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign):
            target = stmt.target
        if isinstance(target, ast.Name):
            fam = ann.jit_cache_at(stmt.lineno, stmt.lineno - 1)
            if fam:
                idx.cache_decls[fam.split(".")[0]] = (target.id, stmt.lineno)

    def visit(node: ast.AST, stack: tuple) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, stack + (child,))
                continue
            if (
                isinstance(child, ast.Call)
                and _is_jit_call(child)
                and id(child) not in deco_calls
            ):
                if stack:
                    idx.jit_sites.append((child, stack))
                else:
                    idx.module_jit.append(child)
            visit(child, stack)

    visit(tree, ())

    # traced bodies from decorators
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            nums: set[int] = set()
            names: set[str] = set()
            traced = False
            if attr_chain(dec) in _JIT_CHAINS:
                traced = True
            elif isinstance(dec, ast.Call):
                ch = attr_chain(dec.func)
                if ch in _JIT_CHAINS:
                    traced = True
                    nums, names = _static_from_keywords(dec)
                elif ch[-1:] == ["partial"] and dec.args and attr_chain(
                    dec.args[0]
                ) in _JIT_CHAINS:
                    traced = True
                    nums, names = _static_from_keywords(dec)
            if traced:
                idx.traced.append(
                    _TracedBody(
                        node,
                        _static_param_names(node, nums, names),
                        node.lineno,
                        f"@jit decorator at line {node.lineno}",
                    )
                )
                break

    # traced bodies from call-time and module-level jit sites
    module_scope = _local_defs(tree)
    for call, stack in [*[(c, ()) for c in idx.module_jit], *idx.jit_sites]:
        if not call.args:
            continue
        arg0 = call.args[0]
        target = arg0
        if isinstance(target, ast.Call) and attr_chain(target.func)[-1:] == list(
            _SHARD_MAP_TAILS
        ):
            target = target.args[0] if target.args else None
        fn = None
        if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = target
        elif isinstance(target, ast.Name):
            scopes = [module_scope] + [_local_defs(s) for s in stack]
            fn = _resolve_callable(target.id, scopes)
        if fn is None:
            continue
        nums, names = _static_from_keywords(call)
        idx.traced.append(
            _TracedBody(
                fn,
                _static_param_names(fn, nums, names),
                call.lineno,
                f"jax.jit at line {call.lineno}",
            )
        )

    sf._dlint_jit_index = idx
    return idx


# ------------------------------------------------------------ taint engine


def _expr_tainted(node: ast.AST, tainted: set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
    ):
        # `x is (not) None` is a host-level structural check: the None-ness
        # of a name is static even when the value it may hold is traced
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, tainted)
    if isinstance(node, ast.Lambda):
        return False
    if isinstance(node, ast.Call):
        ch = attr_chain(node.func)
        if ch and ch[-1] in _STATIC_CALLS:
            return False
        if any(_expr_tainted(a, tainted) for a in node.args):
            return True
        if any(kw.value is not None and _expr_tainted(kw.value, tainted)
               for kw in node.keywords):
            return True
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in STATIC_ATTRS:
                return False
            return _expr_tainted(node.func.value, tainted)
        return False
    return any(_expr_tainted(c, tainted) for c in ast.iter_child_nodes(node))


def _target_names(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in node.elts:
            out.extend(_target_names(el))
        return out
    if isinstance(node, ast.Starred):
        return _target_names(node.value)
    return []


def _flag_traced_body(
    sf: SourceFile,
    body: _TracedBody,
    tainted: set[str],
    out: list[Finding],
    seen: set[tuple],
    visited: set[tuple],
    depth: int = 0,
) -> None:
    key = (id(body.fn), frozenset(tainted))
    if key in visited or depth > 3:
        return
    visited.add(key)

    # fixpoint taint propagation over own statements (loops feed backwards)
    changed = True
    while changed:
        changed = False
        for node in _own_statements(body.fn):
            targets: list[str] = []
            value = None
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    targets.extend(_target_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value = node.value
                targets.extend(_target_names(node.target))
            elif isinstance(node, ast.For):
                value = node.iter
                targets.extend(_target_names(node.target))
            elif isinstance(node, ast.NamedExpr):
                value = node.value
                targets.extend(_target_names(node.target))
            if value is not None and targets and _expr_tainted(value, tainted):
                fresh = set(targets) - tainted
                if fresh:
                    tainted |= fresh
                    changed = True

    for node in _own_statements(body.fn):
        kw = None
        if isinstance(node, ast.If):
            kw = "if"
        elif isinstance(node, ast.While):
            kw = "while"
        elif isinstance(node, ast.Assert):
            kw = "assert"
        if kw is None or not _expr_tainted(node.test, tainted):
            continue
        mark = (node.lineno, kw)
        if mark in seen:
            continue
        seen.add(mark)
        out.append(
            Finding(
                rule="traced-control-flow",
                path=sf.rel,
                line=node.lineno,
                message=(
                    f"Python `{kw}` on a traced value inside jit'd body "
                    f"`{body.fn.name}` ({body.via}) — this concretizes the "
                    "tracer (recompile per branch at best); use jnp.where/"
                    "lax.cond/lax.while_loop or hoist to a static argument"
                ),
                context=enclosing_context(sf.tree, node) or body.fn.name,
            )
        )

    # propagate into directly nested defs: by tainted call-argument position,
    # or wholesale when the def is handed to a combinator (fori_loop, scan…)
    nested = {
        n.name: n
        for n in ast.iter_child_nodes(body.fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for name, fn in nested.items():
        sub = _TracedBody(fn, set(), body.origin_line, body.via)
        params = _param_names(fn)
        closure = {t for t in tainted if t not in params}
        handed_off = False
        for node in _own_statements(body.fn):
            if not isinstance(node, ast.Call):
                continue
            direct = isinstance(node.func, ast.Name) and node.func.id == name
            if direct:
                pos_taint = {
                    params[i]
                    for i, a in enumerate(node.args)
                    if i < len(params) and _expr_tainted(a, tainted)
                }
                if pos_taint or closure:
                    _flag_traced_body(
                        sf, sub, pos_taint | closure, out, seen, visited, depth + 1
                    )
            elif any(
                isinstance(a, ast.Name) and a.id == name for a in node.args
            ):
                handed_off = True
        if handed_off:
            _flag_traced_body(
                sf, sub, set(params) | closure, out, seen, visited, depth + 1
            )


# ------------------------------------------------------------------- rules


class JitCacheDisciplineRule(Rule):
    """Call-time ``jax.jit`` must flow through a declared program cache.

    A ``jax.jit(closure)`` executed per query builds (and on a TPU backend,
    compiles) a fresh program every call — the recompile-per-query failure
    mode the paper's static-plan reference architecture never has.  The
    discipline: annotate the site ``# jit-cache: <family>.<program>``,
    declare the cache at module level (``_CACHE = {}  # jit-cache:
    <family>``), and make the enclosing function read from and store into
    it, keyed by shape/dtype/static-args.  The P_DLINT tripwire then
    attributes every real XLA compile to the declared program name.
    """

    name = "jit-cache-discipline"
    description = "call-time jax.jit must ride a declared, keyed program cache"
    rationale = (
        "an unkeyed call-time jit recompiles per query; the 3 executor "
        "program families exist precisely to amortize tracing+XLA compile "
        "across warm queries"
    )

    def applies(self, rel: str) -> bool:
        return rel.startswith(("parseable_tpu/query/", "parseable_tpu/ops/")) and (
            rel.endswith(".py")
        )

    def check(self, sf: SourceFile):
        idx = _index(sf)
        ann = annotations_for(sf)
        for call, stack in idx.jit_sites:
            fn = stack[-1]
            cache_name = ann.jit_cache_at(
                call.lineno, call.lineno - 1, fn.lineno, fn.lineno - 1
            )
            ctx = enclosing_context(sf.tree, call)
            if cache_name is None:
                yield Finding(
                    rule=self.name,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        "call-time jax.jit() builds a program on every "
                        "invocation — annotate `# jit-cache: "
                        "<family>.<program>` and route it through a keyed "
                        "program cache"
                    ),
                    context=ctx,
                )
                continue
            family = cache_name.split(".")[0]
            decl = idx.cache_decls.get(family)
            if decl is None:
                yield Finding(
                    rule=self.name,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f"`# jit-cache: {cache_name}` names cache family "
                        f"'{family}' but no module-level declaration "
                        f"(`CACHE = {{}}  # jit-cache: {family}`) exists"
                    ),
                    context=ctx,
                )
                continue
            var = decl[0]
            has_lookup = has_store = False
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    ch = attr_chain(n.func)
                    if ch == [var, "get"]:
                        has_lookup = True
                elif isinstance(n, ast.Subscript) and isinstance(
                    n.value, ast.Name
                ) and n.value.id == var:
                    if isinstance(n.ctx, ast.Store):
                        has_store = True
                    else:
                        has_lookup = True
                elif isinstance(n, ast.Compare) and any(
                    isinstance(c, ast.Name) and c.id == var
                    for c in n.comparators
                ):
                    has_lookup = True
            if not (has_lookup and has_store):
                missing = "read from" if not has_lookup else "stored into"
                yield Finding(
                    rule=self.name,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        f"jit'd program '{cache_name}' is never {missing} "
                        f"cache '{var}' in this function — it is rebuilt on "
                        "every call despite the annotation"
                    ),
                    context=ctx,
                )


class TracedControlFlowRule(Rule):
    """Python control flow on traced values inside jit'd bodies.

    ``if``/``while``/``assert`` on a tracer either concretizes (error) or
    burns a recompile per branch taken.  Traced bodies are discovered from
    decorators and from call-time jit sites resolved through local defs and
    ``shard_map`` aliases; static_argnums/static_argnames params are exempt,
    and ``.shape``/``.dtype``-style static reads break the taint.
    """

    name = "traced-control-flow"
    description = "Python if/while/assert on traced values in jit'd bodies"
    rationale = (
        "branching on a tracer is a ConcretizationTypeError at worst and a "
        "silent per-branch recompile at best; lax.cond/jnp.where keep the "
        "program static"
    )

    def applies(self, rel: str) -> bool:
        return is_device_module(rel)

    def check(self, sf: SourceFile):
        idx = _index(sf)
        out: list[Finding] = []
        seen: set[tuple] = set()
        visited: set[tuple] = set()
        for body in idx.traced:
            tainted = set(_param_names(body.fn)) - body.static_names
            _flag_traced_body(sf, body, tainted, out, seen, visited)
        return out


class DtypePromotionRule(Rule):
    """float64 leaking into the f32 device layer.

    The kernels, accumulators, and wire formats are float32 end to end
    (README "dtype discipline"); a float64 reference inside a traced body
    doubles HBM traffic and recompiles every downstream program, and
    ``jax_enable_x64`` flips the default for the whole process.
    """

    name = "dtype-promotion"
    description = "float64 references inside traced bodies / x64 enable flips"
    rationale = (
        "one f64 leak silently promotes the whole lattice: 2x HBM, new "
        "program shapes, and a recompile storm the tripwire would attribute "
        "to every cache family at once"
    )

    def applies(self, rel: str) -> bool:
        return is_device_module(rel)

    def check(self, sf: SourceFile):
        idx = _index(sf)
        seen: set[int] = set()
        for body in idx.traced:
            for node in ast.walk(body.fn):
                hit = None
                if isinstance(node, ast.Attribute) and node.attr == "float64":
                    hit = "float64 reference"
                elif isinstance(node, ast.Constant) and node.value == "float64":
                    hit = 'dtype string "float64"'
                if hit and node.lineno not in seen:
                    seen.add(node.lineno)
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"{hit} inside jit'd body `{body.fn.name}` — the "
                            "device layer is f32; promote on the host after "
                            "readback instead"
                        ),
                        context=enclosing_context(sf.tree, node),
                    )
        if sf.tree is not None:
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.Call)
                    and attr_chain(node.func)[-2:] == ["config", "update"]
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "jax_enable_x64"
                    and not (
                        len(node.args) > 1
                        and isinstance(node.args[1], ast.Constant)
                        and node.args[1].value is False
                    )
                ):
                    yield Finding(
                        rule=self.name,
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            "jax_enable_x64 flipped in the device layer — "
                            "this promotes every weak-typed literal in every "
                            "kernel to f64 process-wide"
                        ),
                        context=enclosing_context(sf.tree, node),
                    )


class DonationHazardRule(Rule):
    """Buffer-donation misuse at call-time jit sites.

    Reading a name after it was passed at a ``donate_argnums`` position is
    a use-after-donate (the buffer is gone).  The inverse — a call-time jit
    with *no* donation — is only an advisory, and only when no nearby
    comment documents why (executor_tpu documents a measured 424ms-vs-10ms
    no-donate rationale for tunneled PJRT backends).
    """

    name = "donation-hazard"
    description = "use-after-donate errors; undocumented missed donation (advisory)"
    rationale = (
        "a donated buffer is deallocated on dispatch: any later host read "
        "is undefined; but donation is also a measured pessimization on "
        "tunneled backends, so absence is advisory-only"
    )

    def applies(self, rel: str) -> bool:
        return rel.startswith(("parseable_tpu/query/", "parseable_tpu/ops/")) and (
            rel.endswith(".py")
        )

    def check(self, sf: SourceFile):
        idx = _index(sf)
        for call, stack in idx.jit_sites:
            donate: set[int] = set()
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    donate |= _int_positions(kw.value)
            if not donate:
                continue
            fn = stack[-1]
            var = None
            for n in ast.walk(fn):
                if (
                    isinstance(n, ast.Assign)
                    and n.value is call
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    var = n.targets[0].id
            if var is None:
                continue
            for n in ast.walk(fn):
                if not (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == var
                ):
                    continue
                for pos in donate:
                    if pos >= len(n.args) or not isinstance(n.args[pos], ast.Name):
                        continue
                    donated = n.args[pos].id
                    reads = sorted(
                        m.lineno
                        for m in ast.walk(fn)
                        if isinstance(m, ast.Name)
                        and m.id == donated
                        and isinstance(m.ctx, ast.Load)
                        and m.lineno > n.lineno
                    )
                    stores = {
                        m.lineno
                        for m in ast.walk(fn)
                        if isinstance(m, ast.Name)
                        and m.id == donated
                        and isinstance(m.ctx, ast.Store)
                    }
                    for read_line in reads:
                        if any(n.lineno < s <= read_line for s in stores):
                            break  # rebound before the read: fine
                        yield Finding(
                            rule=self.name,
                            path=sf.rel,
                            line=read_line,
                            message=(
                                f"`{donated}` was donated to `{var}` at line "
                                f"{n.lineno} (donate_argnums={sorted(donate)}) "
                                "and is read here — the buffer no longer "
                                "exists after dispatch"
                            ),
                            context=enclosing_context(sf.tree, n),
                        )
                        break

    def advisories(self, project: Project):
        for sf in project.files:
            if not self.applies(sf.rel):
                continue
            idx = _index(sf)
            for call, _stack in idx.jit_sites:
                if any(kw.arg == "donate_argnums" for kw in call.keywords):
                    continue
                window = range(call.lineno - 3, call.lineno + 2)
                documented = any(
                    "donate" in sf.comments.get(ln, "").lower() for ln in window
                )
                if documented:
                    continue
                yield Finding(
                    rule=self.name,
                    path=sf.rel,
                    line=call.lineno,
                    message=(
                        "call-time jit without donate_argnums — donation "
                        "saves an accumulator copy when the input dies here; "
                        "document the no-donate rationale in a nearby "
                        "comment if it is deliberate"
                    ),
                    context=enclosing_context(sf.tree, call),
                )
