"""dlint — device-path discipline static analysis for the TPU layer.

plint watches Python concurrency, psan runtime behavior, nsan native
memory, wlint cross-boundary wire contracts.  None of them sees the layer
the paper's TPU-native thesis actually rests on: the hand-rolled JAX
device mapping in ``query/executor_tpu.py`` and ``ops/``, where a
recompile-per-query closure, an implicit device->host sync, or one f64
leak silently eats the MFU the hardware roadmap item needs to prove.  The
reference architecture gets this discipline for free from static plans;
we enforce it with a linter.

Rules (each is one discipline):

- jit-cache-discipline  call-time jax.jit must ride a declared, keyed
                        program cache (``# jit-cache: <family>.<program>``)
- host-sync             undeclared device->host syncs reachable from
                        ``# device-hot`` roots via the call graph
                        (``# sync-boundary: <why>`` declares one)
- traced-control-flow   Python if/while/assert on traced values in jit'd
                        bodies, resolved from jit sites through local defs
- transfer-discipline   device_put/device_get must be priced into
                        LinkProfile/route_stats accounting
                        (``# link-priced: <where>`` points elsewhere)
- dtype-promotion       float64 inside traced bodies; jax_enable_x64 flips
- donation-hazard       use-after-donate errors; undocumented missed
                        donation as advisory
- bench-sync            (advisory) timed device regions must
                        block_until_ready before the clock stops

The dynamic companion is the ``P_DLINT=1`` pytest tripwire
(``parseable_tpu.analysis.device.tripwire``): it hooks ``jax.jit``,
attributes every real XLA compile to its declared program-cache name, and
enforces a compiles-per-shape-class budget over the tier-1 session,
exporting ``tpu_recompiles_total{program}``.

Reuses plint's Finding/fingerprint/baseline machinery verbatim; the
suppression marker is ``# dlint: disable[=rule,...]`` so a plint/wlint
suppression never silences a device finding or vice versa.  Run as
``python -m parseable_tpu.analysis.device``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace
from pathlib import Path

from parseable_tpu.analysis.framework import (
    AnalysisReport,
    Finding,
    Project,
    Rule,
    SourceFile,
    iter_python_files,
    load_baseline,
    write_baseline,
)
from parseable_tpu.analysis.device.rules_jit import (
    DonationHazardRule,
    DtypePromotionRule,
    JitCacheDisciplineRule,
    TracedControlFlowRule,
)
from parseable_tpu.analysis.device.rules_sync import (
    BenchSyncRule,
    HostSyncRule,
    TransferDisciplineRule,
)

DLINT_VERSION = "1"

DEVICE_RULES: list[type[Rule]] = [
    JitCacheDisciplineRule,
    HostSyncRule,
    TracedControlFlowRule,
    TransferDisciplineRule,
    DtypePromotionRule,
    DonationHazardRule,
    BenchSyncRule,
]

# tests/ deliberately touch device arrays (that is what device tests do);
# the discipline applies to shipped code and the bench harnesses.
DEFAULT_PATHS = ["parseable_tpu", "scripts", "bench.py"]

_SUPPRESS_RE = re.compile(r"dlint:\s*disable(?:=([A-Za-z0-9_,-]+))?")


@dataclass
class DeviceReport(AnalysisReport):
    """plint's report shape plus non-gating advisories (bench-sync and
    missed-donation notes): printed as notes, serialized under their own
    key, never part of the exit code."""

    advisories: list[Finding] = field(default_factory=list)

    def to_json(self) -> dict:
        doc = super().to_json()
        doc["advisories"] = [f.to_json() for f in self.advisories]
        return doc


def _dlint_suppressions(sf: SourceFile) -> dict[int, set[str] | None]:
    """SourceFile's own suppression table answers to `plint:` markers;
    device findings answer only to `dlint:` ones, scanned from the same
    comments."""
    out: dict[int, set[str] | None] = {}
    for line, comment in sf.comments.items():
        m = _SUPPRESS_RE.search(comment)
        if m:
            names = m.group(1)
            out[line] = (
                {s.strip() for s in names.split(",") if s.strip()} if names else None
            )
    return out


def run_device_analysis(
    root: Path,
    paths: list[str] | None = None,
    rules: list[Rule] | None = None,
    baseline_path: Path | None = None,
    report_only: set[str] | None = None,
) -> DeviceReport:
    """Analyze `paths` under `root` with the device rules. Same contract as
    framework.run_analysis; differences: analyzer sources are excluded from
    the project outright (the host-sync reachability pass never sees them),
    and suppression/baseline use dlint's own marker and file."""
    root = Path(root)
    rules = rules if rules is not None else [cls() for cls in DEVICE_RULES]
    paths = paths or DEFAULT_PATHS
    project = Project(root=root)
    parse_errors: list[str] = []
    for p in iter_python_files(root, paths):
        rel = p.relative_to(root).as_posix()
        if rel.startswith("parseable_tpu/analysis/"):
            continue  # the analyzer does not lint itself
        try:
            project.files.append(SourceFile.from_path(root, p))
        except (SyntaxError, UnicodeDecodeError) as e:
            parse_errors.append(f"{p}: {e}")

    by_rel = {sf.rel: sf for sf in project.files}
    suppress = {sf.rel: _dlint_suppressions(sf) for sf in project.files}

    def suppressed(f: Finding) -> bool:
        table = suppress.get(f.path)
        if table is None or f.line not in table:
            return False
        names = table[f.line]
        return names is None or f.rule in names

    def finish(f: Finding) -> Finding:
        if f.snippet:
            return f
        src = by_rel.get(f.path)
        return replace(f, snippet=src.snippet(f.line)) if src is not None else f

    findings: list[Finding] = []
    advisories: list[Finding] = []
    for sf in project.files:
        for rule in rules:
            if not rule.applies(sf.rel):
                continue
            for f in rule.check(sf):
                if not suppressed(f):
                    findings.append(finish(f))
    for rule in rules:
        for f in rule.finalize(project):
            if not suppressed(f):
                findings.append(finish(f))
        advise = getattr(rule, "advisories", None)
        if advise is not None:
            for f in advise(project):
                if not suppressed(f):
                    advisories.append(finish(f))

    if report_only is not None:
        findings = [f for f in findings if f.path in report_only]
        advisories = [f for f in advisories if f.path in report_only]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    advisories.sort(key=lambda f: (f.path, f.line, f.rule))
    baseline = load_baseline(baseline_path)
    baselined = [
        f
        for f in findings
        if f.fingerprint in baseline or f.legacy_fingerprint in baseline
    ]
    unbaselined = [
        f
        for f in findings
        if f.fingerprint not in baseline and f.legacy_fingerprint not in baseline
    ]
    return DeviceReport(
        findings=findings,
        baselined=baselined,
        unbaselined=unbaselined,
        files_checked=len(project.files),
        parse_errors=parse_errors,
        advisories=advisories,
    )


__all__ = [
    "DLINT_VERSION",
    "DEVICE_RULES",
    "DEFAULT_PATHS",
    "DeviceReport",
    "run_device_analysis",
    "write_baseline",
]
