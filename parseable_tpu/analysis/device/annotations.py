"""Shared device-path annotation vocabulary for dlint.

The device rules and the P_DLINT tripwire agree on a tiny comment grammar —
the same "declare intent where the code is" pattern plint uses for lock
hierarchies and wlint uses for wire headers:

``# jit-cache: <family>[.<program>]``
    On a module-level dict assignment: declares a memoized program cache
    (family).  On a call-time ``jax.jit(...)`` line (or its enclosing def
    line): declares which cache the built program flows through, and names
    the program for tripwire attribution / the ``tpu_recompiles_total``
    metric label.

``# sync-boundary[: reason]``
    Marks a line (or a whole function, via its def line) as a *declared*
    device->host synchronization point — a priced readback, a sampled link
    probe.  The host-sync rule exempts declared boundaries; everything else
    reachable from a hot loop is a finding.

``# device-hot``
    Marks a loop/function as a device hot path.  These are the roots the
    host-sync rule walks the call graph from; no root, no reachability.

``# link-priced[: reason]``
    Marks a ``device_put``/``device_get`` (or the function owning it) as
    accounted for in LinkProfile/route_stats byte accounting even though
    the pricing calls live elsewhere in the function.

Annotations are read from ``SourceFile.comments`` (tokenize-derived, so
they work on the same line as code).  A line-level annotation may sit on
the flagged line itself or on the line directly above it — multi-line
calls make same-line comments awkward.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from parseable_tpu.analysis.framework import SourceFile

JIT_CACHE_RE = re.compile(r"jit-cache:\s*([A-Za-z_][A-Za-z0-9_.-]*)")
SYNC_BOUNDARY_RE = re.compile(r"sync-boundary\b")
DEVICE_HOT_RE = re.compile(r"device-hot\b")
LINK_PRICED_RE = re.compile(r"link-priced\b")

#: Files that constitute "the device layer" for path-scoped rules.  The
#: analysis package itself is excluded upstream (the analyzer does not lint
#: itself); tests are excluded because tests touch device arrays on purpose.
DEVICE_MODULE_PREFIXES = (
    "parseable_tpu/ops/",
    "parseable_tpu/parallel/",
)
DEVICE_MODULE_FILES = (
    "parseable_tpu/query/executor_tpu.py",
    "parseable_tpu/query/sketch.py",
)

#: Attribute reads that are static under tracing — touching them does NOT
#: propagate device/traced taint (``x.shape[0]`` is a Python int).
STATIC_ATTRS = frozenset(
    {"shape", "ndim", "dtype", "size", "itemsize", "nbytes", "sharding",
     "aval", "weak_type", "at"}
)


def is_device_module(rel: str) -> bool:
    if rel in DEVICE_MODULE_FILES:
        return True
    return rel.startswith(DEVICE_MODULE_PREFIXES) and rel.endswith(".py")


@dataclass
class DeviceAnnotations:
    """Per-file index of dlint annotations, keyed by line number."""

    jit_cache: dict[int, str] = field(default_factory=dict)
    sync_boundary: set[int] = field(default_factory=set)
    device_hot: set[int] = field(default_factory=set)
    link_priced: set[int] = field(default_factory=set)

    def jit_cache_at(self, *lines: int) -> str | None:
        """First jit-cache annotation on any of the given lines."""
        for ln in lines:
            name = self.jit_cache.get(ln)
            if name:
                return name
        return None

    def _near(self, index: set[int], node: ast.AST, fn: ast.AST | None) -> bool:
        lines = {node.lineno, node.lineno - 1}
        if fn is not None and hasattr(fn, "lineno"):
            lines |= {fn.lineno, fn.lineno - 1}
        return bool(lines & index)

    def sync_boundary_near(self, node: ast.AST, fn: ast.AST | None = None) -> bool:
        return self._near(self.sync_boundary, node, fn)

    def link_priced_near(self, node: ast.AST, fn: ast.AST | None = None) -> bool:
        return self._near(self.link_priced, node, fn)


def annotations_for(sf: SourceFile) -> DeviceAnnotations:
    """Extract (and memoize on the SourceFile) this file's annotations."""
    cached = getattr(sf, "_device_annotations", None)
    if cached is not None:
        return cached
    ann = DeviceAnnotations()
    for line, text in sf.comments.items():
        m = JIT_CACHE_RE.search(text)
        if m:
            ann.jit_cache[line] = m.group(1)
        if SYNC_BOUNDARY_RE.search(text):
            ann.sync_boundary.add(line)
        if DEVICE_HOT_RE.search(text):
            ann.device_hot.add(line)
        if LINK_PRICED_RE.search(text):
            ann.link_priced.add(line)
    sf._device_annotations = ann
    return ann
