"""dlint CLI: `python -m parseable_tpu.analysis.device [paths...]`.

Exit codes: 0 = no unbaselined findings, 1 = findings, 2 = usage/parse
error — plint/wlint's contract exactly, so check_green.sh treats the
gates identically. `--json` emits a machine-diffable report (stable
ordering, content fingerprints); `--json-out FILE` writes the same report
as a gate artifact while keeping human-readable output on stdout.
Advisories (bench-sync, missed-donation) print as notes and never affect
the exit code.

No --changed / result cache here: host-sync is a whole-graph reachability
rule (the sync and the hot loop that reaches it are rarely in the same
file), so a changed-files scope would be exactly the blind spot the gate
exists to close, and a full run is already sub-second.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from parseable_tpu.analysis.device import (
    DEFAULT_PATHS,
    DEVICE_RULES,
    run_device_analysis,
    write_baseline,
)

DEFAULT_BASELINE = ".dlint-baseline.json"


def explain(rule_name: str) -> int:
    for cls in DEVICE_RULES:
        if cls.name == rule_name:
            print(f"{cls.name}: {cls.description}")
            print(f"why: {cls.rationale}")
            doc = (cls.__doc__ or "").strip()
            if doc:
                print()
                print(doc)
            print()
            print(f"suppress one line with:  # dlint: disable={cls.name}")
            return 0
    known = ", ".join(cls.name for cls in DEVICE_RULES)
    print(f"unknown rule {rule_name!r}; known rules: {known}", file=sys.stderr)
    return 2


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m parseable_tpu.analysis.device",
        description="dlint: device-path discipline checks (jit caching, "
        "host syncs, traced control flow, transfer pricing, dtype, donation)",
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=None,
        help=f"files/dirs relative to --root (default: {' '.join(DEFAULT_PATHS)})",
    )
    p.add_argument("--root", default=".", help="repository root (default: cwd)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--json-out",
        metavar="FILE",
        default=None,
        help="also write the JSON report to FILE (gate artifact)",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help=f"baseline file relative to --root (default: {DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="acknowledge every current finding into the baseline file",
    )
    p.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="NAME",
        help="run only these rules (repeatable)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    p.add_argument(
        "--explain",
        metavar="RULE",
        default=None,
        help="print one rule's rationale, discipline, and suppression syntax",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for cls in DEVICE_RULES:
            print(f"{cls.name:30s} {cls.description}")
            print(f"{'':30s}   why: {cls.rationale}")
        return 0

    if args.explain:
        return explain(args.explain)

    rules = [cls() for cls in DEVICE_RULES]
    if args.rule:
        known = {r.name for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in set(args.rule)]

    root = Path(args.root).resolve()
    baseline_path = root / args.baseline

    started = time.monotonic()
    report = run_device_analysis(
        root,
        paths=args.paths or None,
        rules=rules,
        baseline_path=baseline_path,
    )

    if args.write_baseline:
        write_baseline(baseline_path, report.findings)
        print(
            f"baseline written: {len(report.findings)} finding(s) -> {baseline_path}"
        )
        return 0

    if report.parse_errors:
        for e in report.parse_errors:
            print(f"parse error: {e}", file=sys.stderr)
        return 2

    doc = report.to_json()
    doc["elapsed_seconds"] = round(time.monotonic() - started, 3)

    if args.json_out:
        Path(args.json_out).write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")

    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        for f in doc["findings"]:
            ctx = f" [{f['context']}]" if f.get("context") else ""
            print(f"{f['path']}:{f['line']}: {f['rule']}{ctx}: {f['message']}")
        for f in doc["advisories"]:
            print(
                f"note: {f['path']}:{f['line']}: {f['rule']}: {f['message']}"
            )
        n_base = len(doc.get("baselined", []))
        base_note = f" ({n_base} baselined)" if n_base else ""
        adv_note = (
            f", {len(doc['advisories'])} advisory(ies)" if doc["advisories"] else ""
        )
        print(
            f"dlint: {len(doc['findings'])} finding(s){base_note}{adv_note} "
            f"across {doc['files_checked']} files"
        )
    return 0 if doc["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
