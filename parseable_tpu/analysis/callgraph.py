"""Whole-program symbol table + call graph for plint's interprocedural rules.

PR 4's rules are lexical: they see one file at a time, so a handler that
calls a helper that calls `storage.list_dirs()` passes `blocking-in-async`,
and nothing at all observes the *order* locks nest across call chains. This
module gives rules_interproc.py the project-wide view:

- `Module`    — dotted name, import alias map, module-level lock objects;
- `ClassInfo` — methods, resolved base classes, and **attribute types**
  (`self.x = ClassName(...)` / annotated ctor params / `self.x: T`), the key
  to resolving `self.metastore.get_stream_json(...)` into a real method;
- `FuncInfo`  — one function/method/nested def, with its outgoing
  `CallEdge`s (direct calls vs. *deferred* references handed to executors),
  its directly-blocking call sites, and its lock acquisition sites;
- `CallGraph` — the index over all of it, plus the interprocedural
  summaries the rules consume (`blocking_reach`, `acquires_closure`,
  `raise_escapes`).

Resolution is deliberately conservative: an edge exists only when the
callee is resolved to a project symbol through names, `self`, annotated
locals/params, or attribute types. Dynamic dispatch we can't see simply
produces no edge — rules built on the graph under-approximate, they never
guess.

Everything here is pure AST walking over `Project.files`; building the
graph for the whole ~20k LoC package takes well under a second, and the
result is memoized per `Project` (see `build_call_graph`).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from parseable_tpu.analysis.framework import Project, SourceFile, attr_chain

# files that are part of the analyzer itself: never modeled (rule sources
# are full of pattern fragments that would pollute the graph)
_SELF_PREFIX = "parseable_tpu/analysis/"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock"}

# callables that move their function argument onto another thread/loop hop:
# a reference passed through these is a *deferred* edge of kind "executor"
_EXECUTOR_RECEIVERS = re.compile(r"pool|executor|workers", re.IGNORECASE)
_EXECUTOR_FUNCS = {"run_in_executor", "_run_traced"}
_THREAD_CTORS = {"Thread", "Timer"}

# blocking primitives (kind tags are stable: rules and tests key on them)
_BLOCKING_STORAGE_OPS = {
    "get_object",
    "put_object",
    "delete_object",
    "head",
    "list_prefix",
    "list_dirs",
    "upload_file",
    "download_file",
    "delete_prefix",
    "get_range",
    "get_objects",
    "exists",
}

_LOCK_ID_RE = re.compile(r"lock-id:\s*([A-Za-z_][A-Za-z0-9_.]*)(\s+reentrant)?")
_LOCK_ORDER_RE = re.compile(
    r"lock-order:\s*([A-Za-z_][A-Za-z0-9_.]*)\s*<\s*([A-Za-z_][A-Za-z0-9_.]*)"
)


def rel_to_module(rel: str) -> str:
    """`parseable_tpu/query/provider.py` -> `parseable_tpu.query.provider`."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


@dataclass
class LockDef:
    """One lock object: a `self.<attr>` of a class or a module global."""

    lock_id: str  # "Class.attr" or "module_tail._NAME" — what messages show
    reentrant: bool
    rel: str
    line: int


@dataclass
class LockSite:
    """One `with <lock>:` acquisition inside a function."""

    lock_id: str
    line: int
    reentrant: bool
    held: tuple[str, ...]  # lock ids lexically held at this acquisition
    same_instance: bool  # receiver is `self.<attr>` (identity-preserving)


@dataclass
class CallEdge:
    callee: str  # FuncInfo key
    line: int
    deferred: bool  # reference handed along, not called here
    executor: bool  # crosses a thread/loop hop (run_in_executor, pool, Thread)
    held: tuple[str, ...]  # lock ids lexically held at the call site
    self_receiver: bool  # call shaped `self.meth(...)` (instance-preserving)


@dataclass
class BlockingSite:
    kind: str  # "time.sleep" | "storage-op" | "parquet-io" | "urlopen" | "future-result"
    line: int
    detail: str  # rendered call, e.g. ".storage.list_dirs()"


@dataclass
class FuncInfo:
    key: str  # "parseable_tpu.core:Parseable.local_sync"
    rel: str
    qualname: str  # "Parseable.local_sync" / "handler.work"
    name: str
    line: int
    is_async: bool
    cls: str | None  # ClassInfo key of the enclosing class, if a method
    node: ast.FunctionDef | ast.AsyncFunctionDef = field(repr=False, default=None)
    edges: list[CallEdge] = field(default_factory=list)
    blocking: list[BlockingSite] = field(default_factory=list)
    locks: list[LockSite] = field(default_factory=list)


@dataclass
class ClassInfo:
    key: str  # "parseable_tpu.core.Parseable"
    rel: str
    name: str
    line: int
    bases: list[str] = field(default_factory=list)  # resolved ClassInfo keys
    methods: dict[str, str] = field(default_factory=dict)  # name -> FuncInfo key
    attr_types: dict[str, str] = field(default_factory=dict)  # attr -> ClassInfo key
    lock_attrs: dict[str, LockDef] = field(default_factory=dict)


@dataclass
class Module:
    rel: str
    dotted: str
    imports: dict[str, str] = field(default_factory=dict)  # local name -> dotted target
    functions: dict[str, str] = field(default_factory=dict)  # top-level name -> key
    classes: dict[str, str] = field(default_factory=dict)  # name -> ClassInfo key
    lock_globals: dict[str, LockDef] = field(default_factory=dict)


class CallGraph:
    """Project-wide function index + call edges + derived summaries."""

    def __init__(self) -> None:
        self.modules: dict[str, Module] = {}  # dotted -> Module
        self.classes: dict[str, ClassInfo] = {}  # key -> ClassInfo
        self.funcs: dict[str, FuncInfo] = {}  # key -> FuncInfo
        # `# lock-order: A < B` declarations: (A, B, rel, line)
        self.declared_order: list[tuple[str, str, str, int]] = []

    # ------------------------------------------------------------- lookups

    def methods_named(self, name: str) -> list[FuncInfo]:
        return [f for f in self.funcs.values() if f.name == name]

    def resolve_method(self, cls_key: str, name: str) -> str | None:
        """Look `name` up on the class, then its project base classes."""
        seen: set[str] = set()
        stack = [cls_key]
        while stack:
            k = stack.pop(0)
            if k in seen:
                continue
            seen.add(k)
            ci = self.classes.get(k)
            if ci is None:
                continue
            if name in ci.methods:
                return ci.methods[name]
            stack.extend(ci.bases)
        return None

    def class_lock(self, cls_key: str, attr: str) -> LockDef | None:
        seen: set[str] = set()
        stack = [cls_key]
        while stack:
            k = stack.pop(0)
            if k in seen:
                continue
            seen.add(k)
            ci = self.classes.get(k)
            if ci is None:
                continue
            if attr in ci.lock_attrs:
                return ci.lock_attrs[attr]
            stack.extend(ci.bases)
        return None

    # ------------------------------------------- interprocedural summaries

    def blocking_reach(self) -> dict[str, tuple[BlockingSite, tuple[str, ...]]]:
        """For every function: the first blocking primitive reachable through
        DIRECT (non-deferred) call edges, with the call chain that reaches it
        (tuple of function keys, excluding the starting function). Fixpoint
        over the graph; deferred/executor edges never propagate blockage —
        that is precisely the `run_in_executor` absolution."""
        reach: dict[str, tuple[BlockingSite, tuple[str, ...]]] = {}
        for key, fn in self.funcs.items():
            if fn.blocking:
                site = min(fn.blocking, key=lambda s: s.line)
                reach[key] = (site, ())
        changed = True
        while changed:
            changed = False
            for key, fn in self.funcs.items():
                best = reach.get(key)
                if best is not None and not best[1]:
                    continue  # already directly blocking: no shorter chain
                for e in sorted(fn.edges, key=lambda e: e.line):
                    if e.deferred or e.executor:
                        continue
                    sub = reach.get(e.callee)
                    if sub is None or e.callee == key:
                        continue
                    chain = (e.callee, *sub[1])
                    if key in chain:
                        continue  # recursion guard
                    if best is None or len(chain) < len(best[1]):
                        best = (sub[0], chain)
                        reach[key] = best
                        changed = True
        return reach

    def acquires_closure(self) -> dict[str, dict[str, tuple[str, ...]]]:
        """For every function: {lock_id -> call chain (possibly empty) that
        acquires it}, through direct non-deferred edges."""
        acq: dict[str, dict[str, tuple[str, ...]]] = {}
        for key, fn in self.funcs.items():
            acq[key] = {s.lock_id: () for s in fn.locks}
        changed = True
        while changed:
            changed = False
            for key, fn in self.funcs.items():
                mine = acq[key]
                for e in fn.edges:
                    if e.deferred or e.executor or e.callee == key:
                        continue
                    for lock, chain in acq.get(e.callee, {}).items():
                        if lock in mine:
                            continue
                        new_chain = (e.callee, *chain)
                        if key in new_chain:
                            continue
                        mine[lock] = new_chain
                        changed = True
        return acq

    def raise_escapes(self) -> dict[str, tuple[int, tuple[str, ...]]]:
        """For every function: (line, chain) of a `raise` that escapes it —
        not enclosed in a broad `except` within the raising function, and
        not absorbed by a broad `except` wrapping the call site on the way
        up. Direct edges only (a deferred callee's raises are the *worker's*
        problem — which is exactly what escaping-exception-in-worker asks)."""
        escapes: dict[str, tuple[int, tuple[str, ...]]] = {}
        for key, fn in self.funcs.items():
            line = _local_escaping_raise(fn.node)
            if line is not None:
                escapes[key] = (line, ())
        guarded = {
            key: _broadly_guarded_call_lines(fn.node) for key, fn in self.funcs.items()
        }
        changed = True
        while changed:
            changed = False
            for key, fn in self.funcs.items():
                if key in escapes and not escapes[key][1]:
                    continue
                for e in sorted(fn.edges, key=lambda e: e.line):
                    if e.deferred or e.executor or e.callee == key:
                        continue
                    sub = escapes.get(e.callee)
                    if sub is None or e.line in guarded[key]:
                        continue
                    chain = (e.callee, *sub[1])
                    if key in chain:
                        continue
                    cur = escapes.get(key)
                    if cur is None or len(chain) < len(cur[1]):
                        escapes[key] = (sub[0], chain)
                        changed = True
        return escapes


# ---------------------------------------------------------------------------
# local AST analyses shared with the builder


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        chain = attr_chain(t)
        if chain and chain[-1] in ("Exception", "BaseException"):
            return True
    return False


def _own_statements(fn: ast.AST):
    """Yield the statements of `fn` (nested def/class statements included)
    WITHOUT descending into their bodies — those are separate graph nodes."""
    stack = list(getattr(fn, "body", []))
    while stack:
        stmt = stack.pop(0)
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                stack.append(child)


def _local_escaping_raise(fn: ast.AST) -> int | None:
    """Line of the first `raise` in fn's own body not covered by a broad
    except of a `try` *in the same function*. A raise inside an except
    handler's body escapes (nothing above it in this try catches it)."""
    if fn is None:
        return None
    hits: list[int] = []

    def walk_stmt(stmt: ast.stmt, protected: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.Raise):
            if not protected:
                hits.append(stmt.lineno)
            return
        if isinstance(stmt, ast.Try):
            broad = any(_is_broad_handler(h) for h in stmt.handlers)
            for b in stmt.body:
                walk_stmt(b, protected or broad)
            for h in stmt.handlers:
                for b in h.body:
                    walk_stmt(b, protected)
            for b in stmt.orelse:
                walk_stmt(b, protected or broad)
            for b in stmt.finalbody:
                walk_stmt(b, protected)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                if isinstance(child, ast.ExceptHandler):
                    for b in child.body:
                        walk_stmt(b, protected)
                else:
                    walk_stmt(child, protected)

    for stmt in fn.body:
        walk_stmt(stmt, False)
    return min(hits) if hits else None


def _broadly_guarded_call_lines(fn: ast.AST) -> set[int]:
    """Lines inside `try:` bodies whose handlers include a broad except —
    calls there cannot let a callee's raise escape this function."""
    out: set[int] = set()
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) and any(
            _is_broad_handler(h) for h in node.handlers
        ):
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if hasattr(sub, "lineno"):
                        out.add(sub.lineno)
    return out


# ---------------------------------------------------------------------------
# builder


class _Builder:
    def __init__(self, project: Project):
        self.project = project
        self.g = CallGraph()

    # ----- pass 1: symbols

    def build(self) -> CallGraph:
        files = [
            sf for sf in self.project.files if not sf.rel.startswith(_SELF_PREFIX)
        ]
        for sf in files:
            self._collect_module(sf)
        for sf in files:
            self._link_classes(sf)
        for sf in files:
            self._collect_attr_types(sf)
        for sf in files:
            self._collect_edges(sf)
        return self.g

    def _collect_module(self, sf: SourceFile) -> None:
        dotted = rel_to_module(sf.rel)
        mod = Module(rel=sf.rel, dotted=dotted)
        self.g.modules[dotted] = mod
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mod.imports[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        mod.imports[head] = head
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg_parts = dotted.split(".")
                    # level 1 inside a module: the containing package
                    pkg_parts = pkg_parts[: len(pkg_parts) - node.level]
                    base = ".".join(pkg_parts + ([node.module] if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
        # module-level defs, classes, lock globals; comment-driven order decls
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(sf, mod, node, qual=node.name, cls=None)
                self._add_nested(sf, mod, node, prefix=node.name, cls=None)
            elif isinstance(node, ast.ClassDef):
                ckey = f"{dotted}.{node.name}"
                ci = ClassInfo(key=ckey, rel=sf.rel, name=node.name, line=node.lineno)
                self.g.classes[ckey] = ci
                mod.classes[node.name] = ckey
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fkey = self._add_func(
                            sf, mod, item, qual=f"{node.name}.{item.name}", cls=ckey
                        )
                        ci.methods[item.name] = fkey
                        self._add_nested(
                            sf, mod, item, prefix=f"{node.name}.{item.name}", cls=ckey
                        )
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                chain = attr_chain(node.value.func)
                if chain and chain[-1] in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tail = dotted.rsplit(".", 1)[-1]
                            mod.lock_globals[t.id] = LockDef(
                                lock_id=f"{tail}.{t.id}",
                                reentrant=chain[-1] in _REENTRANT_CTORS,
                                rel=sf.rel,
                                line=node.lineno,
                            )
        for line, comment in sf.comments.items():
            m = _LOCK_ORDER_RE.search(comment)
            if m:
                self.g.declared_order.append((m.group(1), m.group(2), sf.rel, line))

    def _add_func(self, sf, mod: Module, node, qual: str, cls: str | None) -> str:
        key = f"{mod.dotted}:{qual}"
        self.g.funcs[key] = FuncInfo(
            key=key,
            rel=sf.rel,
            qualname=qual,
            name=node.name,
            line=node.lineno,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            cls=cls,
            node=node,
        )
        if cls is None and "." not in qual:
            mod.functions[node.name] = key
        return key

    def _add_nested(self, sf, mod: Module, fn, prefix: str, cls: str | None) -> None:
        for stmt in _own_statements(fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_func(sf, mod, stmt, qual=f"{prefix}.{stmt.name}", cls=cls)
                self._add_nested(sf, mod, stmt, prefix=f"{prefix}.{stmt.name}", cls=cls)

    # ----- pass 2: base-class links

    def _link_classes(self, sf: SourceFile) -> None:
        mod = self.g.modules[rel_to_module(sf.rel)]
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            ci = self.g.classes[mod.classes[node.name]]
            for b in node.bases:
                ck = self._resolve_class_expr(mod, b)
                if ck is not None:
                    ci.bases.append(ck)

    def _resolve_class_expr(self, mod: Module, expr: ast.expr) -> str | None:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            # string annotation: "ServerState" / "parseable_tpu.core.Parseable"
            name = expr.value.strip().strip("'\"")
            return self._resolve_class_name(mod, name.split("."))
        chain = attr_chain(expr)
        if not chain:
            # Optional[T] / T | None: try the subscript value / left side
            if isinstance(expr, ast.Subscript):
                inner = expr.slice
                if isinstance(inner, ast.Tuple) and inner.elts:
                    inner = inner.elts[0]
                return self._resolve_class_expr(mod, inner)
            if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.BitOr):
                return self._resolve_class_expr(mod, expr.left)
            return None
        return self._resolve_class_name(mod, chain)

    def _resolve_class_name(self, mod: Module, chain: list[str]) -> str | None:
        head, rest = chain[0], chain[1:]
        if not rest:
            if head in mod.classes:
                return mod.classes[head]
            target = mod.imports.get(head)
            if target is not None and target in self.g.classes:
                return target
            return None
        # module.Class (or deeper package path)
        target = mod.imports.get(head)
        if target is None:
            return None
        cand = f"{target}.{'.'.join(rest)}"
        if cand in self.g.classes:
            return cand
        # `from parseable_tpu import storage` then storage.ObjectStorage
        m = self.g.modules.get(target)
        if m is not None and rest[0] in m.classes and len(rest) == 1:
            return m.classes[rest[0]]
        return None

    # ----- pass 3: attribute types + lock attrs

    def _collect_attr_types(self, sf: SourceFile) -> None:
        mod = self.g.modules[rel_to_module(sf.rel)]
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            ci = self.g.classes[mod.classes[node.name]]
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                params = self._param_types(mod, item)
                for stmt in _own_statements(item):
                    tgt = None
                    val = None
                    ann = None
                    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                        tgt, val = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign):
                        tgt, val, ann = stmt.target, stmt.value, stmt.annotation
                    if tgt is None or not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    attr = tgt.attr
                    # lock attribute?
                    if isinstance(val, ast.Call):
                        chain = attr_chain(val.func)
                        if chain and chain[-1] in _LOCK_CTORS:
                            ci.lock_attrs.setdefault(
                                attr,
                                LockDef(
                                    lock_id=f"{ci.name}.{attr}",
                                    reentrant=chain[-1] in _REENTRANT_CTORS,
                                    rel=sf.rel,
                                    line=stmt.lineno,
                                ),
                            )
                            continue
                    ck = None
                    if ann is not None:
                        ck = self._resolve_class_expr(mod, ann)
                    if ck is None and isinstance(val, ast.Call):
                        ck = self._resolve_class_expr(mod, val.func)
                    if ck is None and isinstance(val, ast.Name):
                        ck = params.get(val.id)
                    if ck is not None:
                        ci.attr_types.setdefault(attr, ck)

    def _param_types(self, mod: Module, fn) -> dict[str, str]:
        out: dict[str, str] = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
        for a in args:
            if a.annotation is not None:
                ck = self._resolve_class_expr(mod, a.annotation)
                if ck is not None:
                    out[a.arg] = ck
        return out

    # ----- pass 4: edges, blocking sites, lock sites

    def _collect_edges(self, sf: SourceFile) -> None:
        mod = self.g.modules[rel_to_module(sf.rel)]
        for fn in self.g.funcs.values():
            if fn.rel != sf.rel or fn.node is None:
                continue
            _FuncScanner(self, sf, mod, fn).scan()


class _FuncScanner:
    """Walk one function's own body: local var types, call edges with the
    lexically-held lock set, blocking primitives, lock acquisitions."""

    def __init__(self, b: _Builder, sf: SourceFile, mod: Module, fn: FuncInfo):
        self.b = b
        self.g = b.g
        self.sf = sf
        self.mod = mod
        self.fn = fn
        self.locals: dict[str, str] = b._param_types(mod, fn.node)
        if fn.cls is not None:
            self.locals.setdefault("self", fn.cls)
            self.locals.setdefault("cls", fn.cls)
        # local names of defs nested directly in this function
        self.local_defs: dict[str, str] = {}
        for stmt in _own_statements(fn.node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.local_defs[stmt.name] = f"{mod.dotted}:{fn.qualname}.{stmt.name}"
        # submit-future locals: names assigned from `<pool>.submit(...)`
        self.future_names: set[str] = set()

    # -- type resolution ---------------------------------------------------

    def _resolve_chain_type(self, chain: list[str]) -> str | None:
        """Type (ClassInfo key) of `a.b.c` — resolving the base through
        locals/imports and each attribute through attr_types."""
        if not chain:
            return None
        head, rest = chain[0], chain[1:]
        cur: str | None = self.locals.get(head)
        if cur is None:
            target = self.mod.imports.get(head)
            if target is not None:
                if target in self.g.classes and not rest:
                    return target
                # walk module attributes: module.Class / package.module.Class
                cur_mod = target
                while rest:
                    nxt = f"{cur_mod}.{rest[0]}"
                    if nxt in self.g.classes:
                        cur = nxt
                        rest = rest[1:]
                        break
                    if nxt in self.g.modules:
                        cur_mod = nxt
                        rest = rest[1:]
                        continue
                    return None
                if cur is None:
                    return None
            elif head in self.mod.classes and not rest:
                return self.mod.classes[head]
            else:
                return None
        for attr in rest:
            ci = self.g.classes.get(cur)
            if ci is None:
                return None
            nxt = None
            seen: set[str] = set()
            stack = [cur]
            while stack:
                k = stack.pop(0)
                if k in seen:
                    continue
                seen.add(k)
                c = self.g.classes.get(k)
                if c is None:
                    continue
                if attr in c.attr_types:
                    nxt = c.attr_types[attr]
                    break
                stack.extend(c.bases)
            if nxt is None:
                return None
            cur = nxt
        return cur

    def _resolve_callee(self, func: ast.expr) -> tuple[str | None, bool]:
        """Resolve a call's target to a FuncInfo key. Returns
        (key, self_receiver)."""
        chain = attr_chain(func)
        if not chain:
            return None, False
        if len(chain) == 1:
            name = chain[0]
            if name in self.local_defs:
                return self.local_defs[name], False
            if name in self.mod.functions:
                return self.mod.functions[name], False
            target = self.mod.imports.get(name)
            if target is not None:
                mod_name, _, tail = target.rpartition(".")
                m = self.g.modules.get(mod_name)
                if m is not None and tail in m.functions:
                    return m.functions[tail], False
                if target in self.g.classes:
                    init = self.g.resolve_method(target, "__init__")
                    return init, False
            if name in self.mod.classes:
                return self.g.resolve_method(self.mod.classes[name], "__init__"), False
            return None, False
        *base, meth = chain
        # Class.method / module.func / module.Class(...)
        base_type = self._resolve_chain_type(base)
        if base_type is not None:
            key = self.g.resolve_method(base_type, meth)
            return key, base == ["self"]
        # module function through imports: telemetry.propagate etc.
        target = self.mod.imports.get(base[0])
        if target is not None:
            cur = target
            for part in base[1:]:
                cur = f"{cur}.{part}"
            m = self.g.modules.get(cur)
            if m is not None:
                if meth in m.functions:
                    return m.functions[meth], False
                if meth in m.classes:
                    return self.g.resolve_method(m.classes[meth], "__init__"), False
            if cur in self.g.classes:  # module.Class.method (unbound)
                return self.g.resolve_method(cur, meth), False
        return None, False

    # -- the walk ----------------------------------------------------------

    def scan(self) -> None:
        for stmt in self.fn.node.body:
            self._stmt(stmt, held=())

    def _with_lock(self, item: ast.withitem) -> tuple[LockDef | None, bool]:
        """Resolve one with-item to a lock. Returns (lockdef, same_instance).
        Comment annotation `# lock-id: Name [reentrant]` on the with line
        wins (dynamic acquisitions like `with self.stream_json_lock(n):`)."""
        expr = item.context_expr
        comment = self.sf.comments.get(expr.lineno, "")
        m = _LOCK_ID_RE.search(comment)
        if m:
            return (
                LockDef(
                    lock_id=m.group(1),
                    reentrant=bool(m.group(2)),
                    rel=self.sf.rel,
                    line=expr.lineno,
                ),
                False,
            )
        chain = attr_chain(expr)
        if not chain:
            return None, False
        if len(chain) == 1:
            ld = self.mod.lock_globals.get(chain[0])
            if ld is None:
                target = self.mod.imports.get(chain[0])
                if target is not None:
                    mod_name, _, tail = target.rpartition(".")
                    m2 = self.g.modules.get(mod_name)
                    if m2 is not None:
                        ld = m2.lock_globals.get(tail)
            return ld, ld is not None
        *base, attr = chain
        base_type = self._resolve_chain_type(base)
        if base_type is not None:
            ld = self.g.class_lock(base_type, attr)
            if ld is not None:
                return ld, base == ["self"]
        # module-global through import: `with othermod._LOCK:`
        target = self.mod.imports.get(base[0])
        if target is not None and len(base) == 1:
            m2 = self.g.modules.get(target)
            if m2 is not None:
                ld = m2.lock_globals.get(attr)
                if ld is not None:
                    return ld, True
        return None, False

    def _stmt(self, stmt: ast.stmt, held: tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate node
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self._expr(item.context_expr, held)
                ld, same = self._with_lock(item)
                if ld is not None:
                    self.fn.locks.append(
                        LockSite(
                            lock_id=ld.lock_id,
                            line=item.context_expr.lineno,
                            reentrant=ld.reentrant,
                            held=inner,
                            same_instance=same,
                        )
                    )
                    inner = inner + (ld.lock_id,)
            for s in stmt.body:
                self._stmt(s, inner)
            return
        # local type tracking on plain assignments
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                if isinstance(stmt.value, ast.Call):
                    ck = self.b._resolve_class_expr(self.mod, stmt.value.func)
                    if ck is not None:
                        self.locals[t.id] = ck
                    fchain = attr_chain(stmt.value.func)
                    if fchain and fchain[-1] == "submit":
                        self.future_names.add(t.id)
                elif isinstance(stmt.value, ast.Name):
                    if stmt.value.id in self.locals:
                        self.locals[t.id] = self.locals[stmt.value.id]
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            ck = self.b._resolve_class_expr(self.mod, stmt.annotation)
            if ck is not None:
                self.locals[stmt.target.id] = ck
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, (ast.stmt, ast.ExceptHandler)):
                self._stmt(child, held)
            elif isinstance(child, ast.withitem):  # pragma: no cover - handled above
                self._expr(child.context_expr, held)

    def _expr(self, expr: ast.expr, held: tuple[str, ...]) -> None:
        # walk EVERY node under the expression (comprehension generators and
        # keyword arguments are not ast.expr but contain calls) — only
        # lambdas are skipped: their bodies run in a separate context
        stack: list[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # separate execution context; not modeled as a node
            if isinstance(node, ast.Call):
                self._call(node, held)
            for child in ast.iter_child_nodes(node):
                stack.append(child)

    def _call(self, call: ast.Call, held: tuple[str, ...]) -> None:
        chain = attr_chain(call.func)
        self._record_blocking(call, chain)
        key, self_recv = self._resolve_callee(call.func)
        if key is not None:
            self.fn.edges.append(
                CallEdge(
                    callee=key,
                    line=call.lineno,
                    deferred=False,
                    executor=False,
                    held=held,
                    self_receiver=self_recv,
                )
            )
        # references handed as arguments -> deferred edges
        executor = self._is_executor_call(chain)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            self._ref_edges(arg, call.lineno, held, executor)

    def _is_executor_call(self, chain: list[str]) -> bool:
        if not chain:
            return False
        tail = chain[-1]
        if tail in _EXECUTOR_FUNCS:
            return True
        if tail in _THREAD_CTORS:
            return True
        if tail in ("submit", "map") and len(chain) >= 2:
            recv = chain[-2]
            return bool(_EXECUTOR_RECEIVERS.search(recv)) or recv in (
                "uploader",
                "enrichment",
            )
        return False

    def _ref_edges(
        self, arg: ast.expr, line: int, held: tuple[str, ...], executor: bool
    ) -> None:
        """A bare function reference inside an argument becomes a deferred
        edge (executor=True when the receiving call moves it cross-thread).
        Wrapper calls like telemetry.propagate(fn) are looked through."""
        if isinstance(arg, ast.Call):
            for a in list(arg.args) + [kw.value for kw in arg.keywords]:
                self._ref_edges(a, line, held, executor)
            return
        if isinstance(arg, (ast.Name, ast.Attribute)):
            key, self_recv = self._resolve_callee(arg)
            if key is not None and key in self.g.funcs:
                self.fn.edges.append(
                    CallEdge(
                        callee=key,
                        line=line,
                        deferred=True,
                        executor=executor,
                        held=held,
                        self_receiver=self_recv,
                    )
                )

    def _record_blocking(self, call: ast.Call, chain: list[str]) -> None:
        line = call.lineno
        add = self.fn.blocking.append
        if chain == ["time", "sleep"]:
            add(BlockingSite("time.sleep", line, "time.sleep(...)"))
            return
        if chain:
            tail = chain[-1]
            if (
                len(chain) >= 2
                and "storage" in chain[:-1]
                and tail in _BLOCKING_STORAGE_OPS
            ):
                add(BlockingSite("storage-op", line, f".storage.{tail}()"))
                return
            if chain[0] in ("pq", "parquet") and tail in (
                "read_table",
                "write_table",
                "ParquetFile",
                "read_metadata",
            ):
                add(BlockingSite("parquet-io", line, f"pq.{tail}(...)"))
                return
            if tail == "urlopen":
                add(BlockingSite("urlopen", line, "urllib.request.urlopen(...)"))
                return
            if tail == "result":
                # fut.result() on a known pool future, or chained
                # `<pool>.submit(...).result()`
                recv = call.func.value if isinstance(call.func, ast.Attribute) else None
                if isinstance(recv, ast.Name) and recv.id in self.future_names:
                    add(BlockingSite("future-result", line, f"{recv.id}.result()"))
                elif isinstance(recv, ast.Call):
                    rchain = attr_chain(recv.func)
                    if rchain and rchain[-1] == "submit":
                        add(BlockingSite("future-result", line, ".submit(...).result()"))


def build_call_graph(project: Project) -> CallGraph:
    """Build (or fetch the memoized) whole-program call graph."""
    cached = getattr(project, "_callgraph", None)
    if cached is not None:
        return cached
    g = _Builder(project).build()
    project._callgraph = g
    return g
