"""psan — parseable_tpu's runtime concurrency sanitizer.

The dynamic sibling of plint: where `parseable_tpu.analysis` proves the
annotated concurrency contracts statically (lexically/interprocedurally,
necessarily conservative), psan enforces the *same* contracts under the
real interleavings of a live run — Eraser-style lockset race detection
over `# guarded-by:` attributes, lockdep-style runtime lock-order
enforcement against the declared `# lock-order:` hierarchy with a
deadlock watchdog, an event-loop blocking monitor, and per-test
thread/executor leak accounting.

Activate with `P_PSAN=1` on a pytest run (tests/conftest.py registers the
plugin) or programmatically:

    from parseable_tpu.analysis.psan import contracts, runtime
    rt = runtime.get_runtime()
    rt.enable(root=repo_root, extra_prefixes=("my_fixture_module",))
    cs = contracts.build_contracts(repo_root, ["my_fixture_module.py"])
    contracts.instrument(rt, cs)
    ...  # run the workload
    findings = rt.findings()
    rt.disable()

Findings share plint's fingerprints, `# plint: disable=` suppressions,
and baseline policy (`.psan-baseline.json`, kept empty). See the README
"Dynamic analysis (psan)" section for the detector catalog and knobs.
"""

from parseable_tpu.analysis.psan.contracts import ContractSet, build_contracts, instrument
from parseable_tpu.analysis.psan.report import assemble_report, render_lines, write_report
from parseable_tpu.analysis.psan.runtime import PsanRuntime, get_runtime

__all__ = [
    "ContractSet",
    "PsanRuntime",
    "assemble_report",
    "build_contracts",
    "get_runtime",
    "instrument",
    "render_lines",
    "write_report",
]
