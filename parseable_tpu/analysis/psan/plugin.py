"""psan pytest plugin: every tier-1 run becomes a race/deadlock/leak hunt.

Registered by tests/conftest.py when `P_PSAN=1`:

- `pytest_configure` (historic hook, so late registration still fires it)
  enables the runtime patches *before collection imports any
  parseable_tpu module*, parses the annotation contracts, and installs
  the guarded-attribute hooks.
- each test runs inside a thread/executor snapshot; anything watched that
  survives teardown plus the grace join is a psan-thread-leak.
- `pytest_sessionfinish` assembles the plint-shaped report, writes the
  gate artifact (`P_PSAN_JSON`, default /tmp/psan.json), and turns a
  green exit red when unbaselined findings exist — the same contract as
  the plint gate in scripts/check_green.sh.
"""

from __future__ import annotations

import os
from pathlib import Path

import pytest

import parseable_tpu
from parseable_tpu.analysis.psan.runtime import get_runtime


def _repo_root() -> Path:
    return Path(parseable_tpu.__file__).resolve().parent.parent


class PsanPytestPlugin:
    def __init__(self):
        self.rt = get_runtime()
        self.root = _repo_root()
        self.report: dict | None = None

    # ------------------------------------------------------------ lifecycle

    def pytest_configure(self, config):
        from parseable_tpu.analysis.psan import contracts as _contracts

        self.rt.enable(root=str(self.root))
        cs = _contracts.build_contracts(self.root)
        installed = _contracts.instrument(self.rt, cs)
        config._psan_installed = installed  # introspectable in -q output

    # ------------------------------------------------------------- per test

    @pytest.hookimpl(hookwrapper=True)
    def pytest_runtest_protocol(self, item, nextitem):
        rt = self.rt
        rt.test_context = item.nodeid
        pre_threads = rt.thread_snapshot()
        pre_executors = rt.executor_snapshot()
        yield
        try:
            rt.check_leaks(pre_threads, pre_executors)
        finally:
            rt.test_context = ""

    # ------------------------------------------------------------- wrap-up

    def pytest_sessionfinish(self, session, exitstatus):
        from parseable_tpu.analysis.psan import report as _report
        from parseable_tpu.config import psan_options

        rt = self.rt
        # the gate judges THIS repository: findings in files outside the
        # repo root (absolute paths — e.g. tmp-dir fixture modules from the
        # sanitizer's own seeded-bug tests) are excluded from the verdict
        in_repo = [f for f in rt.findings() if not os.path.isabs(f.path)]
        self.report = _report.assemble_report(in_repo, rt.stats(), self.root)
        out = psan_options()["json_path"] or "/tmp/psan.json"
        try:
            _report.write_report(self.report, out)
        except OSError as e:  # pragma: no cover - artifact is best-effort
            print(f"psan: cannot write report to {out}: {e}")
        if not self.report["clean"] and session.exitstatus == 0:
            session.exitstatus = 1

    def pytest_terminal_summary(self, terminalreporter):
        if self.report is None:
            return
        from parseable_tpu.analysis.psan import report as _report

        terminalreporter.section("psan (runtime concurrency sanitizer)")
        for line in _report.render_lines(self.report):
            terminalreporter.write_line(line)
        if not self.report["clean"]:
            terminalreporter.write_line(
                "psan: RED — fix the findings (or suppress a justified site "
                "with `# plint: disable=<rule>`); the baseline stays empty."
            )
