"""psan reporting: baseline gate + JSON artifact, plint-shaped.

Findings carry plint `Finding` fingerprints, so the baseline file
(`.psan-baseline.json`, same schema as `.plint-baseline.json`) and the
JSON artifact (`/tmp/psan.json` by default, `P_PSAN_JSON` to move it) are
diffable with the same tooling. Policy matches plint: the baseline stays
EMPTY — a finding is either fixed or explicitly `# plint: disable=`-
suppressed at the site with a justification, never parked.
"""

from __future__ import annotations

import json
from pathlib import Path

from parseable_tpu.analysis.framework import Finding, load_baseline

DEFAULT_BASELINE = ".psan-baseline.json"


def assemble_report(
    findings: list[Finding],
    stats: dict,
    root: Path,
    baseline: str = DEFAULT_BASELINE,
) -> dict:
    baseline_fps = load_baseline(Path(root) / baseline)
    baselined = [
        f
        for f in findings
        if f.fingerprint in baseline_fps or f.legacy_fingerprint in baseline_fps
    ]
    unbaselined = [
        f
        for f in findings
        if f.fingerprint not in baseline_fps
        and f.legacy_fingerprint not in baseline_fps
    ]
    return {
        "tool": "psan",
        "stats": stats,
        "baselined": [f.to_json() for f in baselined],
        "findings": [f.to_json() for f in unbaselined],
        "clean": not unbaselined,
    }


def write_report(report: dict, path: str) -> None:
    Path(path).write_text(json.dumps(report, indent=2) + "\n", encoding="utf-8")


def render_lines(report: dict) -> list[str]:
    lines = []
    for f in report["findings"]:
        ctx = f" [{f['context']}]" if f.get("context") else ""
        lines.append(f"{f['path']}:{f['line']}: {f['rule']}{ctx}: {f['message']}")
    stats = report.get("stats", {})
    hits = stats.get("raw_hits", {})
    n_base = len(report.get("baselined", []))
    base_note = f" ({n_base} baselined)" if n_base else ""
    lines.append(
        f"psan: {len(report['findings'])} finding(s){base_note}; raw detector "
        f"hits {hits or '{}'}, {stats.get('suppressed', 0)} suppressed, "
        f"{stats.get('lock_order_edges', 0)} lock-order edges observed"
    )
    return lines
