"""psan contracts: one annotation source shared with plint.

The static checker (analysis/rules.py) and the sanitizer enforce the same
comments:

- ``# guarded-by: <expr>`` on an attribute assignment declares which lock
  protects it. plint checks the lexical `with` discipline; psan installs a
  runtime access hook (`runtime._GuardedAttr`) on the class and applies
  the Eraser lockset algorithm to real interleavings.
- ``# lock-id: Name [reentrant]`` on a lock *creation* line names that
  site's locks in the runtime lock-order graph (plint reads the same tag
  on `with` lines for its static graph). Unannotated `self.<attr> =
  threading.Lock()` sites auto-name as ``Class.attr`` and module-level
  ones as ``module.name`` — the same scheme plint's callgraph uses — so
  declared hierarchies match runtime observations without duplication.
- ``# lock-order: A < B`` comments declare the hierarchy both checkers
  verify: plint on the static acquisition graph, psan on the acquisitions
  that actually happen.

`build_contracts()` parses these from source with plint's `SourceFile`
(same tokenizer comment map, same suppression syntax); `instrument()`
imports the contract modules and installs the runtime hooks.
"""

from __future__ import annotations

import importlib
import logging
import re
from dataclasses import dataclass, field
from pathlib import Path

from parseable_tpu.analysis.framework import (
    SourceFile,
    attr_chain,
    is_self_attr,
    iter_python_files,
)

import ast

logger = logging.getLogger(__name__)

# superset of plint's _GUARDED_BY_RE: capture the full dotted guard
# expression (e.g. `self._lock`, `self._cond`, `sched._cond`)
_GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
_LOCK_ID_RE = re.compile(r"lock-id:\s*([A-Za-z_][A-Za-z0-9_.]*)(\s+reentrant)?")
_LOCK_ORDER_RE = re.compile(
    r"lock-order:\s*([A-Za-z_][A-Za-z0-9_.]*)\s*<\s*([A-Za-z_][A-Za-z0-9_.]*)"
)

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_REENTRANT_CTORS = {"RLock", "Condition"}


@dataclass
class ContractSet:
    """Everything the runtime needs, extracted from annotations."""

    root: Path
    # (dotted module, class name) -> {attr: (guard expr, decl line)}
    guarded: dict[tuple[str, str], dict[str, tuple[str, int]]] = field(
        default_factory=dict
    )
    # (absolute file path, line) -> (lock name, reentrant)
    lock_sites: dict[tuple[str, int], tuple[str, bool]] = field(default_factory=dict)
    # (before, after) -> (rel, line) of the declaration
    declared_order: dict[tuple[str, str], tuple[str, int]] = field(
        default_factory=dict
    )
    files: int = 0
    parse_errors: list[str] = field(default_factory=list)


def _dotted(rel: str) -> str:
    return rel[:-3].replace("/", ".") if rel.endswith(".py") else rel


def _scan_file(cs: ContractSet, sf: SourceFile) -> None:
    modtail = _dotted(sf.rel).rsplit(".", 1)[-1]
    abspath = str((cs.root / sf.rel).resolve())

    def note_lock_site(node: ast.Assign | ast.expr, default_name: str, ctor: str):
        line = node.lineno
        comment = sf.comments.get(line, "")
        m = _LOCK_ID_RE.search(comment)
        if m:
            name, reentrant = m.group(1), bool(m.group(2))
        else:
            name, reentrant = default_name, ctor in _REENTRANT_CTORS
        cs.lock_sites[(abspath, line)] = (name, reentrant)

    def lock_ctor(value: ast.expr) -> str | None:
        if isinstance(value, ast.Call):
            chain = attr_chain(value.func)
            if chain and chain[-1] in _LOCK_CTORS:
                return chain[-1]
        return None

    for node in sf.tree.body:
        # module-level `NAME = threading.Lock()` globals
        if isinstance(node, ast.Assign):
            ctor = lock_ctor(node.value)
            if ctor:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        note_lock_site(node, f"{modtail}.{t.id}", ctor)

    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        dotted = _dotted(sf.rel)
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            ctor = lock_ctor(value) if value is not None else None
            if ctor:
                for t in targets:
                    if is_self_attr(t):
                        note_lock_site(node, f"{cls.name}.{t.attr}", ctor)
                    elif not isinstance(t, ast.Name):
                        # dynamic holders (dicts of locks): name only via an
                        # explicit creation-line `# lock-id:` tag
                        comment = sf.comments.get(node.lineno, "")
                        if _LOCK_ID_RE.search(comment):
                            note_lock_site(node, f"{modtail}:{node.lineno}", ctor)
            comment = sf.comments.get(node.lineno, "")
            m = _GUARDED_BY_RE.search(comment)
            if not m:
                continue
            for t in targets:
                if is_self_attr(t):
                    cs.guarded.setdefault((dotted, cls.name), {})[t.attr] = (
                        m.group(1),
                        node.lineno,
                    )

    # bare function-level lock creations with an explicit lock-id tag
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _LOCK_CTORS:
                key = (abspath, node.lineno)
                if key not in cs.lock_sites:
                    comment = sf.comments.get(node.lineno, "")
                    if _LOCK_ID_RE.search(comment):
                        note_lock_site(node, f"{modtail}:{node.lineno}", chain[-1])

    for line, comment in sf.comments.items():
        m = _LOCK_ORDER_RE.search(comment)
        if m:
            cs.declared_order.setdefault(
                (m.group(1), m.group(2)), (sf.rel, line)
            )


def build_contracts(root: Path, paths: list[str] | None = None) -> ContractSet:
    """Parse the annotation contracts out of `paths` under `root`."""
    root = Path(root).resolve()
    cs = ContractSet(root=root)
    for p in iter_python_files(root, paths or ["parseable_tpu"]):
        try:
            sf = SourceFile.from_path(root, p)
        except (SyntaxError, UnicodeDecodeError) as e:
            cs.parse_errors.append(f"{p}: {e}")
            continue
        cs.files += 1
        _scan_file(cs, sf)
    return cs


def instrument(runtime, contracts: ContractSet) -> int:
    """Feed lock names/hierarchy into the runtime and install the guarded-
    attribute hooks (importing each contract module). Returns the number
    of instrumented attributes."""
    runtime.lock_sites.update(contracts.lock_sites)
    runtime.declared_order.update(contracts.declared_order)
    installed = 0
    for (dotted, clsname), attrs in sorted(contracts.guarded.items()):
        try:
            mod = importlib.import_module(dotted)
        except Exception as e:  # optional deps may be absent in this env
            logger.debug("psan: cannot import contract module %s: %s", dotted, e)
            continue
        cls = getattr(mod, clsname, None)
        if not isinstance(cls, type):
            logger.debug("psan: %s.%s is not a class; skipped", dotted, clsname)
            continue
        decl_path = str(
            (contracts.root / (dotted.replace(".", "/") + ".py")).resolve()
        )
        for attr, (guard, line) in attrs.items():
            runtime.install_guard(cls, attr, guard, decl_path, line)
            installed += 1
    return installed
