"""psan runtime: the instrumentation layer behind the dynamic sanitizer.

plint (analysis/rules*.py) proves the annotated concurrency contracts
*statically*; this module enforces the same contracts *dynamically*, under
the real interleavings of a live test run. One `PsanRuntime` owns four
detectors:

- **psan-race** — Eraser-style lockset race detection. `threading.Lock` /
  `RLock` / `Condition` constructed from watched modules are swapped for
  delegating wrappers that maintain a per-thread lockset; every attribute
  annotated `# guarded-by:` (the same comment plint reads — one contract
  source for both checkers) gets a data descriptor that records each
  read/write together with the accessor's held locks. A variable accessed
  by two threads whose candidate lockset intersects to empty — with at
  least one write after sharing began — is a race, reported with both
  access stacks. Initialization is exempt the way Eraser's state machine
  makes it exempt: a variable owned by one thread (or whose previous
  owners all terminated — join() publication) never reports.

- **psan-lock-order** — runtime lockdep. Each acquisition while other
  instrumented locks are held records an edge in the process-wide
  lock-order graph, keyed by the `# lock-id:` / `Class.attr` names plint
  uses. An edge that contradicts a declared `# lock-order: A < B`, closes
  a cycle, or re-acquires a non-reentrant lock the thread already holds is
  a finding even when no deadlock actually fires.

- **psan-stall** (deadlock watchdog) — an acquisition blocked longer than
  `P_PSAN_WATCHDOG_S` dumps every thread's stack plus its held-lock set to
  the log and records a finding at the blocked call site, then keeps
  waiting (semantics are never changed, only observed).

- **psan-loop-block** — the dynamic sibling of plint's
  transitive-blocking-in-async rule: every asyncio callback is timed, and
  a sampler thread attributes a stall > `P_PSAN_LOOP_MS` to the innermost
  watched frame that was on the loop thread's stack mid-stall (so a
  `time.sleep` inside a handler is pinned to its exact line, not to the
  aiohttp machinery that scheduled it).

- **psan-thread-leak** — `threading.Thread` / `ThreadPoolExecutor`
  construction from watched modules is stamped with its creation site;
  the pytest plugin snapshots live stamped threads and tracked executors
  around each test and flags anything that survives teardown (plus a
  grace join) and is not on the known-daemon allowlist.

Findings reuse plint's `Finding` (same fingerprints), honor the same
`# plint: disable=<rule>` line suppressions, and gate against their own
baseline file (`.psan-baseline.json` — kept empty, like plint's).

Everything is reversible: `disable()` restores the patched factories and
uninstalls the descriptors, so fixture tests can enable a scoped sanitizer
mid-session without leaking instrumentation into the rest of the suite.
"""

from __future__ import annotations

import _thread
import logging
import os
import sys
import threading
import time
import weakref
from dataclasses import dataclass, field

from parseable_tpu.analysis.framework import Finding, SourceFile

logger = logging.getLogger(__name__)

_RAW_LOCK = _thread.allocate_lock  # always the uninstrumented factory

# default allowlist: process-wide daemons that legitimately outlive a test
# (singleton schedulers, device warmers, monitors). Extend via P_PSAN_ALLOW.
DEFAULT_THREAD_ALLOW = (
    "device-warmer",
    "device-probe",
    "resource-monitor",
    "profiler-sampler",
    "qsched-",
    "enccache-writer",
    "cluster",
    "alert-notify",
    "psan-",
)

_PSAN_DIR = os.path.dirname(os.path.abspath(__file__))
# <repo>/tests and <repo>/scripts drive sync product APIs from their own
# async scenarios on purpose; their coroutines are exempt from the
# loop-blocking contract (the product's handlers and coroutines are not)
_REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(_PSAN_DIR)))
_TEST_DIRS = (
    os.path.join(_REPO_DIR, "tests") + os.sep,
    os.path.join(_REPO_DIR, "scripts") + os.sep,
)


def _is_watched_frame(frame, prefixes: tuple[str, ...]) -> bool:
    name = frame.f_globals.get("__name__", "")
    return bool(name) and name.startswith(prefixes)


def _caller_site(skip: int, depth: int = 5) -> list[tuple[str, int, str]]:
    """Cheap partial stack: (filename, lineno, funcname) for up to `depth`
    frames starting `skip` levels above this call, psan frames dropped."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallower stack than skip
        return []
    out: list[tuple[str, int, str]] = []
    while f is not None and len(out) < depth:
        co = f.f_code
        if not co.co_filename.startswith(_PSAN_DIR):
            out.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    return out


def _fmt_site(site: list[tuple[str, int, str]]) -> str:
    if not site:
        return "<unknown>"
    return " <- ".join(f"{os.path.basename(fn)}:{ln}({name})" for fn, ln, name in site)


# --------------------------------------------------------------- thread state


class _TState(threading.local):
    """Per-thread sanitizer state: the ordered multiset of held locks."""

    def __init__(self):
        self.counts: dict[int, int] = {}  # id(wrapper) -> recursion depth
        self.order: list = []  # wrappers, outermost first, unique


# ------------------------------------------------------------- lock wrappers


class _LockSiteInfo:
    __slots__ = ("name", "reentrant", "file", "line")

    def __init__(self, name: str, reentrant: bool, file: str, line: int):
        self.name = name
        self.reentrant = reentrant
        self.file = file
        self.line = line


class PsanLock:
    """Delegating wrapper over a raw lock; tracks held-set + order edges.

    Mirrors the full lock protocol including the private hooks
    `threading.Condition` uses (`_is_owned`, `_release_save`,
    `_acquire_restore`), so a Condition built over a wrapped RLock keeps
    the sanitizer's view of the held-set exact across `wait()`.
    """

    _reentrant = False

    def __init__(self, raw, site: _LockSiteInfo, rt: "PsanRuntime"):
        self._raw = raw
        self.site = site
        self._rt = rt

    # ------------------------------------------------------------- protocol

    def acquire(self, blocking: bool = True, timeout: float = -1):
        rt = self._rt
        if not blocking:
            ok = self._raw.acquire(False)
            if ok:
                rt._note_acquire(self)
            return ok
        rt._pre_acquire(self)
        ok = rt._acquire_with_watchdog(self, timeout)
        if ok:
            rt._note_acquire(self)
        return ok

    def release(self):
        self._raw.release()
        self._rt._note_release(self)

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<PsanLock {self.site.name} over {self._raw!r}>"


class PsanRLock(PsanLock):
    _reentrant = True

    def _is_owned(self):
        return self._raw._is_owned()

    def _release_save(self):
        state = self._raw._release_save()
        depth = self._rt._note_release_all(self)
        return (state, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        self._raw._acquire_restore(state)
        self._rt._note_acquire(self, depth=depth)


# ------------------------------------------------------------ variable state


class _VarState:
    """Eraser state machine for one (object, attribute)."""

    __slots__ = ("oid", "phase", "owner", "cands", "last", "last_held", "threads")

    VIRGIN, EXCLUSIVE, SHARED, MODIFIED, REPORTED = range(5)

    def __init__(self, oid: int):
        self.oid = oid
        self.phase = self.VIRGIN
        self.owner: int | None = None
        self.cands: frozenset[int] | None = None
        self.last: tuple | None = None  # (tid, site, write)
        self.last_held: frozenset[int] = frozenset()
        self.threads: set[int] = set()


# ------------------------------------------------------------------- runtime


@dataclass
class _LoopBusy:
    t0: float
    sampled: list = field(default_factory=list)  # innermost watched frames


class PsanRuntime:
    """Process-wide sanitizer state + the monkeypatch lifecycle."""

    def __init__(self):
        self._state_lock = _RAW_LOCK()  # guards everything cross-thread below
        self.enabled = False
        self.watch_prefixes: tuple[str, ...] = ("parseable_tpu",)
        self.root: str = os.getcwd()
        # knobs (re-read from config at enable())
        self.watchdog_s = 20.0
        self.loop_ms = 50.0
        self.leak_grace_ms = 500.0
        self.max_findings_per_rule = 200
        self.thread_allow: tuple[str, ...] = DEFAULT_THREAD_ALLOW
        # contracts (set by contracts.instrument)
        self.lock_sites: dict[tuple[str, int], tuple[str, bool]] = {}
        self.declared_order: dict[tuple[str, str], tuple[str, int]] = {}
        # detector state
        self._tstate = _TState()
        self._tstates: dict[int, _TState] = {}  # tid -> state (watchdog dumps)
        # thread identity survives OS tid reuse: tid -> generation counter,
        # (tid, gen) -> weakref(Thread). The Eraser join exemption must not
        # mistake a NEW worker that inherited a dead worker's tid for the
        # dead worker still being alive (pthread ids recycle aggressively).
        self._tid_gen: dict[int, int] = {}
        self._gen_thread: dict[tuple[int, int], "weakref.ref"] = {}
        self._edges: dict[tuple[str, str], list] = {}  # (a,b) -> site
        self._adj: dict[str, set[str]] = {}
        self._var_fallback: dict[tuple[int, str], _VarState] = {}
        self._loop_busy: dict[int, _LoopBusy] = {}
        self._executors: "weakref.WeakSet" = weakref.WeakSet()
        self._findings: dict[str, Finding] = {}  # fingerprint -> finding
        self._counts: dict[str, int] = {}  # rule -> raw hit count (pre-dedup)
        self._suppressed = 0
        self._sf_cache: dict[str, SourceFile | None] = {}
        self._stalled: set[int] = set()  # id(lock) currently past watchdog
        self.test_context: str = ""  # current test id (plugin sets it)
        # patch bookkeeping
        self._orig: dict[str, object] = {}
        self._guard_undo: list[tuple[type, str, object, bool]] = []
        self._sampler: threading.Thread | None = None
        self._sampler_stop: threading.Event | None = None

    # ------------------------------------------------------------ lifecycle

    def enable(
        self,
        root: str | None = None,
        extra_prefixes: tuple[str, ...] = (),
    ) -> None:
        """Patch the threading/asyncio seams. Idempotent."""
        if self.enabled:
            return
        from parseable_tpu.config import psan_options

        opts = psan_options()
        self.watchdog_s = max(1.0, opts["watchdog_s"])
        self.loop_ms = max(1.0, opts["loop_ms"])
        self.leak_grace_ms = max(0.0, opts["leak_grace_ms"])
        self.max_findings_per_rule = max(1, opts["max_findings"])
        self.thread_allow = DEFAULT_THREAD_ALLOW + opts["allow"]
        if root:
            self.root = os.path.abspath(root)
        self.watch_prefixes = ("parseable_tpu",) + tuple(extra_prefixes)

        self._patch()
        self._sampler_stop = threading.Event()
        self._sampler = threading.Thread(
            target=self._sample_loop, name="psan-loop-monitor", daemon=True
        )
        self._sampler.start()
        self.enabled = True

    def disable(self) -> None:
        """Restore every patch and uninstall guard descriptors."""
        if not self.enabled:
            return
        self.enabled = False
        if self._sampler_stop is not None:
            self._sampler_stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=2.0)
            self._sampler = None
        self._unpatch()
        for cls, attr, prev, had in self._guard_undo:
            try:
                if had:
                    setattr(cls, attr, prev)
                else:
                    delattr(cls, attr)
            except (AttributeError, TypeError):  # pragma: no cover
                pass
        self._guard_undo.clear()

    def reset_findings(self) -> None:
        with self._state_lock:
            self._findings.clear()
            self._counts.clear()
            self._suppressed = 0

    # -------------------------------------------------------------- patches

    def _patch(self) -> None:
        import asyncio.events
        import concurrent.futures

        rt = self
        self._orig["Lock"] = threading.Lock
        self._orig["RLock"] = threading.RLock
        self._orig["Condition"] = threading.Condition
        self._orig["Thread.__init__"] = threading.Thread.__init__
        self._orig["Executor.__init__"] = (
            concurrent.futures.ThreadPoolExecutor.__init__
        )
        self._orig["Handle._run"] = asyncio.events.Handle._run
        raw_lock, raw_rlock = threading.Lock, threading.RLock
        raw_condition = threading.Condition

        def _site_for_caller(depth: int) -> _LockSiteInfo | None:
            try:
                f = sys._getframe(depth)
            except ValueError:  # pragma: no cover
                return None
            if not _is_watched_frame(f, rt.watch_prefixes):
                return None
            return _LockSiteInfo("", False, f.f_code.co_filename, f.f_lineno)

        def Lock():
            site = _site_for_caller(2)
            if site is None or not rt.enabled:
                return raw_lock()
            rt._name_site(site, reentrant=False)
            return PsanLock(raw_lock(), site, rt)

        def RLock():
            site = _site_for_caller(2)
            if site is None or not rt.enabled:
                return raw_rlock()
            rt._name_site(site, reentrant=True)
            return PsanRLock(raw_rlock(), site, rt)

        def Condition(lock=None):
            if lock is None:
                site = _site_for_caller(2)
                if site is not None and rt.enabled:
                    rt._name_site(site, reentrant=True)
                    lock = PsanRLock(raw_rlock(), site, rt)
            return raw_condition(lock)

        threading.Lock = Lock
        threading.RLock = RLock
        threading.Condition = Condition

        orig_thread_init = self._orig["Thread.__init__"]

        def thread_init(tself, *args, **kwargs):
            orig_thread_init(tself, *args, **kwargs)
            try:
                f = sys._getframe(1)
                if _is_watched_frame(f, rt.watch_prefixes):
                    tself._psan_site = (f.f_code.co_filename, f.f_lineno)
            except ValueError:  # pragma: no cover
                pass

        threading.Thread.__init__ = thread_init

        orig_exec_init = self._orig["Executor.__init__"]

        def exec_init(eself, *args, **kwargs):
            orig_exec_init(eself, *args, **kwargs)
            try:
                f = sys._getframe(1)
                if _is_watched_frame(f, rt.watch_prefixes):
                    eself._psan_site = (f.f_code.co_filename, f.f_lineno)
                    rt._executors.add(eself)
            except (ValueError, TypeError):  # pragma: no cover
                pass

        concurrent.futures.ThreadPoolExecutor.__init__ = exec_init

        orig_handle_run = self._orig["Handle._run"]

        def handle_run(hself):
            if not rt.enabled:
                return orig_handle_run(hself)
            tid = _thread.get_ident()
            busy = _LoopBusy(time.monotonic())
            rt._loop_busy[tid] = busy
            try:
                return orig_handle_run(hself)
            finally:
                rt._loop_busy.pop(tid, None)
                dt_ms = (time.monotonic() - busy.t0) * 1000.0
                if dt_ms > rt.loop_ms:
                    rt._record_loop_block(hself, dt_ms, busy)

        asyncio.events.Handle._run = handle_run

    def _unpatch(self) -> None:
        import asyncio.events
        import concurrent.futures

        threading.Lock = self._orig.pop("Lock")
        threading.RLock = self._orig.pop("RLock")
        threading.Condition = self._orig.pop("Condition")
        threading.Thread.__init__ = self._orig.pop("Thread.__init__")
        concurrent.futures.ThreadPoolExecutor.__init__ = self._orig.pop(
            "Executor.__init__"
        )
        asyncio.events.Handle._run = self._orig.pop("Handle._run")

    # -------------------------------------------------------- lock site names

    def _name_site(self, site: _LockSiteInfo, reentrant: bool) -> None:
        key = (site.file, site.line)
        named = self.lock_sites.get(key)
        if named is not None:
            site.name, site.reentrant = named
        else:
            site.name = f"{self._rel(site.file)}:{site.line}"
            site.reentrant = reentrant

    # ------------------------------------------------------- acquire/release

    def _tid_state(self) -> _TState:
        st = self._tstate
        tid = _thread.get_ident()
        if self._tstates.get(tid) is not st:
            # first touch from this thread (a fresh _TState also means a
            # fresh thread reusing an old tid): bump the generation so the
            # (tid, gen) identity is reuse-proof
            self._tstates[tid] = st  # GIL-atomic; watchdog reads best-effort
            gen = self._tid_gen.get(tid, 0) + 1
            self._tid_gen[tid] = gen
            if len(self._gen_thread) > 8192:  # bounded: prune dead entries
                self._gen_thread = {
                    k: w for k, w in self._gen_thread.items() if w() is not None
                }
            self._gen_thread[(tid, gen)] = weakref.ref(threading.current_thread())
        return st

    def _cur_tkey(self) -> tuple[int, int]:
        tid = _thread.get_ident()
        return (tid, self._tid_gen.get(tid, 0))

    def _tkey_alive(self, key: tuple[int, int]) -> bool:
        wr = self._gen_thread.get(key)
        t = wr() if wr is not None else None
        return t is not None and t.is_alive()

    def held_ids(self) -> frozenset[int]:
        return frozenset(self._tid_state().counts)

    def _pre_acquire(self, lock: PsanLock) -> None:
        """Order/self-deadlock checks before a blocking acquire."""
        st = self._tid_state()
        lid = id(lock)
        if lid in st.counts:
            if not (lock._reentrant or lock.site.reentrant):
                site = _caller_site(3)
                f0 = site[0] if site else (lock.site.file, lock.site.line, "?")
                self._emit(
                    "psan-lock-order",
                    f0[0],
                    f0[1],
                    f"non-reentrant lock {lock.site.name} re-acquired by the "
                    f"thread that already holds it (guaranteed self-deadlock); "
                    f"acquired at {_fmt_site(site)}",
                )
            return
        if not st.order:
            return
        after = lock.site.name
        for held in st.order:
            before = held.site.name
            if before == after:
                continue
            self._note_edge(before, after)

    def _note_edge(self, before: str, after: str) -> None:
        key = (before, after)
        with self._state_lock:
            if key in self._edges:
                return
            site = _caller_site(4)
            self._edges[key] = site
            # declared-order contradiction: someone declared `after < before`
            decl = self.declared_order.get((after, before))
            adj = self._adj.setdefault(before, set())
            cycle = self._find_path(after, before)
            adj.add(after)
        if decl is not None:
            drel, dline = decl
            self._emit(
                "psan-lock-order",
                site[0][0] if site else "",
                site[0][1] if site else 0,
                f"runtime acquisition order {before} -> {after} contradicts "
                f"declared `# lock-order: {after} < {before}` ({drel}:{dline}); "
                f"observed at {_fmt_site(site)}",
            )
        elif cycle:
            path = " -> ".join(cycle + [before])
            self._emit(
                "psan-lock-order",
                site[0][0] if site else "",
                site[0][1] if site else 0,
                f"lock-order cycle closed at runtime (potential deadlock): "
                f"{before} -> {path}; observed at {_fmt_site(site)}",
            )

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        """DFS src -> dst over recorded edges; returns the node path."""
        stack: list[tuple[str, list[str]]] = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _acquire_with_watchdog(self, lock: PsanLock, timeout: float) -> bool:
        raw_acquire = lock._raw.acquire
        deadline = None if timeout is None or timeout < 0 else time.monotonic() + timeout
        waited = 0.0
        stalled = False
        while True:
            step = self.watchdog_s
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                step = min(step, left)
            if raw_acquire(True, step):
                if stalled:
                    self._stalled.discard(id(lock))
                return True
            waited += step
            if not stalled and waited >= self.watchdog_s:
                stalled = True
                self._stalled.add(id(lock))
                self._record_stall(lock, waited)

    def _record_stall(self, lock: PsanLock, waited: float) -> None:
        site = _caller_site(4)
        lines = [
            f"psan-stall: acquisition of {lock.site.name} blocked "
            f"> {waited:.0f}s at {_fmt_site(site)}; all-thread dump:"
        ]
        frames = sys._current_frames()
        for t in threading.enumerate():
            tid = t.ident
            held = []
            st = self._tstates.get(tid)
            if st is not None:
                held = [w.site.name for w in st.order]
            f = frames.get(tid)
            top = []
            depth = 0
            while f is not None and depth < 8:
                top.append(
                    f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
                    f"({f.f_code.co_name})"
                )
                f = f.f_back
                depth += 1
            lines.append(
                f"  thread {t.name} (tid={tid}) holds {held or '[]'}: "
                + " <- ".join(top)
            )
        logger.error("\n".join(lines))
        self._emit(
            "psan-stall",
            site[0][0] if site else lock.site.file,
            site[0][1] if site else lock.site.line,
            f"acquisition of {lock.site.name} blocked > {self.watchdog_s:.1f}s "
            f"(thread dump in log); holder set at stall: see log",
        )

    def _note_acquire(self, lock: PsanLock, depth: int = 1) -> None:
        st = self._tid_state()
        lid = id(lock)
        n = st.counts.get(lid)
        if n is None:
            st.counts[lid] = depth
            st.order.append(lock)
        else:
            st.counts[lid] = n + depth

    def _note_release(self, lock: PsanLock) -> None:
        st = self._tstate
        lid = id(lock)
        n = st.counts.get(lid, 0)
        if n <= 1:
            st.counts.pop(lid, None)
            try:
                st.order.remove(lock)
            except ValueError:  # pragma: no cover - release without acquire
                pass
        else:
            st.counts[lid] = n - 1

    def _note_release_all(self, lock: PsanLock) -> int:
        """Full release for Condition.wait; returns the recursion depth."""
        st = self._tstate
        lid = id(lock)
        depth = st.counts.pop(lid, 0)
        try:
            st.order.remove(lock)
        except ValueError:  # pragma: no cover
            pass
        return max(1, depth)

    # -------------------------------------------------------- guarded access

    def install_guard(
        self,
        cls: type,
        attr: str,
        guard_expr: str,
        decl_path: str,
        decl_line: int,
    ) -> None:
        """Install the access-recording descriptor for one guarded attr."""
        prev = cls.__dict__.get(attr)
        had = attr in cls.__dict__
        if isinstance(prev, _GuardedAttr):  # already instrumented
            return
        desc = _GuardedAttr(self, attr, guard_expr, decl_path, decl_line, prev)
        try:
            setattr(cls, attr, desc)
        except (AttributeError, TypeError):  # pragma: no cover - exotic class
            logger.debug("psan: cannot instrument %s.%s", cls.__name__, attr)
            return
        self._guard_undo.append((cls, attr, prev, had))

    def record_access(
        self,
        obj,
        attr: str,
        guard_expr: str,
        write: bool,
        decl_path: str,
        decl_line: int,
    ) -> None:
        if not self.enabled:
            return
        held = self.held_ids()
        tid = self._cur_tkey()
        site = _caller_site(3)
        store = getattr(obj, "__dict__", None)
        with self._state_lock:
            if store is not None:
                states = store.get("#psan")
                if states is None:
                    states = store["#psan"] = {}
                st = states.get(attr)
                if st is None or st.oid != id(obj):
                    st = states[attr] = _VarState(id(obj))
            else:  # pragma: no cover - __slots__ holder
                key = (id(obj), attr)
                st = self._var_fallback.get(key)
                if st is None:
                    st = self._var_fallback[key] = _VarState(id(obj))
            self._track_var(st, tid, held, write, site, obj, attr, guard_expr,
                            decl_path, decl_line)

    def _track_var(
        self, st: _VarState, tid, held, write, site, obj, attr, guard_expr,
        decl_path, decl_line,
    ) -> None:
        V = _VarState
        if st.phase == V.REPORTED:
            return
        if st.phase == V.VIRGIN:
            st.phase = V.EXCLUSIVE
            st.owner = tid
            st.threads.add(tid)
            st.last = (tid, site, write)
            st.last_held = held
            return
        if st.phase == V.EXCLUSIVE:
            if tid == st.owner:
                st.last = (tid, site, write)
                st.last_held = held
                return
            # second thread: unless the old owner terminated (join/publish
            # happens-before), sharing starts and refinement begins
            if not self._tkey_alive(st.owner):
                st.owner = tid
                st.threads = {tid}
                st.last = (tid, site, write)
                st.last_held = held
                return
            # initialization exemption (Eraser): the owner's construction-
            # phase accesses happen-before publication, so the candidate
            # set starts from THIS access's lockset, not intersected with
            # locks (not) held while the object was still thread-private
            st.cands = held
            st.phase = V.MODIFIED if write else V.SHARED
        else:
            st.cands = (st.cands if st.cands is not None else held) & held
            if write:
                st.phase = V.MODIFIED
        st.threads.add(tid)
        prev = st.last
        st.last = (tid, site, write)
        st.last_held = held
        if st.phase == V.MODIFIED and not st.cands:
            # join exemption: if every OTHER thread that ever touched the
            # variable has terminated, their accesses happen-before this one
            # (join/publication) — re-own instead of reporting, the same
            # reasoning as the exclusive-phase owner-death reset above
            if not any(self._tkey_alive(k) for k in st.threads - {tid}):
                st.phase = V.EXCLUSIVE
                st.owner = tid
                st.threads = {tid}
                st.cands = None
                return
            st.phase = V.REPORTED
            prev_desc = (
                f"thread {prev[0][0]} {'wrote' if prev[2] else 'read'} at "
                f"{_fmt_site(prev[1])}"
                if prev
                else "<unknown>"
            )
            cls = type(obj).__name__
            self._emit(
                "psan-race",
                site[0][0] if site else decl_path,
                site[0][1] if site else decl_line,
                f"data race on {cls}.{attr} (declared `# guarded-by: "
                f"{guard_expr}` at {self._rel(decl_path)}:{decl_line}): "
                f"candidate lockset is empty — thread {tid[0]} "
                f"{'wrote' if write else 'read'} at {_fmt_site(site)}; "
                f"previously {prev_desc}",
                locked=True,
            )

    # ----------------------------------------------------------- loop monitor

    def _sample_loop(self) -> None:
        stop = self._sampler_stop
        interval = max(0.005, self.loop_ms / 2000.0)
        while not stop.wait(interval):
            busy = list(self._loop_busy.items())
            if not busy:
                continue
            now = time.monotonic()
            frames = None
            for tid, entry in busy:
                if (now - entry.t0) * 1000.0 < self.loop_ms:
                    continue
                if frames is None:
                    frames = sys._current_frames()
                f = frames.get(tid)
                hit = None
                while f is not None:
                    # the sanitizer's own instrumentation frames never count
                    # as "the offending handler frame"
                    if not f.f_code.co_filename.startswith(
                        _PSAN_DIR
                    ) and _is_watched_frame(f, self.watch_prefixes):
                        hit = (f.f_code.co_filename, f.f_lineno, f.f_code.co_name)
                        break
                    f = f.f_back
                if hit is not None:
                    entry.sampled.append(hit)

    @staticmethod
    def _callback_code(handle):
        """Code object of the callback a Handle will run: the Task's
        coroutine for `Task.__step`, else the plain function's code."""
        cb = getattr(handle, "_callback", None)
        task = getattr(cb, "__self__", None)
        if task is not None and hasattr(task, "get_coro"):
            coro = task.get_coro()
            return getattr(coro, "cr_code", None) or getattr(coro, "gi_code", None)
        return getattr(cb, "__code__", None) if cb is not None else None

    def _record_loop_block(self, handle, dt_ms: float, busy: _LoopBusy) -> None:
        # Who OWNS the blocked callback? A test/bench/script coroutine that
        # calls sync product APIs on its own loop is that caller's choice,
        # not a server defect — only product coroutines and framework-owned
        # callbacks (aiohttp's RequestHandler running our handlers, asyncio
        # plumbing) are held to the no-blocking contract.
        owner = self._callback_code(handle)
        if owner is not None:
            of = owner.co_filename
            if of.startswith(_TEST_DIRS):
                return
        if busy.sampled:
            fn, line, name = busy.sampled[0]
        else:
            # fall back to the callback's own code object (covers callbacks
            # too fast for the sampler but still over threshold); product
            # code only — attributing a loop stall to test frames would
            # just relitigate the owner check above
            if owner is None or (os.sep + "parseable_tpu" + os.sep) not in owner.co_filename:
                return
            fn, line, name = (
                owner.co_filename,
                owner.co_firstlineno,
                owner.co_name,
            )
        self._emit(
            "psan-loop-block",
            fn,
            line,
            f"event-loop callback blocked the loop for {dt_ms:.0f}ms "
            f"(> {self.loop_ms:.0f}ms) in {name}() — move the blocking work "
            f"to run_in_executor / asyncio.sleep",
        )

    # ----------------------------------------------------------- leak checks

    def thread_snapshot(self) -> set[int]:
        return {
            id(t)
            for t in threading.enumerate()
            if getattr(t, "_psan_site", None) is not None
        }

    def executor_snapshot(self) -> set[int]:
        return {id(e) for e in list(self._executors)}

    def check_leaks(self, pre_threads: set[int], pre_executors: set[int]) -> None:
        """Flag watched threads/executors born during the test that survive
        teardown + grace and are not allowlisted daemons."""
        fresh = [
            t
            for t in threading.enumerate()
            if getattr(t, "_psan_site", None) is not None
            and id(t) not in pre_threads
            and t.is_alive()
        ]
        deadline = time.monotonic() + self.leak_grace_ms / 1000.0
        for t in fresh:
            left = deadline - time.monotonic()
            if left > 0:
                t.join(left)
        for t in fresh:
            if not t.is_alive():
                continue
            if (t.name or "").startswith(self.thread_allow):
                continue
            fn, line = t._psan_site
            self._emit(
                "psan-thread-leak",
                fn,
                line,
                f"thread {t.name!r} created here survived test teardown "
                f"({self.test_context or 'session'}) and is not on the "
                f"known-daemon allowlist — join it or register a stop path",
            )
        for e in list(self._executors):
            if id(e) in pre_executors:
                continue
            if getattr(e, "_shutdown", True):
                continue
            threads = [t for t in getattr(e, "_threads", ()) if t.is_alive()]
            if not threads:
                continue
            prefix = getattr(e, "_thread_name_prefix", "") or ""
            if prefix.startswith(self.thread_allow):
                continue
            fn, line = e._psan_site
            self._emit(
                "psan-thread-leak",
                fn,
                line,
                f"ThreadPoolExecutor (prefix {prefix!r}, {len(threads)} live "
                f"workers) created here was never shut down before test "
                f"teardown ({self.test_context or 'session'})",
            )

    # -------------------------------------------------------------- findings

    def _rel(self, path: str) -> str:
        ap = os.path.abspath(path)
        root = self.root.rstrip(os.sep) + os.sep
        if ap.startswith(root):
            return ap[len(root):].replace(os.sep, "/")
        return ap.replace(os.sep, "/")

    def _source(self, path: str) -> SourceFile | None:
        sf = self._sf_cache.get(path, False)
        if sf is not False:
            return sf
        sf = None
        try:
            with open(path, "r", encoding="utf-8") as fh:
                sf = SourceFile(self._rel(path), fh.read())
        except (OSError, SyntaxError, UnicodeDecodeError, ValueError):
            sf = None
        self._sf_cache[path] = sf
        return sf

    def _emit(
        self, rule: str, path: str, line: int, message: str, locked: bool = False
    ) -> None:
        """Record one finding: suppression-checked, deduped, capped."""
        sf = self._source(path) if path else None
        if sf is not None and sf.is_suppressed(rule, line):
            if locked:
                self._suppressed += 1
            else:
                with self._state_lock:
                    self._suppressed += 1
            return
        if self.test_context:
            message += f" [test: {self.test_context}]"
        f = Finding(
            rule=rule,
            path=self._rel(path) if path else "<runtime>",
            line=line,
            message=message,
            context=self.test_context,
            snippet=sf.snippet(line) if sf is not None else "",
        )
        logger.warning("%s", f.render())

        def _store():
            self._counts[rule] = self._counts.get(rule, 0) + 1
            per_rule = sum(
                1 for x in self._findings.values() if x.rule == rule
            )
            if per_rule < self.max_findings_per_rule:
                self._findings.setdefault(f.fingerprint, f)

        if locked:
            _store()
        else:
            with self._state_lock:
                _store()

    def findings(self) -> list[Finding]:
        with self._state_lock:
            return sorted(
                self._findings.values(), key=lambda f: (f.rule, f.path, f.line)
            )

    def remove_findings(self, fingerprints) -> None:
        """Discard specific findings by fingerprint. For the sanitizer's own
        test suite ONLY: a detector test that deliberately provokes a bug
        in product code removes the finding it just asserted on, so the
        session gate judges the tree, not the test's sabotage."""
        fps = set(fingerprints)
        with self._state_lock:
            for fp in fps:
                self._findings.pop(fp, None)

    def stats(self) -> dict:
        with self._state_lock:
            return {
                "raw_hits": dict(sorted(self._counts.items())),
                "suppressed": self._suppressed,
                "lock_order_edges": len(self._edges),
            }


_RUNTIME: PsanRuntime | None = None


def get_runtime() -> PsanRuntime:
    global _RUNTIME
    if _RUNTIME is None:
        _RUNTIME = PsanRuntime()
    return _RUNTIME


# ------------------------------------------------------------- the descriptor


class _GuardedAttr:
    """Data descriptor recording every access to a `# guarded-by:` attr.

    The value lives in the instance `__dict__` under the attribute's own
    name: a *data* descriptor (defines both __get__ and __set__) wins the
    lookup over the instance dict, so every read/write still routes through
    here — while instances constructed *before* instrumentation (module
    singletons created by the contract import itself) keep working, and
    `vars(obj)` / copy / pickle stay faithful. If the class already had a
    descriptor for the attr (a slot), we delegate to it instead."""

    def __init__(self, rt, attr, guard_expr, decl_path, decl_line, wrapped):
        self.rt = rt
        self.attr = attr
        self.guard = guard_expr
        self.decl_path = decl_path
        self.decl_line = decl_line
        self.wrapped = wrapped if hasattr(wrapped, "__get__") else None
        self.fallback = wrapped
        self.key = attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self.wrapped is not None:
            val = self.wrapped.__get__(obj, objtype)
        else:
            try:
                val = obj.__dict__[self.key]
            except KeyError:
                if self.fallback is not None:
                    return self.fallback
                raise AttributeError(self.attr) from None
        self.rt.record_access(
            obj, self.attr, self.guard, False, self.decl_path, self.decl_line
        )
        return val

    def __set__(self, obj, value):
        if self.wrapped is not None:
            self.wrapped.__set__(obj, value)
        else:
            obj.__dict__[self.key] = value
        self.rt.record_access(
            obj, self.attr, self.guard, True, self.decl_path, self.decl_line
        )

    def __delete__(self, obj):  # pragma: no cover - rare
        if self.wrapped is not None:
            self.wrapped.__delete__(obj)
        else:
            obj.__dict__.pop(self.key, None)
